from .nets import SimpleConvNet, GeeseNet, GeisterNet
from .transformer import TransformerNet
from .inference import (
    InferenceModel,
    RandomModel,
    build_inference_model,
    fetch_outputs,
    init_variables,
)
from .export import ExportedModel, OnnxModel, export_model, export_onnx

__all__ = [
    "SimpleConvNet",
    "GeeseNet",
    "GeisterNet",
    "TransformerNet",
    "InferenceModel",
    "RandomModel",
    "build_inference_model",
    "fetch_outputs",
    "init_variables",
    "ExportedModel",
    "OnnxModel",
    "export_model",
    "export_onnx",
]
