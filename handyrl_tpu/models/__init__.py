from .nets import SimpleConvNet, GeeseNet, GeisterNet
from .inference import InferenceModel, RandomModel, init_variables

__all__ = [
    "SimpleConvNet",
    "GeeseNet",
    "GeisterNet",
    "InferenceModel",
    "RandomModel",
    "init_variables",
]
