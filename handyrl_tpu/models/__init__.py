from .nets import SimpleConvNet, GeeseNet, GeisterNet
from .transformer import TransformerNet
from .inference import InferenceModel, RandomModel, init_variables
from .export import ExportedModel, export_model

__all__ = [
    "SimpleConvNet",
    "GeeseNet",
    "GeisterNet",
    "TransformerNet",
    "InferenceModel",
    "RandomModel",
    "init_variables",
    "ExportedModel",
    "export_model",
]
