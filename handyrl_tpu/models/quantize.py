"""Low-precision fast path: int8 weight + observation quantization.

The game nets are bandwidth-bound (tools/roofline.py: arithmetic
intensity far below the chip's ridge point), so the lever is *fewer
bytes*, not fewer flops.  Two byte streams get an int8 rung here:

* **Weights (serving/fleet/league engines)** — per-channel symmetric
  int8 weight-only quantization (LLM.int8 lineage: fp32 scales, no
  zero-point).  Each quantizable kernel leaf (ndim >= 2, output channel
  on the LAST axis: Conv ``(kh, kw, in, out)``, Dense ``(in, out)``) is
  replaced in place inside ``variables['params']`` by a
  ``{'int8_q', 'int8_scale'}`` pair; biases/norm params stay fp32.  The
  engine holds the int8 tree device-resident and ``jitted_dequant_apply``
  dequantizes INSIDE the compiled program — XLA fuses the
  convert-and-scale into the consuming matmul/conv (dequantize-in-
  matmul), so HBM traffic for weights drops ~4x while the MXU still
  computes in fp32.  Win-rate parity is MEASURED, never assumed: the
  ``lowprec`` bench stage pits quantized vs fp32 through the league's
  ``PayoffMatrix`` ledger (bar |dwp| <= 0.03 over >= 400 games).

* **Observations (wire / shm slots / device rings)** — static per-plane
  scale/zero-point from env metadata (``env.obs_int8_spec()``, default
  scale 1.0 / zero-point 0 — EXACT for the 0/1-occupancy planes that
  dominate the zoo: TicTacToe's 3, HungryGeese's 17, Geister's board +
  scalar are all 0/1-valued fp32).  Quantization happens once at episode
  finalize (runtime/generation.py), so the compressed wire blocks, the
  shm ring slots, and the device rings all carry int8; dequantize runs
  on device at the consumption seams (EpisodeObsView inside the ring
  sample programs, forward_prediction's observation entry) — zero extra
  host syncs, zero recompiles on warm buckets.

Calibration is activation-informed and honest: ``calibration_report``
replays stored episode observations through the fp32 and int8 engines
and reports the measured output deviation — the number is captured, not
derived from a weight-space bound.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map
from .inference import SingleInferenceMixin

# the in-place wrapper marker: a params subtree with EXACTLY these keys
# is one quantized kernel leaf, not a module collection
QUANT_KEYS = frozenset({"int8_q", "int8_scale"})

# symmetric int8: codes -127..127 (the -128 code is unused so the range
# stays symmetric and dequantize needs no zero-point)
_QMAX = 127.0


def is_quantized_leaf(node: Any) -> bool:
    return isinstance(node, dict) and frozenset(node.keys()) == QUANT_KEYS


def _quantizable(leaf: np.ndarray) -> bool:
    """Kernels only: >= 2 dims and floating.  Biases, norm scales and
    other small 1-d leaves stay fp32 — they are a rounding error of the
    byte budget and quantizing them costs accuracy for nothing."""
    return leaf.ndim >= 2 and np.issubdtype(np.asarray(leaf).dtype, np.floating)


def quantize_leaf(w: np.ndarray) -> Dict[str, np.ndarray]:
    """Per-OUT-channel symmetric int8: scale over all-but-last axes.

    Flax kernel layout puts the output channel last (Dense ``(in, out)``,
    Conv ``(kh, kw, in, out)``), so axis=-1 is the per-channel granule.
    """
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    # an all-zero channel gets scale 1.0 (quantizes to zeros exactly);
    # the floor also guards subnormal-scale blowups on tiny channels
    scale = np.where(absmax > 0, absmax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return {"int8_q": q, "int8_scale": scale}


def dequantize_leaf(node: Dict[str, Any], xp=np):
    """Inverse of ``quantize_leaf``; ``xp=jnp`` runs traced inside jit
    (the compiled engines' dequantize-in-matmul path)."""
    q = node["int8_q"]
    scale = node["int8_scale"]
    if xp is np:
        return np.asarray(q, np.float32) * np.asarray(scale, np.float32)
    return q.astype(jnp.float32) * scale.astype(jnp.float32)


def _map_params(tree: Any, on_array, on_quant):
    """Structure-preserving walk that treats ``{'int8_q','int8_scale'}``
    dicts as LEAVES (a plain tree_map would descend into them)."""
    if is_quantized_leaf(tree):
        return on_quant(tree)
    if isinstance(tree, dict) or type(tree).__name__ == "FrozenDict":
        return {k: _map_params(v, on_array, on_quant) for k, v in tree.items()}
    return on_array(tree)


def quantize_params(params: Any) -> Any:
    """fp32 param tree -> tree with quantizable kernels wrapped int8.

    The result is a plain pytree (``jax.device_put`` / ``jit`` see the
    wrapper dicts as ordinary nested containers), so engine code that
    moves ``variables`` between devices needs no changes."""
    return _map_params(
        params,
        lambda leaf: quantize_leaf(leaf) if _quantizable(np.asarray(leaf)) else leaf,
        lambda node: node,  # already quantized: idempotent
    )


def dequantize_params(params: Any, xp=np) -> Any:
    """Quantized (or mixed) param tree -> all-fp32 tree."""
    return _map_params(
        params, lambda leaf: leaf, lambda node: dequantize_leaf(node, xp=xp)
    )


def has_quantized_leaves(params: Any) -> bool:
    found = []
    _map_params(params, lambda leaf: leaf, lambda node: found.append(node))
    return bool(found)


def param_bytes(params: Any) -> int:
    """Resident bytes of a param tree, honoring int8 wrappers — the
    numerator of the bench's weight-bytes-shrink report."""
    total = [0]

    def _arr(leaf):
        total[0] += np.asarray(leaf).nbytes
        return leaf

    def _q(node):
        total[0] += np.asarray(node["int8_q"]).nbytes
        total[0] += np.asarray(node["int8_scale"]).nbytes
        return node

    _map_params(params, _arr, _q)
    return total[0]


@functools.lru_cache(maxsize=None)
def jitted_dequant_apply(module):
    """One compiled dequantizing apply per module *value* (linen modules
    hash by config) — the quantized twin of ``inference.jitted_apply``:
    swapping int8 param trees (hot-swap, league opponents) never
    recompiles, and flipping ``weight_dtype`` compiles each batch bucket
    at most once per dtype (pinned by the RecompileSentinel test)."""

    def _apply(variables, obs, hidden):
        deq = {
            k: (dequantize_params(v, xp=jnp) if k == "params" else v)
            for k, v in variables.items()
        }
        return module.apply(deq, obs, hidden)

    return jax.jit(_apply)


class QuantizedInferenceModel(SingleInferenceMixin):
    """``InferenceModel`` twin holding int8-resident params.

    Exposes the exact engine surface ``ContinuousBatcher`` consumes:
    ``module`` / settable ``variables`` (the batcher device_puts them) /
    ``init_hidden`` / ``inference_batch_async`` / ``inference_batch``.
    The dequantize runs inside the compiled apply, so the resident tree
    stays int8 on device and only the fused matmul/conv sees fp32.
    """

    def __init__(self, module, variables):
        self.module = module
        params = variables.get("params", variables)
        if not has_quantized_leaves(params):
            variables = dict(variables, params=quantize_params(params))
        self.variables = variables

    @property
    def _apply(self):
        return jitted_dequant_apply(self.module)

    def init_hidden(self, batch_dims=()):
        hidden = self.module.initial_state(tuple(batch_dims))
        return None if hidden is None else tree_map(np.asarray, hidden)

    def inference_batch_async(self, obs, hidden=None):
        return self._apply(self.variables, obs, hidden)

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        outputs = self._apply(self.variables, obs, hidden)
        # graftlint: allow[HS001] reason=synchronous convenience entry for calibration/eval callers; the serving hot path uses inference_batch_async and gathers off-thread
        return jax.device_get(outputs)


def calibration_report(module, params, obs_batches: Sequence[Any],
                       hidden=None) -> Dict[str, float]:
    """MEASURED fp32-vs-int8 output deviation over replay observations.

    ``obs_batches``: batched obs pytrees drawn from stored episodes (the
    serving router samples them at publish time; the bench feeds its
    replay store).  Returns max/mean absolute deviation per output head
    family collapsed to scalars — the honest calibration record the
    router logs and the ``lowprec`` bench stage reports, instead of a
    weight-space error bound that says nothing about the policy."""
    from .inference import InferenceModel

    fp32 = InferenceModel(module, {"params": params})
    q = QuantizedInferenceModel(module, {"params": params})
    max_dev, dev_sum, n = 0.0, 0.0, 0
    for obs in obs_batches:
        bdims = (jax.tree.leaves(obs)[0].shape[0],)
        h = hidden if hidden is not None else fp32.init_hidden(bdims)
        out_f = fp32.inference_batch(obs, h)
        out_q = q.inference_batch(obs, h)
        for key, vf in out_f.items():
            if key == "hidden" or vf is None:
                continue
            d = np.abs(np.asarray(vf, np.float32)
                       - np.asarray(out_q[key], np.float32))
            max_dev = max(max_dev, float(d.max()))
            dev_sum += float(d.sum())
            n += d.size
    return {
        "calib_batches": float(len(obs_batches)),
        "calib_max_dev": round(max_dev, 6),
        "calib_mean_dev": round(dev_sum / max(n, 1), 8),
    }


def calibration_batches_from_store(store, n: int) -> List[Any]:
    """Draw up to ``n`` recent episodes' observations from an
    ``EpisodeStore`` as batched obs pytrees — the learner wires this as
    the router's ``calibration_source`` so publish-time calibration runs
    against REAL replay data, not synthetic templates.  Stored int8 obs
    (the ``obs_int8`` wire plane) are host-dequantized under the spec the
    episode carries before being replayed through both engines."""
    from ..runtime.replay import decompress_block

    if n <= 0:
        return []
    batches: List[Any] = []
    for ep in store.snapshot()[-int(n):]:
        obs = decompress_block(ep["blocks"][0])["obs"]   # (t, P, ...) leaves
        if obs_tree_is_int8(obs):
            spec = None
            if ep.get("obs_scale") is not None:
                spec = list(zip(
                    np.asarray(ep["obs_scale"], np.float32).tolist(),
                    np.asarray(ep["obs_zero"], np.float32).tolist(),
                ))
            obs = dequantize_obs_tree(obs, spec)  # numpy in -> numpy out
        batches.append(tree_map(
            lambda x: np.asarray(x).reshape((-1,) + np.asarray(x).shape[2:]),
            obs,
        ))
    return batches


# -- observation int8 plane ---------------------------------------------------


def obs_quant_spec(env, obs=None) -> List[Tuple[float, float]]:
    """Per-leaf (scale, zero_point) for an env's observation pytree,
    aligned with ``jax.tree.flatten`` order.

    Envs with non-0/1 planes override via an ``obs_int8_spec()`` method;
    the default (1.0, 0) is EXACT for 0/1-occupancy planes and keeps the
    fp32 padding convention intact (quantized 0 dequantizes to 0.0 —
    required because make_batch/reset_out fill padding regions with
    zeros before the dequantize sees them)."""
    hook = getattr(env, "obs_int8_spec", None)
    if hook is not None:
        spec = [(float(s), float(z)) for s, z in hook()]
    else:
        if obs is None:
            env.reset()
            obs = env.observation(env.players()[0])
        spec = [(1.0, 0.0) for _ in jax.tree.leaves(obs)]
    for scale, zp in spec:
        if scale <= 0:
            raise ValueError(f"obs_int8 scale must be > 0, got {scale}")
    return spec


def quantize_obs_tree(tree: Any, spec: Optional[Sequence[Tuple[float, float]]] = None):
    """Host-side (numpy) obs quantize at episode finalize: the wire
    blocks, shm slots, and device rings all inherit the int8 leaves."""
    leaves, treedef = jax.tree.flatten(tree)
    if spec is None:
        spec = [(1.0, 0.0)] * len(leaves)
    out = []
    for leaf, (scale, zp) in zip(leaves, spec):
        x = np.asarray(leaf)
        if np.issubdtype(x.dtype, np.floating):
            q = np.clip(np.rint(x / scale) + zp, -128, 127).astype(np.int8)
            out.append(q)
        else:
            out.append(x)
    return jax.tree.unflatten(treedef, out)


def dequantize_obs_tree(tree: Any, spec: Optional[Sequence[Tuple[float, float]]] = None):
    """Device-side (traced) obs dequantize — runs INSIDE the jitted ring
    sample programs and the train step's forward, so int8 planes stream
    H2D/HBM and widen to fp32 only in registers.  Non-int8 leaves pass
    through untouched, making the call a no-op on fp32 batches."""
    leaves, treedef = jax.tree.flatten(tree)
    if spec is None:
        spec = [(1.0, 0.0)] * len(leaves)
    out = []
    for leaf, (scale, zp) in zip(leaves, spec):
        if leaf.dtype == jnp.int8:
            x = leaf.astype(jnp.float32)
            if zp:
                x = x - jnp.float32(zp)
            if scale != 1.0:
                x = x * jnp.float32(scale)
            out.append(x)
        else:
            out.append(leaf)
    return jax.tree.unflatten(treedef, out)


def obs_tree_is_int8(tree: Any) -> bool:
    return any(
        np.asarray(leaf).dtype == np.int8 for leaf in jax.tree.leaves(tree)
    )
