"""Game-specific policy/value networks (Flax).

Every net shares one calling convention:

    outputs = module.apply(variables, obs, hidden, train=False)

* ``obs`` — the environment's observation pytree with a leading batch dim
  (CHW feature planes, as emitted by envs; nets convert to NHWC).
* ``hidden`` — recurrent state pytree or None.
* returns a dict with 'policy' (action logits), 'value' in [-1, 1],
  optionally 'return' (reward-sum head) and 'hidden' (next state).

Recurrent nets also expose ``initial_state(batch_dims)`` which needs no
params (pure zeros), so hosts can allocate hidden state cheaply.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

from .layers import ConvBlock, DenseHead, DRC, ScalarHead, SpatialHead, chw_to_nhwc


class SimpleConvNet(nn.Module):
    """TicTacToe net: conv stem + 3 normed conv blocks + policy/value heads.

    Capability parity with reference SimpleConv2dModel
    (envs/tictactoe.py:52-69); norm is GroupNorm (see layers.py).
    """

    filters: int = 32
    blocks: int = 3
    num_actions: int = 9

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False):
        h = chw_to_nhwc(obs)
        h = nn.relu(nn.Conv(self.filters, (3, 3), padding="SAME")(h))
        for _ in range(self.blocks):
            h = nn.relu(ConvBlock(self.filters)(h))
        policy = DenseHead(2, self.num_actions)(h)
        value = jnp.tanh(DenseHead(1, 1)(h))
        return {"policy": policy, "value": value}

    @nn.nowrap
    def initial_state(self, batch_dims: Sequence[int] = ()):
        return None


class GeeseNet(nn.Module):
    """HungryGeese net: torus-conv residual tower, head-cell + mean pooling.

    Capability parity with reference GeeseNet
    (envs/kaggle/hungry_geese.py:38-57): policy reads features at the own
    head cell (obs channel 0), value reads head + board-average features.
    Circular padding is native (layers.ConvBlock(circular=True)).
    """

    filters: int = 32
    blocks: int = 12
    num_actions: int = 4

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False):
        x = chw_to_nhwc(obs)  # (B, 7, 11, 17)
        h = nn.relu(ConvBlock(self.filters, circular=True)(x))
        for _ in range(self.blocks):
            h = nn.relu(h + ConvBlock(self.filters, circular=True)(h))
        head_mask = x[..., :1]  # own head plane
        h_head = (h * head_mask).sum(axis=(-3, -2))
        h_avg = h.mean(axis=(-3, -2))
        # Zero-init output heads: the residual tower's std grows ~sqrt(depth),
        # so a variance-preserving head init yields logit std ~3-4 — a
        # near-deterministic random policy (measured entropy 0.004-0.72 of
        # ln4 at init) that kills self-play exploration.  Zero kernels give
        # the uniform policy / zero value RL training assumes at step 0.
        policy = nn.Dense(
            self.num_actions, use_bias=False, kernel_init=nn.initializers.zeros_init()
        )(h_head)
        value = jnp.tanh(
            nn.Dense(1, use_bias=False, kernel_init=nn.initializers.zeros_init())(
                jnp.concatenate([h_head, h_avg], axis=-1)
            )
        )
        return {"policy": policy, "value": value}

    @nn.nowrap
    def initial_state(self, batch_dims: Sequence[int] = ()):
        return None


class GeisterNet(nn.Module):
    """Geister net: conv stem + DRC ConvLSTM core + move/set policy,
    value and return heads.

    Capability parity with reference GeisterNet (envs/geister.py:130-166):
    scalar features are broadcast to board planes and concatenated; the
    'set' policy (70 layout logits) is a linear map of the turn-color bit;
    outputs 144 move logits ++ 70 set logits.
    """

    filters: int = 32
    drc_layers: int = 3
    drc_repeats: int = 3
    board_size: int = 6

    def _drc(self):
        return DRC(self.drc_layers, self.filters, self.drc_repeats, name="drc")

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False):
        board = chw_to_nhwc(obs["board"])        # (B, 6, 6, 7)
        scalar = obs["scalar"]                   # (B, 18)
        s_planes = jnp.broadcast_to(
            scalar[..., None, None, :],
            (*scalar.shape[:-1], self.board_size, self.board_size, scalar.shape[-1]),
        )
        h = jnp.concatenate([s_planes, board], axis=-1)
        h = nn.relu(ConvBlock(self.filters)(h))

        if hidden is None:
            hidden = self.initial_state(board.shape[:-3])
        h, new_hidden = self._drc()(h, hidden)

        p_move = SpatialHead(8, 4)(h)                       # 4 * 36 = 144 logits
        turn_color = scalar[..., 0:1]
        p_set = nn.Dense(70)(turn_color)                    # layout logits
        policy = jnp.concatenate([p_move, p_set], axis=-1)
        value = jnp.tanh(ScalarHead(2, 1)(h))
        ret = ScalarHead(2, 1, name="return_head")(h)
        return {"policy": policy, "value": value, "return": ret, "hidden": new_hidden}

    @nn.nowrap
    def initial_state(self, batch_dims: Sequence[int] = ()):
        shape = (*batch_dims, self.drc_layers, self.board_size, self.board_size, self.filters)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
