"""Transformer policy/value nets with a KV-cache ring buffer as hidden state.

A model family beyond the reference's convnets/ConvLSTMs (SURVEY.md §2.2):
episode memory is a fixed-size per-layer key/value cache instead of an
RNN carry, so context is attention over the last ``memory_len`` steps.
The cache IS the hidden-state pytree, which makes the family drop-in
compatible with every existing path:

* acting — ``initial_state``/``apply(obs, hidden)`` step semantics, so the
  batched inference engine and agents work unchanged;
* training — the lax.scan hidden-carry path (parallel/train_step.py)
  trains it exactly like an RNN, burn-in included;
* export — the cache rides as the ``hidden0`` pytree of StableHLO
  artifacts (models/export.py).

Positions use ALiBi-style additive age biases (slope per head), so ring
wraparound needs no positional-embedding bookkeeping.  The cache write is
a one-hot blend — O(memory_len) per step, branch-free, XLA-friendly.

The sequence-parallel training path for very long windows is the ops
layer's ring attention (ops/ring_attention.py); this module is the
step-wise consumer of the same attention math.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

NEG_INF = -1e30


def _alibi_slopes(n_heads: int) -> jnp.ndarray:
    """Geometric head slopes as in ALiBi: 2^(-8i/n)."""
    return jnp.asarray([2.0 ** (-8.0 * (i + 1) / n_heads) for i in range(n_heads)])


def _flatten_obs(obs, lead_dims: int = 1) -> jnp.ndarray:
    """Env-agnostic encoder input: flatten and concat every obs leaf,
    keeping the first ``lead_dims`` axes (batch, or batch+time)."""
    leaves = jax.tree_util.tree_leaves(obs)
    flat = [
        l.reshape(l.shape[:lead_dims] + (-1,)).astype(jnp.float32) for l in leaves
    ]
    return jnp.concatenate(flat, axis=-1)


class CachedSelfAttention(nn.Module):
    """Causal self-attention with two modes sharing one parameter set:

    * step mode — one decode-step over a KV ring buffer (acting path);
    * seq mode — a whole (B, T) window at once (training path): the
      ring-buffer semantics are reproduced exactly with masks, so both
      modes compute identical values: keys must be observed steps, ages
      count *observed* steps (matching the commit-masked cache writes),
      and keys older than ``memory_len`` observed steps are invisible
      (ring eviction).  Burn-in keys get stop_gradient, matching the
      scan path's no-grad warmup.
    """

    d_model: int
    n_heads: int
    memory_len: int

    @nn.compact
    def __call__(self, x, cache=None, slot=None, count=None, seq: bool = False,
                 key_mask=None, burn_in: int = 0, use_flash: bool = False,
                 ring_mesh=None, blk_q: int = 128, blk_k: int = 128):
        H, S = self.n_heads, self.memory_len
        Dh = self.d_model // H

        if not seq:
            B = x.shape[0]
            q = nn.Dense(H * Dh, name="q")(x).reshape(B, H, Dh)
            k_new = nn.Dense(H * Dh, name="k")(x).reshape(B, H, Dh)
            v_new = nn.Dense(H * Dh, name="v")(x).reshape(B, H, Dh)

            oh = jax.nn.one_hot(slot, S, dtype=x.dtype)[..., None, None]  # (B,S,1,1)
            k_cache = cache["k"] * (1 - oh) + oh * k_new[:, None]
            v_cache = cache["v"] * (1 - oh) + oh * v_new[:, None]

            scores = jnp.einsum("bhd,bshd->bhs", q, k_cache) / (Dh ** 0.5)
            idx = jnp.arange(S)
            age = (slot[:, None] - idx[None, :]) % S                      # 0 = newest
            valid = age < count[:, None]
            bias = -_alibi_slopes(H)[None, :, None] * age[:, None, :]
            scores = jnp.where(valid[:, None, :], scores + bias, NEG_INF)
            attn = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhs,bshd->bhd", attn, v_cache).reshape(B, H * Dh)
            return nn.Dense(self.d_model, name="o")(out), {"k": k_cache, "v": v_cache}

        # -- seq mode: (B, T, d_model) ------------------------------------
        B, T, _ = x.shape
        q = nn.Dense(H * Dh, name="q")(x).reshape(B, T, H, Dh)
        k = nn.Dense(H * Dh, name="k")(x).reshape(B, T, H, Dh)
        v = nn.Dense(H * Dh, name="v")(x).reshape(B, T, H, Dh)

        if burn_in > 0:  # scan parity: no gradients through warmup keys
            bmask = (jnp.arange(T) < burn_in).astype(x.dtype)[None, :, None, None]
            k = jax.lax.stop_gradient(k) * bmask + k * (1 - bmask)
            v = jax.lax.stop_gradient(v) * bmask + v * (1 - bmask)

        if key_mask is None:
            key_mask = jnp.ones((B, T), x.dtype)

        # named for the remat ladder (TransformerNet seq mode): under
        # jax.checkpoint with save_only_these_names('attn_qkv') these
        # projections — the flash kernel's custom-VJP residuals — stay
        # materialized while everything else in the block is recomputed,
        # so the kernel's own chunked backward never waits on a second
        # dense-projection replay
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "attn_qkv")
        k = checkpoint_name(k, "attn_qkv")
        v = checkpoint_name(v, "attn_qkv")

        # one semantics, three executions: the O(T^2) einsum reference
        # (masked_attention_reference — per-key masks, observed-age ALiBi,
        # ring-window eviction, self always visible), the O(T·blk) Pallas
        # kernel golden-tested against it
        # (tests/test_flash_attention.py::test_masked_flash_matches_reference),
        # or — when a mesh with an 'sp' axis is supplied — sequence-parallel
        # masked ring attention sharding T across chips
        if ring_mesh is not None:
            from ..ops.ring_attention import masked_ring_self_attention

            out = masked_ring_self_attention(
                q, k, v, key_mask, _alibi_slopes(H), ring_mesh, window=S
            )
        elif use_flash:
            from ..ops.flash_attention import masked_flash_attention

            out = masked_flash_attention(
                q, k, v, key_mask, _alibi_slopes(H), window=S,
                blk_q=blk_q, blk_k=blk_k,
            )
        else:
            from ..ops.flash_attention import masked_attention_reference

            out = masked_attention_reference(q, k, v, key_mask, _alibi_slopes(H), window=S)
        return nn.Dense(self.d_model, name="o")(out.reshape(B, T, H * Dh)), None


class TransformerNet(nn.Module):
    """Generic memory-transformer policy/value net.

    ``num_actions`` sets the policy head; ``with_return`` adds the reward-sum
    head (Geister-style).  Observations of any pytree shape are flattened
    into the token encoder, so one family serves every bundled env.
    """

    num_actions: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    memory_len: int = 32
    mlp_ratio: int = 4
    with_return: bool = False
    supports_seq: bool = True  # train path may call with seq=True

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False, *,
                 seq: bool = False, key_mask=None, burn_in: int = 0,
                 use_flash: bool = False, ring_mesh=None,
                 remat: str = "none", blk_q: int = 128, blk_k: int = 128):
        if seq:
            x = nn.relu(nn.Dense(self.d_model, name="enc1")(_flatten_obs(obs, 2)))
            slot = count = None
        else:
            if hidden is None:
                leaves = jax.tree_util.tree_leaves(obs)
                hidden = self.initial_state((leaves[0].shape[0],))
            x = nn.relu(nn.Dense(self.d_model, name="enc1")(_flatten_obs(obs)))
            pos = hidden["pos"]                 # float32 (B,): scan-carry safe
            count = jnp.minimum(pos + 1, self.memory_len).astype(jnp.int32)
            slot = jnp.mod(pos, float(self.memory_len)).astype(jnp.int32)
        x = nn.Dense(self.d_model, name="enc2")(x)

        # selective-remat ladder (seq mode only; config: train_args.remat):
        #   none  — store every activation (fastest backward, most HBM);
        #   attn  — jax.checkpoint around each attention sublayer: the
        #           O(T^2) score/softmax tensors (einsum) or the kernel
        #           forward (flash) recompute in the backward pass;
        #   block — checkpoint the whole attention+FFN residual block:
        #           only block inputs (B, T, d) survive per layer, the
        #           lever that fits T1024 x d1536 in HBM.
        # Both rungs keep the q/k/v projections — the flash kernel's
        # custom-VJP residuals, tagged 'attn_qkv' in CachedSelfAttention —
        # materialized via save_only_these_names, so the kernel's chunked
        # backward starts from stored operands.  Param names/trees are
        # unchanged (flax lifted remat), so checkpoints stay compatible
        # and remat on/off is bit-identical under jit (pinned by
        # tests/test_transformer.py::test_seq_remat_bit_parity).
        if seq and remat not in ("none", "attn", "block"):
            raise ValueError(f"remat={remat!r} not one of ('none', 'attn', 'block')")
        pol = jax.checkpoint_policies.save_only_these_names("attn_qkv")

        new_layers = []
        for i in range(self.n_layers):
            # one definition of each block half, shared by every rung of
            # the ladder AND the step path — an edit to the block math
            # cannot diverge the executions
            def attn_sub(mdl, h, km, i=i):
                a, _ = CachedSelfAttention(
                    self.d_model, self.n_heads, self.memory_len, name=f"attn{i}"
                )(
                    h, seq=True, key_mask=km, burn_in=burn_in,
                    use_flash=use_flash, ring_mesh=ring_mesh,
                    blk_q=blk_q, blk_k=blk_k,
                )
                return a

            def mlp_half(mdl, x, i=i):
                h = nn.LayerNorm(name=f"ln_m{i}")(x)
                m = nn.Dense(self.mlp_ratio * self.d_model, name=f"mlp_up{i}")(h)
                return x + nn.Dense(self.d_model, name=f"mlp_dn{i}")(nn.relu(m))

            def block_fn(mdl, x, km, i=i):
                h = nn.LayerNorm(name=f"ln_a{i}")(x)
                return mlp_half(mdl, x + attn_sub(mdl, h, km))

            if not seq:
                h = nn.LayerNorm(name=f"ln_a{i}")(x)
                a, new_cache = CachedSelfAttention(
                    self.d_model, self.n_heads, self.memory_len, name=f"attn{i}"
                )(
                    h,
                    cache=hidden["layers"][i],
                    slot=slot,
                    count=count,
                    seq=False,
                    key_mask=key_mask,
                    burn_in=burn_in,
                    use_flash=use_flash,
                    ring_mesh=ring_mesh,
                )
                x = mlp_half(self, x + a)
                new_layers.append(new_cache)
            elif remat == "block":
                x = nn.remat(block_fn, policy=pol)(self, x, key_mask)
                new_layers.append(None)
            elif remat == "attn":
                h = nn.LayerNorm(name=f"ln_a{i}")(x)
                x = mlp_half(self, x + nn.remat(attn_sub, policy=pol)(self, h, key_mask))
                new_layers.append(None)
            else:
                x = block_fn(self, x, key_mask)
                new_layers.append(None)

        h = nn.LayerNorm(name="ln_f")(x)
        out: Dict[str, Any] = {
            "policy": nn.Dense(self.num_actions, name="policy")(h),
            "value": jnp.tanh(nn.Dense(1, name="value")(h)),
        }
        if not seq:
            out["hidden"] = {"layers": tuple(new_layers), "pos": hidden["pos"] + 1.0}
        if self.with_return:
            out["return"] = nn.Dense(1, name="return_head")(h)
        return out

    @nn.nowrap
    def initial_state(self, batch_dims: Sequence[int] = ()):
        bd = tuple(batch_dims)
        Dh = self.d_model // self.n_heads
        cache = lambda: {  # noqa: E731
            "k": jnp.zeros((*bd, self.memory_len, self.n_heads, Dh), jnp.float32),
            "v": jnp.zeros((*bd, self.memory_len, self.n_heads, Dh), jnp.float32),
        }
        # pos is float32 so the train step's observation-mask arithmetic on
        # the hidden carry (h * mask) never changes the carry dtype
        return {"layers": tuple(cache() for _ in range(self.n_layers)), "pos": jnp.zeros(bd, jnp.float32)}
