"""Real ONNX export without tf2onnx: jaxpr -> torch -> ONNX ModelProto.

Round-5 finding (caught by the conversion-contract test this replaces):
modern jax2tf ALWAYS emits ``XlaCallModule`` — ``native_serialization=
False`` is deprecated and ignored (jax 0.9: the parameter is ``del``eted
on entry) — so the jax2tf -> tf2onnx pipeline the round-3 exporter
promised cannot produce a convertible graph on current JAX anywhere,
including the CI extras job.  The replacement here goes through torch,
whose TorchScript ONNX exporter serializes the ModelProto in C++ (no
``onnx`` package needed):

    jax.make_jaxpr(inference fn)  ->  TorchJaxpr (an nn.Module that
    interprets the jaxpr with torch ops; params ride as buffers)
    ->  torch.jit.trace  ->  torch.onnx.export

The interpreter covers the closed primitive set of this framework's
inference nets (SimpleConvNet, GeeseNet, DRC ConvLSTM, KV-cache
transformer — 35 primitives, enumerated by tracing each family) and
fails loudly on anything outside it.  Correctness is pinned in-image,
without any ONNX runtime: TorchJaxpr output == jax output (elementwise)
at the traced batch AND at a different batch through the traced graph —
the exact graph the ONNX serializer sees — so the artifact's math and
its dynamic batch axis are both verified before the file is written.

Artifact contract (reference parity, scripts/make_onnx_model.py:28-58):
observation pytree leaves -> ``input_N`` inputs, hidden-state leaves ->
``hidden_N``, outputs keep their dict keys (+ ``hidden_N_out`` for the
next-step state), batch axis dynamic, opset 17.  The ``<path>.meta``
sidecar (wire codec) carries the pytree structure + initial hidden for
``OnnxModel`` (export.py) to rebuild framework-shaped values.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["TorchJaxpr", "export_onnx_via_torch"]


_TORCH_DTYPES = {
    "float32": "float32", "float16": "float16", "bfloat16": "bfloat16",
    "float64": "float64", "int32": "int32", "int64": "int64",
    "int16": "int16", "int8": "int8", "uint8": "uint8", "bool": "bool",
}


def _to_torch_dtype(torch, np_dtype) -> Any:
    name = np.dtype(np_dtype).name if np_dtype != bool else "bool"
    if name not in _TORCH_DTYPES:
        raise NotImplementedError(f"dtype {name} not mapped to torch")
    return getattr(torch, _TORCH_DTYPES[name])


class _Interpreter:
    """Evaluate a jaxpr with torch tensors.  Every handler uses only
    torch ops the TorchScript ONNX exporter lowers to standard ONNX
    (Conv, MatMul/Einsum, elementwise, Reduce*, Where, Concat, ...)."""

    def __init__(self, torch, batch_dynamic: bool):
        self.torch = torch
        self.batch_dynamic = batch_dynamic
        self.trace_batch: Optional[int] = None  # set by TorchJaxpr.forward
        self._batch_col = None  # (B, 1) zeros, dynamic under trace

    def begin(self, args) -> None:
        """Stash a dynamic (B, 1) zero column from the first input —
        built with shape-free ops (flatten/slice) so torch.jit.trace
        keeps the batch extent symbolic.  Broadcasts INTO the batch use
        it: ``x + zeros(B, 1...)`` dynamically batches a size-1 tensor,
        where a static ``expand`` would bake the traced batch."""
        if not self.batch_dynamic or not args:
            self._batch_col = None
            return
        a = args[0]
        if a.dim() == 1:
            a = a.unsqueeze(1)
        self._batch_col = a.flatten(1)[:, :1].float() * 0.0

    def _dynamic_batchify(self, x):
        """(1, d1, ...) -> (B, d1, ...) with B symbolic under trace."""
        t = self.torch
        z = self._batch_col.reshape([-1] + [1] * (x.dim() - 1))
        if x.dtype == t.bool:
            return (x.to(t.uint8) + z.to(t.uint8)).to(t.bool)
        return x + z.to(x.dtype)

    # -- driver ----------------------------------------------------------
    def run(self, jaxpr, consts: Sequence, args: Sequence) -> List:
        env: Dict[Any, Any] = {}

        def read(v):
            from jax.extend.core import Literal

            if isinstance(v, Literal):
                t = self.torch.as_tensor(np.asarray(v.val))
                return t
            return env[v]

        for var, const in zip(jaxpr.constvars, consts):
            env[var] = const
        for var, arg in zip(jaxpr.invars, args):
            env[var] = arg

        for eqn in jaxpr.eqns:
            fn = getattr(self, "p_" + eqn.primitive.name.replace("-", "_"), None)
            if fn is None:
                raise NotImplementedError(
                    f"jax primitive '{eqn.primitive.name}' is outside the "
                    "ONNX-exportable inference set (torch_export.py); "
                    "extend _Interpreter to cover it"
                )
            invals = [read(v) for v in eqn.invars]
            out = fn(eqn, invals)
            if eqn.primitive.multiple_results:
                for var, val in zip(eqn.outvars, out):
                    env[var] = val
            else:
                env[eqn.outvars[0]] = out
        return [read(v) for v in jaxpr.outvars]

    def _inline(self, eqn, invals, key):
        inner = eqn.params[key]
        # ClosedJaxpr: consts are embedded values
        consts = [self.torch.as_tensor(np.asarray(c)) for c in inner.consts]
        return self.run(inner.jaxpr, consts, invals)

    # -- call-like primitives (inlined) ---------------------------------
    def p_pjit(self, eqn, invals):
        return self._inline(eqn, invals, "jaxpr")

    p_jit = p_pjit

    def p_custom_jvp_call(self, eqn, invals):
        return self._inline(eqn, invals, "call_jaxpr")

    def p_custom_vjp_call(self, eqn, invals):
        return self._inline(eqn, invals, "call_jaxpr")

    def p_closed_call(self, eqn, invals):
        return self._inline(eqn, invals, "call_jaxpr")

    # -- elementwise -----------------------------------------------------
    def p_add(self, eqn, iv):
        return iv[0] + iv[1]

    def p_sub(self, eqn, iv):
        return iv[0] - iv[1]

    def p_mul(self, eqn, iv):
        return iv[0] * iv[1]

    def p_div(self, eqn, iv):
        a, b = iv
        if not a.dtype.is_floating_point and not b.dtype.is_floating_point:
            # lax.div on integers truncates toward zero
            return self.torch.div(a, b, rounding_mode="trunc")
        return a / b

    def p_rem(self, eqn, iv):
        return self.torch.fmod(iv[0], iv[1])  # lax.rem: sign of dividend

    def p_max(self, eqn, iv):
        return self.torch.maximum(iv[0], iv[1])

    def p_min(self, eqn, iv):
        return self.torch.minimum(iv[0], iv[1])

    def p_and(self, eqn, iv):
        return self.torch.logical_and(iv[0], iv[1])

    def p_or(self, eqn, iv):
        return self.torch.logical_or(iv[0], iv[1])

    def p_eq(self, eqn, iv):
        return iv[0] == iv[1]

    def p_ne(self, eqn, iv):
        return iv[0] != iv[1]

    def p_ge(self, eqn, iv):
        return iv[0] >= iv[1]

    def p_gt(self, eqn, iv):
        return iv[0] > iv[1]

    def p_le(self, eqn, iv):
        return iv[0] <= iv[1]

    def p_lt(self, eqn, iv):
        return iv[0] < iv[1]

    def p_neg(self, eqn, iv):
        return -iv[0]

    def p_exp(self, eqn, iv):
        return self.torch.exp(iv[0])

    def p_log(self, eqn, iv):
        return self.torch.log(iv[0])

    def p_tanh(self, eqn, iv):
        return self.torch.tanh(iv[0])

    def p_logistic(self, eqn, iv):
        return self.torch.sigmoid(iv[0])

    def p_rsqrt(self, eqn, iv):
        return self.torch.rsqrt(iv[0])

    def p_sqrt(self, eqn, iv):
        return self.torch.sqrt(iv[0])

    def p_square(self, eqn, iv):
        return iv[0] * iv[0]

    def p_abs(self, eqn, iv):
        return self.torch.abs(iv[0])

    def p_sign(self, eqn, iv):
        return self.torch.sign(iv[0])

    def p_floor(self, eqn, iv):
        return self.torch.floor(iv[0])

    def p_stop_gradient(self, eqn, iv):
        return iv[0]

    def p_convert_element_type(self, eqn, iv):
        return iv[0].to(_to_torch_dtype(self.torch, eqn.params["new_dtype"]))

    def p_integer_pow(self, eqn, iv):
        return iv[0] ** eqn.params["y"]

    # -- shape ops -------------------------------------------------------
    def p_reshape(self, eqn, iv):
        assert eqn.params.get("dimensions") is None, "reshape with dimensions"
        new_sizes = list(eqn.params["new_sizes"])
        x = iv[0]
        if (
            self.batch_dynamic
            and len(new_sizes) >= 1
            and x.dim() >= 1
            and new_sizes
            and eqn.invars[0].aval.shape[:1] == tuple(new_sizes[:1])
        ):
            # leading dim preserved -> -1 keeps it symbolic under
            # torch.jit.trace (x.shape[0] would be constant-folded), so a
            # trace at batch B stays valid at any batch (the ONNX
            # dynamic axis)
            return x.reshape([-1] + [int(s) for s in new_sizes[1:]])
        return x.reshape([int(s) for s in new_sizes])

    def p_transpose(self, eqn, iv):
        return iv[0].permute(*eqn.params["permutation"])

    def p_squeeze(self, eqn, iv):
        x = iv[0]
        for d in sorted(eqn.params["dimensions"], reverse=True):
            x = x.squeeze(d)
        return x

    def p_expand_dims(self, eqn, iv):
        x = iv[0]
        for d in sorted(eqn.params["dimensions"]):
            x = x.unsqueeze(d)
        return x

    def p_broadcast_in_dim(self, eqn, iv):
        x = iv[0]
        shape = [int(s) for s in eqn.params["shape"]]
        bdims = list(eqn.params["broadcast_dimensions"])  # strictly increasing
        in_shape = eqn.invars[0].aval.shape  # static shapes from the jaxpr
        # insert singleton dims at the unmapped output positions; existing
        # dims keep their (possibly symbolic under trace) extents
        for d in range(len(shape)):
            if d not in bdims:
                x = x.unsqueeze(d)
        # expand: -1 (keep, stays symbolic) for carried dims, the static
        # target for inserted dims and true size-1 broadcasts
        expand = []
        into_batch = False
        for d in range(len(shape)):
            if d in bdims:
                i = bdims.index(d)
                carried = not (in_shape[i] == 1 and shape[d] != 1)
                expand.append(-1 if carried else shape[d])
            else:
                expand.append(shape[d])
            if (
                d == 0
                and self.batch_dynamic
                and self._batch_col is not None
                and expand[0] == shape[0]          # static (not carried)
                and shape[0] == self.trace_batch   # and it IS the batch
            ):
                # broadcast INTO the batch dim: expand to 1 here, then
                # batch it dynamically so the trace stays batch-agnostic
                expand[0] = -1 if d in bdims else 1
                into_batch = True
        out = x.expand(expand)
        return self._dynamic_batchify(out) if into_batch else out

    def p_slice(self, eqn, iv):
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or (1,) * len(starts)
        idx = tuple(
            slice(int(s), int(l), int(st))
            for s, l, st in zip(starts, limits, strides)
        )
        return iv[0][idx]

    def p_split(self, eqn, iv):
        sizes = [int(s) for s in eqn.params["sizes"]]
        return list(self.torch.split(iv[0], sizes, dim=eqn.params["axis"]))

    def p_concatenate(self, eqn, iv):
        return self.torch.cat(list(iv), dim=eqn.params["dimension"])

    def p_pad(self, eqn, iv):
        x, pad_val = iv
        cfg = eqn.params["padding_config"]
        assert all(i == 0 for _, _, i in cfg), "interior padding unsupported"
        # torch.nn.functional.pad lists dims LAST-first
        flat: List[int] = []
        for lo, hi, _ in reversed(cfg):
            flat += [int(lo), int(hi)]
        import torch.nn.functional as F

        return F.pad(x, flat, value=float(pad_val))

    def p_rev(self, eqn, iv):
        return self.torch.flip(iv[0], dims=list(eqn.params["dimensions"]))

    def p_iota(self, eqn, iv):
        shape = [int(s) for s in eqn.params["shape"]]
        dim = eqn.params["dimension"]
        dtype = _to_torch_dtype(self.torch, eqn.params["dtype"])
        r = self.torch.arange(shape[dim], dtype=dtype)
        view = [1] * len(shape)
        view[dim] = shape[dim]
        return r.reshape(view).expand(shape)

    def p_select_n(self, eqn, iv):
        pred, *cases = iv
        if len(cases) == 2:
            return self.torch.where(pred.bool(), cases[1], cases[0])
        out = cases[0]
        for k in range(1, len(cases)):
            out = self.torch.where(pred == k, cases[k], out)
        return out

    # -- reductions ------------------------------------------------------
    def _axes(self, eqn):
        return [int(a) for a in eqn.params["axes"]]

    def p_reduce_sum(self, eqn, iv):
        return iv[0].sum(dim=self._axes(eqn))

    def p_reduce_max(self, eqn, iv):
        return iv[0].amax(dim=self._axes(eqn))

    def p_reduce_min(self, eqn, iv):
        return iv[0].amin(dim=self._axes(eqn))

    def p_reduce_and(self, eqn, iv):
        x = iv[0]
        for d in sorted(self._axes(eqn), reverse=True):
            x = x.all(dim=d)
        return x

    def p_reduce_or(self, eqn, iv):
        x = iv[0]
        for d in sorted(self._axes(eqn), reverse=True):
            x = x.any(dim=d)
        return x

    # -- contractions ----------------------------------------------------
    def p_dot_general(self, eqn, iv):
        lhs, rhs = iv
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        ln, rn = lhs.dim(), rhs.dim()
        letters = iter("abcdefghijklmnopqrstuvwxyz")
        l_spec = [""] * ln
        r_spec = [""] * rn
        out_batch, out_lfree, out_rfree = [], [], []
        for i, j in zip(lb, rb):
            c = next(letters)
            l_spec[i] = c
            r_spec[j] = c
            out_batch.append(c)
        for i, j in zip(lc, rc):
            c = next(letters)
            l_spec[i] = c
            r_spec[j] = c
        for i in range(ln):
            if not l_spec[i]:
                c = next(letters)
                l_spec[i] = c
                out_lfree.append(c)
        for j in range(rn):
            if not r_spec[j]:
                c = next(letters)
                r_spec[j] = c
                out_rfree.append(c)
        spec = (
            "".join(l_spec) + "," + "".join(r_spec) + "->"
            + "".join(out_batch + out_lfree + out_rfree)
        )
        return self.torch.einsum(spec, lhs, rhs)

    def p_conv_general_dilated(self, eqn, iv):
        import torch.nn.functional as F

        lhs, rhs = iv
        p = eqn.params
        dn = p["dimension_numbers"]
        lhs_spec, rhs_spec, out_spec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
        nd = len(lhs_spec) - 2
        if any(d != 1 for d in p["lhs_dilation"]):
            raise NotImplementedError("transposed conv (lhs_dilation) unsupported")
        if p.get("batch_group_count", 1) != 1:
            raise NotImplementedError("batch_group_count != 1 unsupported")
        # to N C spatial... (torch layout), spatial order per the spec
        x = lhs.permute([lhs_spec[0], lhs_spec[1]] + list(lhs_spec[2:]))
        w = rhs.permute([rhs_spec[0], rhs_spec[1]] + list(rhs_spec[2:]))
        pads = [(int(lo), int(hi)) for lo, hi in p["padding"]]
        sym = all(lo == hi for lo, hi in pads)
        if sym:
            padding = [lo for lo, _ in pads]
        else:
            flat: List[int] = []
            for lo, hi in reversed(pads):
                flat += [lo, hi]
            x = F.pad(x, flat)
            padding = [0] * nd
        conv = {1: F.conv1d, 2: F.conv2d, 3: F.conv3d}[nd]
        y = conv(
            x, w, stride=[int(s) for s in p["window_strides"]],
            padding=padding, dilation=[int(d) for d in p["rhs_dilation"]],
            groups=int(p["feature_group_count"]),
        )
        # y is N C' spatial' -> permute into out_spec order
        inv = [0] * len(out_spec)
        src = [out_spec[0], out_spec[1]] + list(out_spec[2:])
        for pos, dim in enumerate(src):
            inv[dim] = pos
        return y.permute(inv)


class TorchJaxpr:
    """Builds an ``nn.Module`` whose forward interprets ``fn``'s jaxpr
    with torch ops (constants/params ride as buffers)."""

    def __new__(cls, fn, example_args, batch_dynamic: bool = True):
        import torch

        closed = __import__("jax").make_jaxpr(fn)(*example_args)
        interp = _Interpreter(torch, batch_dynamic)
        leaves = __import__("jax").tree.leaves(example_args)
        interp.trace_batch = (
            int(leaves[0].shape[0]) if leaves and np.ndim(leaves[0]) else None
        )

        class _Mod(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self._consts = []
                for i, c in enumerate(closed.consts):
                    t = torch.as_tensor(np.asarray(c))
                    self.register_buffer(f"const_{i}", t)
                    self._consts.append(t)

            def forward(self, *flat_inputs):
                consts = [getattr(self, f"const_{i}")
                          for i in range(len(self._consts))]
                interp.begin(list(flat_inputs))
                outs = interp.run(closed.jaxpr, consts, list(flat_inputs))
                return tuple(outs)

        mod = _Mod().eval()
        mod.closed_jaxpr = closed
        return mod


def export_onnx_via_torch(fn, example_args, path: str,
                          input_names: List[str],
                          output_names: List[str],
                          constant_folding: bool = True) -> None:
    """Trace ``fn``'s jaxpr-interpreting torch module and write a real
    ONNX ModelProto via torch's C++ serializer.  Verifies numerics at
    the example batch AND at a different batch through the traced graph
    before writing; works without the ``onnx`` package (the exporter's
    only use of it — appending registered onnxscript functions — is
    bypassed as a no-op when none can exist)."""
    import torch

    import jax

    mod = TorchJaxpr(fn, example_args)
    flat_np = [np.asarray(x) for x in jax.tree.leaves(example_args)]
    tin = [torch.as_tensor(x) for x in flat_np]

    # numeric pin 1: eager interpreter vs jax at the traced batch
    want = [np.asarray(x) for x in jax.tree.leaves(fn(*example_args))]
    got = [t.detach().numpy() for t in mod(*tin)]
    for w, g in zip(want, got):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    traced = torch.jit.trace(mod, tuple(tin))

    # numeric pin 2: the TRACED graph (what ONNX serializes) at batch 3
    B = flat_np[0].shape[0]
    if all(x.ndim >= 1 and x.shape[0] == B for x in flat_np):
        rng = np.random.default_rng(0)
        flat3 = [
            rng.standard_normal((3,) + x.shape[1:]).astype(x.dtype)
            if np.issubdtype(x.dtype, np.floating)
            else np.repeat(x[:1], 3, axis=0)
            for x in flat_np
        ]
        args3 = jax.tree.unflatten(jax.tree.structure(example_args), flat3)
        want3 = [np.asarray(x) for x in jax.tree.leaves(fn(*args3))]
        got3 = [t.detach().numpy()
                for t in traced(*[torch.as_tensor(x) for x in flat3])]
        for w, g in zip(want3, got3):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    try:
        import onnx  # noqa: F401  -- present in the CI extras env
    except ImportError:
        from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

        # no onnxscript functions can be registered without the package;
        # the step is a structural no-op, so skipping it is lossless
        onnx_proto_utils._add_onnxscript_fn = (
            lambda model_bytes, custom_opsets: model_bytes
        )

    # constant_folding=False is the int8 export's request: folding would
    # evaluate the dequantize (Cast+Mul on constant int8 buffers) at
    # export time and bake full-width fp32 weights into the artifact,
    # exactly what the quantized route exists to avoid
    torch.onnx.export(
        traced, tuple(tin), path,
        input_names=input_names,
        output_names=output_names,
        dynamic_axes={n: {0: "batch"} for n in input_names + output_names},
        opset_version=17,
        do_constant_folding=constant_folding,
        dynamo=False,
    )
