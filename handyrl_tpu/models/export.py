"""Deployment export: serialized StableHLO artifacts with params baked in.

TPU-native equivalent of the reference's ONNX path (scripts/
make_onnx_model.py:28-58 export, evaluation.py:287-353 OnnxModel): a
trained model is frozen into a single self-contained artifact that any
JAX runtime can execute without the framework's model code, with a
dynamic (symbolic) batch dimension like the reference's dynamic batch
axis.  Hidden tensors ride along as an explicit pytree (the reference
discovers them by the ``hidden*`` input-name prefix).

Artifact format (our wire codec, runtime/codec.py):
    {"mlir": <jax.export serialized bytes>, "hidden0": pytree|None}
The output names/treedef ride inside the serialized jax.export blob.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map
from .inference import SingleInferenceMixin


def _leaf_specs(pytree, scope, leading: str):
    """ShapeDtypeStructs with a shared symbolic leading dim for every leaf."""

    def spec(x):
        x = np.asarray(x)
        dims = ", ".join(str(d) for d in x.shape)
        shape = jax.export.symbolic_shape(f"{leading}, {dims}" if dims else leading, scope=scope)
        return jax.ShapeDtypeStruct(shape, x.dtype)

    return tree_map(spec, pytree)


def export_model(module, variables, sample_obs, path: str) -> None:
    """Freeze (module, variables) into a serialized StableHLO file.

    ``sample_obs`` is one unbatched observation pytree (from
    ``env.observation(p)``); the exported callable takes batch-leading
    pytrees with a symbolic batch size.
    """
    from ..runtime import codec

    hidden0 = module.initial_state((1,))
    scope = jax.export.SymbolicScope()
    obs_spec = _leaf_specs(sample_obs, scope, "b")

    # multi-platform lowering: the artifact must run wherever it's deployed
    # (the reference's ONNX artifacts are platform-neutral; ours match)
    platforms = ("cpu", "tpu")
    if hidden0 is None:
        fn = lambda obs: module.apply(variables, obs, None)  # noqa: E731
        exported = jax.export.export(jax.jit(fn), platforms=platforms)(obs_spec)
        hidden_host = None
    else:
        fn = lambda obs, hidden: module.apply(variables, obs, hidden)  # noqa: E731
        hidden_spec = _leaf_specs(tree_map(lambda x: np.asarray(x)[0], hidden0), scope, "b")
        exported = jax.export.export(jax.jit(fn), platforms=platforms)(obs_spec, hidden_spec)
        hidden_host = tree_map(np.asarray, hidden0)

    blob = codec.dumps({"mlir": exported.serialize(), "hidden0": hidden_host})
    with open(path, "wb") as f:
        f.write(blob)


class _ArtifactModel(SingleInferenceMixin):
    """Shared base for deployed artifacts: hidden state is stored with a
    leading batch axis of 1 (``self._hidden0``); ``init_hidden`` strips or
    broadcasts it."""

    _hidden0: Optional[Any] = None

    def init_hidden(self, batch_dims=()):
        if self._hidden0 is None:
            return None
        flat = tree_map(lambda x: x[0], self._hidden0)
        if not batch_dims:
            return flat
        return tree_map(lambda x: np.broadcast_to(x, tuple(batch_dims) + x.shape).copy(), flat)

    def _extract_hidden(self, out: Dict[str, Any]) -> Dict[str, Any]:
        """Fold flat next-step-state outputs back into an ``out['hidden']``
        pytree.  Names: 'hidden_N_out' (torch-bridge ONNX artifacts; ONNX
        graph values are SSA, so outputs cannot reuse the input names) or
        bare 'hidden_N' (the TF-bridge artifacts)."""
        hid_names = sorted(
            (k for k in out if k.startswith("hidden_")),
            key=lambda k: int(k[7:-4] if k.endswith("_out") else k[7:]),
        )
        if hid_names:
            _, hid_tree = jax.tree.flatten(self._hidden0)
            out["hidden"] = jax.tree.unflatten(
                hid_tree, [out.pop(k) for k in hid_names]
            )
        return out


class ExportedModel(_ArtifactModel):
    """Inference over a serialized artifact; same API as InferenceModel.

    Role of the reference's OnnxModel (evaluation.py:287-353): standalone
    deployment/eval inference without the original model code.
    """

    def __init__(self, path: str):
        from ..runtime import codec

        with open(path, "rb") as f:
            data = codec.loads(f.read())
        self._exported = jax.export.deserialize(bytearray(data["mlir"]))
        self._hidden0 = data["hidden0"]

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        obs = tree_map(jnp.asarray, obs)
        if self._hidden0 is None:
            outputs = self._exported.call(obs)
        else:
            if hidden is None:
                n = jax.tree_util.tree_leaves(obs)[0].shape[0]
                hidden = self.init_hidden((n,))
            outputs = self._exported.call(obs, tree_map(jnp.asarray, hidden))
        return jax.device_get(outputs)


# -- TF SavedModel / ONNX bridge (non-JAX runtimes) -------------------------

def _poly(x):
    return "(" + ", ".join(["b"] + ["_"] * (np.asarray(x).ndim - 1)) + ")"


def _tf_spec(x, name):
    import tensorflow as tf

    x = np.asarray(x)
    return tf.TensorSpec([None] + list(x.shape[1:]), x.dtype, name=name)


def _bridge_fn(module, variables, sample_obs):
    """Flat-leaf wrapper shared by the SavedModel and ONNX exporters:
    observation leaves become ``input_N``, hidden leaves ``hidden_N``
    (jax.tree order, the reference's name-prefix contract
    evaluation.py:335-344); returns (fn, leaves, names, hidden0, n_obs)."""
    hidden0 = module.initial_state((1,))
    obs_b = tree_map(lambda x: np.asarray(x)[None], sample_obs)
    obs_leaves, obs_tree = jax.tree.flatten(obs_b)
    hid_leaves, hid_tree = jax.tree.flatten(hidden0)  # [] / None when stateless

    def fn(*leaves):
        obs = jax.tree.unflatten(obs_tree, leaves[: len(obs_leaves)])
        hidden = (
            jax.tree.unflatten(hid_tree, leaves[len(obs_leaves):])
            if hid_leaves
            else None
        )
        out = module.apply(variables, obs, hidden)
        flat = {k: v for k, v in out.items() if k != "hidden" and v is not None}
        for i, leaf in enumerate(jax.tree.leaves(out.get("hidden"))):
            flat[f"hidden_{i}"] = leaf
        return flat

    leaves = list(obs_leaves) + list(hid_leaves)
    names = [f"input_{i}" for i in range(len(obs_leaves))] + [
        f"hidden_{i}" for i in range(len(hid_leaves))
    ]
    return fn, leaves, names, hidden0, len(obs_leaves)


def export_savedmodel(module, variables, sample_obs, path: str) -> None:
    """Freeze (module, variables) into a TF SavedModel via jax2tf.

    The bridge artifact for runtimes outside JAX — TF Serving, TFLite,
    or ONNX via the standard tf2onnx converter where installed — covering
    the deployment role of the reference's ONNX export
    (scripts/make_onnx_model.py:28-58).  Naming parity with the reference
    (``input.N``/``hidden.N`` discovered by prefix, evaluation.py:335-344):
    observation pytree leaves flatten to ``input_N``, hidden-state leaves
    to ``hidden_N`` (jax.tree order), outputs to their dict keys plus
    ``hidden_N`` for the next-step state.  Batch dimension is polymorphic.
    """
    import tensorflow as tf
    from jax.experimental import jax2tf

    fn, leaves, names, hidden0, n_obs = _bridge_fn(module, variables, sample_obs)
    converted = jax2tf.convert(
        fn, polymorphic_shapes=[_poly(l) for l in leaves], with_gradient=False
    )
    m = tf.Module()
    m.f = tf.function(
        converted,
        input_signature=[_tf_spec(l, n) for l, n in zip(leaves, names)],
        autograph=False,
    )
    # keep the pytree structure + initial hidden alongside the graph so the
    # loader can rebuild framework-shaped inputs/outputs
    from ..runtime import codec

    os.makedirs(path, exist_ok=True)
    tf.saved_model.save(m, path)
    meta = {
        "n_obs": n_obs,
        "hidden0": None if hidden0 is None else tree_map(np.asarray, hidden0),
    }
    with open(os.path.join(path, "handyrl_meta.bin"), "wb") as f:
        f.write(codec.dumps(meta))


class _DequantApplyShim:
    """Module stand-in for the int8 ONNX export: holds int8-wrapped
    ``variables`` and dequantizes inside the traced apply, so the int8
    codes become int8 initializers in the artifact and the widen-to-fp32
    becomes ordinary Cast/Mul graph ops ahead of each consuming matmul —
    the serialized twin of ``quantize.jitted_dequant_apply``.  The apply
    goes through that SAME jitted entry point on purpose: under
    ``jax.make_jaxpr`` the jit boundary stages the dequantize into a pjit
    sub-jaxpr whose int8 constants survive as int8 constvars, where an
    inline ``astype`` on concrete arrays would constant-fold to fp32 and
    silently ship full-width params."""

    def __init__(self, module):
        self._module = module

    def initial_state(self, batch_dims):
        return self._module.initial_state(batch_dims)

    def apply(self, variables, obs, hidden):
        from .quantize import jitted_dequant_apply

        return jitted_dequant_apply(self._module)(variables, obs, hidden)


def export_onnx(module, variables, sample_obs, path: str,
                weight_dtype: str = "float32") -> None:
    """Freeze (module, variables) into a real ``.onnx`` file — the
    reference's exact artifact kind (scripts/make_onnx_model.py:28-58) —
    via the jaxpr->torch bridge (``torch_export.py``): the inference
    jaxpr is interpreted with torch ops and serialized by torch's C++
    TorchScript ONNX exporter (no ``onnx``/``tf2onnx`` needed; numerics
    are verified against jax at two batch sizes before the file is
    written).  The earlier jax2tf->tf2onnx route is dead on modern JAX:
    jax2tf always emits ``XlaCallModule`` (``native_serialization=False``
    is deprecated and ignored), which no ONNX converter accepts.

    Same naming contract as ``export_savedmodel``: inputs ``input_N`` /
    ``hidden_N``, outputs keep their dict keys, next-step state as
    ``hidden_N_out``, batch axis dynamic.  A sidecar ``<path>.meta``
    carries the pytree structure + initial hidden so ``OnnxModel`` can
    rebuild framework-shaped inputs/outputs.

    ``weight_dtype='int8'`` (the ``.int8.onnx`` route in
    scripts/export_model.py) per-channel-quantizes the kernels first and
    traces through a dequantizing shim, so the artifact carries int8
    initializers plus explicit Cast/Mul dequantize nodes — ~4x smaller
    params on the edge-replica wire, numerics still verified against the
    jax dequantize path before the file is written."""
    from ..runtime import codec
    from .torch_export import export_onnx_via_torch

    if weight_dtype == "int8":
        from .quantize import quantize_params

        params = variables.get("params", variables)
        # device_put up front: numpy constants entering the trace would
        # stage device_put eqns the torch bridge (rightly) rejects
        variables = jax.device_put(dict(variables, params=quantize_params(params)))
        module = _DequantApplyShim(module)
    elif weight_dtype != "float32":
        raise ValueError(f"unknown weight_dtype for ONNX export: {weight_dtype!r}")

    fn, leaves, in_names, hidden0, n_obs = _bridge_fn(module, variables, sample_obs)
    probe = fn(*leaves)
    out_keys = sorted(probe.keys())  # jax dict pytrees flatten key-sorted
    out_names = [
        k + "_out" if k.startswith("hidden_") else k for k in out_keys
    ]

    def tup_fn(*ls):
        d = fn(*ls)
        return tuple(d[k] for k in out_keys)

    # trace at batch 5 (not 1): a batch-1 jaxpr cannot distinguish
    # "broadcast into batch" from "keep batch-1", which bakes the batch
    # into the graph; an unusual trace batch also lets the bridge
    # recognize the batch extent structurally (torch_export.py)
    tiled = tuple(np.repeat(np.asarray(l), 5, axis=0) for l in leaves)
    export_onnx_via_torch(
        tup_fn, tiled, path,
        input_names=list(in_names), output_names=out_names,
        constant_folding=(weight_dtype != "int8"),
    )
    meta = {
        "n_obs": n_obs,
        "hidden0": None if hidden0 is None else tree_map(np.asarray, hidden0),
    }
    with open(path + ".meta", "wb") as f2:
        f2.write(codec.dumps(meta))


class OnnxModel(_ArtifactModel):
    """Inference over a ``.onnx`` artifact via onnxruntime; same API as
    InferenceModel — the direct counterpart of the reference's OnnxModel
    (evaluation.py:287-353), including hidden-state discovery by the
    ``hidden_N`` input-name prefix.  Requires the optional ``onnxruntime``
    package."""

    def __init__(self, path: str):
        try:
            import onnxruntime
        except ImportError as exc:  # pragma: no cover - optional dep
            raise ImportError(
                "loading .onnx artifacts needs the optional 'onnxruntime' "
                "package (pip install onnxruntime)"
            ) from exc
        from ..runtime import codec

        self._sess = onnxruntime.InferenceSession(
            path, providers=["CPUExecutionProvider"]
        )
        with open(path + ".meta", "rb") as f:
            meta = codec.loads(f.read())
        self._n_obs = int(meta["n_obs"])
        self._hidden0 = meta["hidden0"]
        self._input_names = [i.name for i in self._sess.get_inputs()]

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        obs_leaves = jax.tree.leaves(tree_map(np.asarray, obs))
        if len(obs_leaves) != self._n_obs:
            raise ValueError(
                f"observation pytree has {len(obs_leaves)} leaves; the "
                f"artifact was exported for {self._n_obs}"
            )
        if self._hidden0 is not None and hidden is None:
            hidden = self.init_hidden((obs_leaves[0].shape[0],))
        hid_leaves = (
            jax.tree.leaves(tree_map(np.asarray, hidden)) if hidden is not None else []
        )
        feeds = dict(zip(self._input_names, obs_leaves + hid_leaves))
        out_names = [o.name for o in self._sess.get_outputs()]
        vals = self._sess.run(out_names, feeds)
        out = dict(zip(out_names, (np.asarray(v) for v in vals)))
        return self._extract_hidden(out)


class SavedModelModel(_ArtifactModel):
    """Inference over an exported TF SavedModel; same API as InferenceModel.

    TF-runtime twin of ``ExportedModel`` — the reference's OnnxModel role
    (evaluation.py:287-353) for deployments that run TF, not JAX.
    """

    def __init__(self, path: str):
        import tensorflow as tf

        from ..runtime import codec

        self._tf = tf
        self._loaded = tf.saved_model.load(path)
        with open(os.path.join(path, "handyrl_meta.bin"), "rb") as f:
            meta = codec.loads(f.read())
        self._n_obs = int(meta["n_obs"])
        self._hidden0 = meta["hidden0"]

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        obs_leaves = jax.tree.leaves(tree_map(np.asarray, obs))
        if len(obs_leaves) != self._n_obs:
            raise ValueError(
                f"observation pytree has {len(obs_leaves)} leaves; the "
                f"artifact was exported for {self._n_obs}"
            )
        if self._hidden0 is not None and hidden is None:
            hidden = self.init_hidden((obs_leaves[0].shape[0],))
        hid_leaves = jax.tree.leaves(tree_map(np.asarray, hidden)) if hidden is not None else []
        out = self._loaded.f(*[self._tf.constant(l) for l in obs_leaves + hid_leaves])
        out = {k: np.asarray(v) for k, v in out.items()}
        return self._extract_hidden(out)
