"""Deployment export: serialized StableHLO artifacts with params baked in.

TPU-native equivalent of the reference's ONNX path (scripts/
make_onnx_model.py:28-58 export, evaluation.py:287-353 OnnxModel): a
trained model is frozen into a single self-contained artifact that any
JAX runtime can execute without the framework's model code, with a
dynamic (symbolic) batch dimension like the reference's dynamic batch
axis.  Hidden tensors ride along as an explicit pytree (the reference
discovers them by the ``hidden*`` input-name prefix).

Artifact format (our wire codec, runtime/codec.py):
    {"mlir": <jax.export serialized bytes>, "hidden0": pytree|None}
The output names/treedef ride inside the serialized jax.export blob.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map
from .inference import SingleInferenceMixin


def _leaf_specs(pytree, scope, leading: str):
    """ShapeDtypeStructs with a shared symbolic leading dim for every leaf."""

    def spec(x):
        x = np.asarray(x)
        dims = ", ".join(str(d) for d in x.shape)
        shape = jax.export.symbolic_shape(f"{leading}, {dims}" if dims else leading, scope=scope)
        return jax.ShapeDtypeStruct(shape, x.dtype)

    return tree_map(spec, pytree)


def export_model(module, variables, sample_obs, path: str) -> None:
    """Freeze (module, variables) into a serialized StableHLO file.

    ``sample_obs`` is one unbatched observation pytree (from
    ``env.observation(p)``); the exported callable takes batch-leading
    pytrees with a symbolic batch size.
    """
    from ..runtime import codec

    hidden0 = module.initial_state((1,))
    scope = jax.export.SymbolicScope()
    obs_spec = _leaf_specs(sample_obs, scope, "b")

    # multi-platform lowering: the artifact must run wherever it's deployed
    # (the reference's ONNX artifacts are platform-neutral; ours match)
    platforms = ("cpu", "tpu")
    if hidden0 is None:
        fn = lambda obs: module.apply(variables, obs, None)  # noqa: E731
        exported = jax.export.export(jax.jit(fn), platforms=platforms)(obs_spec)
        hidden_host = None
    else:
        fn = lambda obs, hidden: module.apply(variables, obs, hidden)  # noqa: E731
        hidden_spec = _leaf_specs(tree_map(lambda x: np.asarray(x)[0], hidden0), scope, "b")
        exported = jax.export.export(jax.jit(fn), platforms=platforms)(obs_spec, hidden_spec)
        hidden_host = tree_map(np.asarray, hidden0)

    blob = codec.dumps({"mlir": exported.serialize(), "hidden0": hidden_host})
    with open(path, "wb") as f:
        f.write(blob)


class ExportedModel(SingleInferenceMixin):
    """Inference over a serialized artifact; same API as InferenceModel.

    Role of the reference's OnnxModel (evaluation.py:287-353): standalone
    deployment/eval inference without the original model code.
    """

    def __init__(self, path: str):
        from ..runtime import codec

        with open(path, "rb") as f:
            data = codec.loads(f.read())
        self._exported = jax.export.deserialize(bytearray(data["mlir"]))
        self._hidden0 = data["hidden0"]

    def init_hidden(self, batch_dims=()):
        if self._hidden0 is None:
            return None
        # stored with a leading batch axis of 1; strip it for per-sample use
        flat = tree_map(lambda x: x[0], self._hidden0)
        if not batch_dims:
            return flat
        return tree_map(lambda x: np.broadcast_to(x, tuple(batch_dims) + x.shape).copy(), flat)

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        obs = tree_map(jnp.asarray, obs)
        if self._hidden0 is None:
            outputs = self._exported.call(obs)
        else:
            if hidden is None:
                n = jax.tree_util.tree_leaves(obs)[0].shape[0]
                hidden = self.init_hidden((n,))
            outputs = self._exported.call(obs, tree_map(jnp.asarray, hidden))
        return jax.device_get(outputs)
