"""Reusable Flax building blocks for game nets.

TPU-first notes:

* Internally everything is NHWC (the layout XLA's TPU conv emitter
  prefers); environments emit CHW features for parity with the reference,
  so nets transpose once at the stem (``chw_to_nhwc``).
* Torus (wrap-around) convolution is expressed with ``padding='CIRCULAR'``
  — XLA lowers this to a single fused conv, replacing the reference's
  manual concat-pad (handyrl/envs/kaggle/hungry_geese.py:23-35).
* BatchNorm in the reference (e.g. envs/tictactoe.py:26) is replaced by
  GroupNorm: batch-statistics-free, so the whole net is a pure function —
  no mutable state threading through `lax.scan` RNN training loops, and no
  cross-replica batch-stat sync on a mesh.  (Parity note: this changes
  normalization statistics, not the architecture's capacity.)
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import flax.linen as nn


def chw_to_nhwc(x: jnp.ndarray) -> jnp.ndarray:
    """(..., C, H, W) -> (..., H, W, C)."""
    return jnp.moveaxis(x, -3, -1)


def _norm(num_channels: int) -> nn.Module:
    groups = 8 if num_channels % 8 == 0 else 1
    return nn.GroupNorm(num_groups=groups)


class ConvBlock(nn.Module):
    """3x3 conv + (optional) GroupNorm; ReLU is applied by callers."""

    features: int
    kernel: int = 3
    use_norm: bool = True
    circular: bool = False

    @nn.compact
    def __call__(self, x):
        padding = "CIRCULAR" if self.circular else "SAME"
        h = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            padding=padding,
            use_bias=not self.use_norm,
        )(x)
        if self.use_norm:
            h = _norm(self.features)(h)
        return h


class DenseHead(nn.Module):
    """1x1-conv feature mixer + flattening linear head.

    Equivalent role to the reference's Head (envs/tictactoe.py:35-49):
    board features -> per-action logits or scalar value.
    """

    mix_features: int
    outputs: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.mix_features, (1, 1))(x)
        h = nn.leaky_relu(h, 0.1)
        h = h.reshape(*h.shape[:-3], -1)
        return nn.Dense(self.outputs, use_bias=False)(h)


class SpatialHead(nn.Module):
    """conv3x3+GN+relu -> 1x1 conv -> flatten: per-cell action logits.

    Role of the reference's Conv2dHead (envs/geister.py:100-112).
    """

    mix_features: int
    output_features: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.mix_features, (3, 3), padding="SAME", use_bias=False)(x)
        h = nn.relu(_norm(self.mix_features)(h))
        h = nn.Conv(self.output_features, (1, 1), use_bias=False)(h)
        # (H, W, F) -> (F, H, W) flattening so logit index = f*H*W + x*W + y,
        # matching the reference's CHW flatten (envs/geister.py:111).
        h = jnp.moveaxis(h, -1, -3)
        return h.reshape(*h.shape[:-3], -1)


class ScalarHead(nn.Module):
    """1x1 conv+GN+relu -> flatten -> linear scalar head (envs/geister.py:115-127)."""

    mix_features: int
    outputs: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.mix_features, (1, 1), use_bias=False)(x)
        h = nn.relu(_norm(self.mix_features)(h))
        h = h.reshape(*h.shape[:-3], -1)
        return nn.Dense(self.outputs, use_bias=False)(h)


class ConvLSTMCell(nn.Module):
    """Convolutional LSTM cell over NHWC feature maps.

    State is an (h, c) tuple of (..., H, W, C) arrays.  One fused conv
    produces all four gates (cf. reference envs/geister.py:17-57).
    """

    features: int
    kernel: int = 3

    @nn.compact
    def __call__(self, x, state: Tuple[jnp.ndarray, jnp.ndarray]):
        h_prev, c_prev = state
        gates = nn.Conv(4 * self.features, (self.kernel, self.kernel), padding="SAME")(
            jnp.concatenate([x, h_prev], axis=-1)
        )
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        c = nn.sigmoid(f) * c_prev + nn.sigmoid(i) * jnp.tanh(g)
        h = nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)


class DRC(nn.Module):
    """Deep Repeated Convolutional LSTM (arXiv:1901.03559).

    ``num_layers`` stacked ConvLSTM cells applied ``num_repeats`` times per
    timestep; layer i>0 consumes layer i-1's fresh hidden state, layer 0
    consumes the input (cf. reference envs/geister.py:65-97).

    Hidden state is a pair of arrays shaped (*batch, num_layers, H, W, C):
    batch dims lead on every pytree leaf in this framework, so hidden state
    shards / vmaps / stacks exactly like observations.
    """

    num_layers: int
    features: int
    num_repeats: int = 3

    @nn.compact
    def __call__(self, x, hidden):
        hs = [hidden[0][..., i, :, :, :] for i in range(self.num_layers)]
        cs = [hidden[1][..., i, :, :, :] for i in range(self.num_layers)]
        cells = [ConvLSTMCell(self.features, name=f"cell{i}") for i in range(self.num_layers)]
        for _ in range(self.num_repeats):
            for i, cell in enumerate(cells):
                inp = x if i == 0 else hs[i - 1]
                _, (hs[i], cs[i]) = cell(inp, (hs[i], cs[i]))
        new_hidden = (jnp.stack(hs, axis=-4), jnp.stack(cs, axis=-4))
        return hs[-1], new_hidden

    def initial_state(self, batch_dims: Sequence[int], spatial: Tuple[int, int]):
        shape = (*batch_dims, self.num_layers, *spatial, self.features)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
