from .tree import (
    tree_map,
    tree_stack,
    tree_unstack,
    tree_index,
    tree_zeros_like,
    tree_concat,
    softmax,
)

__all__ = [
    "tree_map",
    "tree_stack",
    "tree_unstack",
    "tree_index",
    "tree_zeros_like",
    "tree_concat",
    "softmax",
]
