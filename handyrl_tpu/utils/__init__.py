from .metrics import read_metrics
from .platform import apply_platform_override
from .tree import (
    tree_map,
    tree_stack,
    tree_unstack,
    tree_index,
    tree_zeros_like,
    tree_concat,
    softmax,
)

__all__ = [
    "apply_platform_override",
    "read_metrics",
    "tree_map",
    "tree_stack",
    "tree_unstack",
    "tree_index",
    "tree_zeros_like",
    "tree_concat",
    "softmax",
]
