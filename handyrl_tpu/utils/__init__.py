from .metrics import METRIC_KEY_PREFIXES, METRIC_KEYS, read_metrics
from .platform import apply_platform_override
from .sanitizers import HostSyncSanitizer, RecompileSentinel
from .tree import (
    tree_map,
    tree_stack,
    tree_unstack,
    tree_index,
    tree_zeros_like,
    tree_concat,
    softmax,
)

__all__ = [
    "apply_platform_override",
    "read_metrics",
    "METRIC_KEYS",
    "METRIC_KEY_PREFIXES",
    "HostSyncSanitizer",
    "RecompileSentinel",
    "tree_map",
    "tree_stack",
    "tree_unstack",
    "tree_index",
    "tree_zeros_like",
    "tree_concat",
    "softmax",
]
