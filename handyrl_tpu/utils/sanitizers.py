"""Runtime sanitizers: the dynamic half of the graftlint plane.

Static rules (tools/graftlint) catch what is visible in source; these two
context managers catch what is only visible at runtime, and are cheap
enough for tests and CI to arm around real training windows
(docs/static_analysis.md §Sanitizers):

* ``RecompileSentinel`` — counts REAL XLA compilations (jit cache
  misses) during a window, each attributed to the dispatch site that
  triggered it.  The streaming hot loop's contract is ZERO post-warm-up
  compiles per epoch: one stray shape change (a drifting batch geometry,
  an un-pinned sharding) silently turns a 3 ms update into a 30 s stall,
  which is exactly the class of regression a throughput assertion is too
  noisy to catch on CPU.
* ``HostSyncSanitizer`` — instruments the blocking-transfer entry points
  (``jax.block_until_ready``, ``jax.device_get``, and the
  ``ArrayImpl``-to-host conversions behind ``float()`` / ``.item()`` /
  ``np.asarray``) during a window and reports every hit as a NAMED site
  (file:line:function).  The ``batch_pipeline: device`` / device-replay
  hot paths must record ZERO: PR 6 removed the last per-dispatch host
  sync, and this is the harness that keeps it removed.

Both are nestable-free, thread-aware (events from rollout/pipeline
threads are attributed to their thread), and restore every patched entry
point on exit even when the body raises.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["RecompileSentinel", "HostSyncSanitizer", "SyncEvent", "CompileEvent"]


_JAX_PATH_MARKERS = ("/jax/", "/jaxlib/", "/jax_", "site-packages/jax")
_SELF_MARKERS = ("utils/sanitizers.py",)


def _attribute_site(skip_markers: Sequence[str]) -> Tuple[str, int, str]:
    """Deepest stack frame that is neither jax internals nor this module —
    the user-code site to blame.  Falls back to the deepest frame."""
    stack = traceback.extract_stack()
    for frame in reversed(stack):
        fn = frame.filename.replace("\\", "/")
        if any(m in fn for m in _JAX_PATH_MARKERS):
            continue
        if any(fn.endswith(m) or m in fn for m in _SELF_MARKERS):
            continue
        if any(m in fn for m in skip_markers):
            continue
        if fn.endswith(("threading.py", "contextlib.py")):
            continue
        return (fn, frame.lineno or 0, frame.name)
    last = stack[-1]
    return (last.filename, last.lineno or 0, last.name)


def _short(path: str, keep: int = 3) -> str:
    parts = path.replace("\\", "/").split("/")
    return "/".join(parts[-keep:])


# -- recompile sentinel -------------------------------------------------------


_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


@dataclass
class CompileEvent:
    site: Tuple[str, int, str]
    thread: str
    duration_s: float

    def format(self) -> str:
        f, line, func = self.site
        return f"{_short(f)}:{line} in {func}() [{self.thread}] ({self.duration_s:.3f}s)"


class RecompileSentinel:
    """Context manager asserting no XLA compilation happens in the window.

    Counts ``/jax/core/compile/backend_compile_duration`` monitoring
    events (one per REAL backend compile — jit cache hits emit nothing),
    attributing each to the dispatch site via the listener's synchronous
    stack.  Usage::

        with RecompileSentinel() as sentinel:
            ...run one epoch of the warm hot loop...
        sentinel.assert_no_recompiles("streaming epoch")

    The listener registry is process-global in jax; this class registers
    on ``__enter__`` and unregisters on ``__exit__`` (best effort — jax
    exposes removal as a private helper; when absent the listener stays
    registered but inert, gated by ``self._armed``).
    """

    def __init__(self) -> None:
        self.events: List[CompileEvent] = []
        self._armed = False
        self._lock = threading.Lock()

    # separate method so tests can exercise the listener directly
    def _on_event(self, name: str, duration: float, **kwargs: Any) -> None:
        if not self._armed or name != _COMPILE_EVENT:
            return
        event = CompileEvent(
            site=_attribute_site(()),
            thread=threading.current_thread().name,
            duration_s=float(duration),
        )
        with self._lock:
            self.events.append(event)

    def __enter__(self) -> "RecompileSentinel":
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._on_event)
        self._armed = True
        return self

    def __exit__(self, *exc: Any) -> None:
        self._armed = False
        try:
            from jax._src import monitoring as _mon

            unregister = getattr(
                _mon, "_unregister_event_duration_listener_by_callback", None
            )
            if unregister is not None:
                unregister(self._on_event)
        except Exception:
            pass  # listener stays registered but disarmed

    @property
    def count(self) -> int:
        return len(self.events)

    def report(self) -> str:
        if not self.events:
            return "RecompileSentinel: no compilations in window"
        lines = [f"RecompileSentinel: {len(self.events)} compilation(s) in window:"]
        lines += [f"  - {e.format()}" for e in self.events]
        return "\n".join(lines)

    def assert_no_recompiles(self, context: str = "") -> None:
        if self.events:
            prefix = f"[{context}] " if context else ""
            raise AssertionError(prefix + self.report())


# -- host-sync sanitizer ------------------------------------------------------


# sites where a blocking sync is the documented mechanism, not a leak:
# (path suffix fragment, function name) matched against the IMMEDIATE
# caller of the instrumented entry point
DEFAULT_ALLOWED_SITES: Tuple[Tuple[str, str], ...] = (
    # the CPU backend holds the dispatch locks until outputs are ready —
    # virtual devices share one thunk pool (parallel/mesh.py docstring)
    ("parallel/mesh.py", "dispatch_serialized"),
)


@dataclass
class SyncEvent:
    kind: str                       # block_until_ready | device_get | to_host
    site: Tuple[str, int, str]
    thread: str
    count: int = 1

    def format(self) -> str:
        f, line, func = self.site
        return f"{self.kind} at {_short(f)}:{line} in {func}() [{self.thread}] x{self.count}"


class HostSyncSanitizer:
    """Context manager counting blocking host<->device syncs by named site.

    Instruments, for the duration of the window:

    * ``jax.block_until_ready`` (module attribute — every repo call site
      spells it that way),
    * ``jax.device_get``,
    * ``ArrayImpl._value`` / ``ArrayImpl.__array__`` — the to-host
      conversion behind ``float(x)``, ``x.item()``, and ``np.asarray(x)``
      on device arrays (a single-device CPU array can short-circuit
      through the buffer protocol below Python; the device_get /
      block_until_ready hooks still see the repo's actual call sites).

    Re-entrant inner hits (device_get -> _value) count once.  Events
    whose immediate caller matches ``allow`` are recorded separately in
    ``allowed_events`` — visible in the report, excluded from
    ``assert_clean``.  Usage::

        with HostSyncSanitizer() as sync:
            ...pipeline window on the batch_pipeline: device path...
        sync.assert_clean("device pipeline window")
    """

    def __init__(self, allow: Sequence[Tuple[str, str]] = DEFAULT_ALLOWED_SITES):
        self.allow = tuple(allow)
        self.events: List[SyncEvent] = []
        self.allowed_events: List[SyncEvent] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._saved: List[Tuple[Any, str, Any]] = []

    # -- recording -----------------------------------------------------------

    def _record(self, kind: str) -> None:
        stack = traceback.extract_stack()
        # immediate caller = frame above the wrapper (wrapper is [-2])
        caller = stack[-3] if len(stack) >= 3 else stack[0]
        caller_file = caller.filename.replace("\\", "/")
        allowed = any(
            frag in caller_file and caller.name == func
            for frag, func in self.allow
        )
        site = _attribute_site(())
        event = SyncEvent(kind=kind, site=site,
                          thread=threading.current_thread().name)
        with self._lock:
            bucket = self.allowed_events if allowed else self.events
            for existing in bucket:
                if existing.kind == kind and existing.site == site:
                    existing.count += 1
                    return
            bucket.append(event)

    def _guarded(self, kind: str, orig: Callable) -> Callable:
        def wrapper(*args: Any, **kwargs: Any):
            if getattr(self._tls, "inside", False):
                return orig(*args, **kwargs)
            self._tls.inside = True
            try:
                self._record(kind)
                return orig(*args, **kwargs)
            finally:
                self._tls.inside = False

        wrapper.__name__ = getattr(orig, "__name__", kind)
        return wrapper

    # -- patching ------------------------------------------------------------

    def _patch(self, obj: Any, name: str, kind: str) -> None:
        orig = getattr(obj, name)
        self._saved.append((obj, name, orig))
        if isinstance(orig, property):
            fget = orig.fget
            guarded = self._guarded(kind, fget)
            setattr(obj, name, property(guarded, orig.fset, orig.fdel))
        else:
            setattr(obj, name, self._guarded(kind, orig))

    def __enter__(self) -> "HostSyncSanitizer":
        import jax

        self._patch(jax, "block_until_ready", "block_until_ready")
        self._patch(jax, "device_get", "device_get")
        try:
            from jax._src.array import ArrayImpl

            # _value is the cached to-host conversion float()/.item()/
            # __array__ funnel through on this jax (a property attached to
            # the extension type — patchable from Python)
            if isinstance(ArrayImpl.__dict__.get("_value"), property):
                self._patch(ArrayImpl, "_value", "to_host")
            arr = ArrayImpl.__dict__.get("__array__")
            if callable(arr):
                self._patch(ArrayImpl, "__array__", "to_host")
        except Exception:
            pass  # older/newer jax layout: module-level hooks still armed
        return self

    def __exit__(self, *exc: Any) -> None:
        while self._saved:
            obj, name, orig = self._saved.pop()
            try:
                setattr(obj, name, orig)
            except Exception:
                pass

    # -- reporting -----------------------------------------------------------

    @property
    def count(self) -> int:
        return sum(e.count for e in self.events)

    def report(self) -> str:
        lines: List[str] = []
        if not self.events:
            lines.append("HostSyncSanitizer: no blocking host syncs in window")
        else:
            lines.append(
                f"HostSyncSanitizer: {self.count} blocking host sync(s) "
                f"at {len(self.events)} site(s):"
            )
            lines += [f"  - {e.format()}" for e in self.events]
        if self.allowed_events:
            lines.append(
                f"  (allowed: {sum(e.count for e in self.allowed_events)} "
                f"at {len(self.allowed_events)} allowlisted site(s))"
            )
        return "\n".join(lines)

    def assert_clean(self, context: str = "") -> None:
        if self.events:
            prefix = f"[{context}] " if context else ""
            raise AssertionError(prefix + self.report())
