"""In-process platform override, shared by every entry point.

The JAX_PLATFORMS env var alone is not reliable on hosts whose site
customization imports jax at interpreter startup and pins a platform via
jax.config (config beats env — e.g. the axon sitecustomize pins
``jax_platforms=axon`` in EVERY process).  HANDYRL_PLATFORM re-pins it
here, before the first computation: ``HANDYRL_PLATFORM=cpu`` for a
virtual CPU mesh run of the CLI, bench, or any tools/ script.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    """Honor ``HANDYRL_PLATFORM`` (any platform name jax accepts); no-op
    when unset.  Must run before the first jax computation — importing
    jax is fine, initializing a backend is not."""
    plat = os.environ.get("HANDYRL_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
