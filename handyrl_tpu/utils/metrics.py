"""Crash-tolerant metrics.jsonl reading.

The learner appends one JSON record per epoch with a flush+fsync per
record (runtime/learner.py:_write_metrics), so a SIGKILL / power cut mid-
append leaves at most ONE half-written line — and only at the tail.  Every
reader of metrics.jsonl (the plot scripts via scripts/_logparse.py, the
soak/ablation tools) goes through ``read_metrics`` so that one truncated
final line is tolerated instead of breaking downstream parsing, while a
malformed line anywhere ELSE still raises: mid-file corruption is a real
integrity problem, not an artifact of the append protocol.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

# The metrics.jsonl KEY REGISTRY — the tolerance contract between the
# writers (Learner.update -> _write_metrics, Trainer.stats) and every
# reader (scripts/_logparse.py + the plot scripts, tools/ablate_*).
# graftlint rule MET006 statically checks both sides against this set:
# a writer emitting an unregistered key, or a consumer reading one, is a
# lint finding — so "will every reader tolerate this record" is reviewed
# HERE, once, instead of per call site.  Readers must treat every key as
# optional (records predate keys; null values are legal — win_rate /
# generation_mean are explicitly null on empty epochs).
METRIC_KEYS = frozenset({
    # identity / cadence
    "epoch", "steps", "episodes", "episodes_per_sec", "updates_per_sec",
    # evaluation / generation books
    "win_rate", "eval_games", "generation_mean", "generation_std",
    # trainer loop
    "loss", "train_steps_per_sec", "input_wait_frac", "input_wait_warmup_s",
    "mfu", "device_mean_episode_len",
    # live pipeline / plane topology
    "pipeline", "plane",
    # serving plane (handyrl_tpu/serving): the learner writes only
    # serve_snapshot_substituted (LocalModelServer fallback count); the
    # rest are the ServingServer's periodic health records — exact keys,
    # not a prefix family, so every new serving stat is reviewed here
    "serve_snapshot_substituted", "serve_requests", "serve_replies",
    "serve_shed", "serve_deadline_miss", "serve_batches", "serve_depth",
    "serve_qps", "serve_p50_ms", "serve_p99_ms", "serve_hot_swaps",
    "serve_models", "serve_connections", "serve_errors",
    # server-resident session cache (handyrl_tpu/fleet/sessions.py),
    # folded into the ServingServer's periodic record: residency gauges
    # plus cumulative lifecycle/eviction/restore/affinity-miss counters —
    # exact keys, like serve_*, so every new session stat is reviewed here
    "session_resident", "session_spilled", "session_opened",
    "session_closed", "session_evictions", "session_restored",
    "session_affinity_miss", "session_spill_drops",
    # migration counters (docs/serving.md §Elastic fleet): sessions this
    # cache handed to / adopted from another replica on a planned retire
    # or preemption drain — the zero-loss path's own books
    "session_migrated_in", "session_migrated_out",
    # fleet front-end (handyrl_tpu/fleet/router_tier.py): the session-
    # affinity router's periodic health records — proxy volume, replica
    # liveness (fleet_replica_lost counts loss EVENTS; fleet_replicas_live
    # is the current gauge, fleet_replicas_warming the connected-but-not-
    # admitted subset), sessions routed, and orchestrated fleet-wide
    # hot-swaps
    "fleet_requests", "fleet_replies", "fleet_errors", "fleet_qps",
    "fleet_replicas", "fleet_replicas_live", "fleet_replicas_warming",
    "fleet_replica_lost", "fleet_sessions", "fleet_hot_swaps",
    # elastic fleet: autoscale actions, zero-loss migrations (events /
    # sessions moved / last handoff wall ms), bounded stateless failover
    # retries, and preemption drains handled
    "fleet_scale_ups", "fleet_scale_downs", "fleet_migrations",
    "fleet_sessions_migrated", "fleet_migration_ms",
    "fleet_failover_retries", "fleet_preempt_drains",
    # transient-fault retries the stats poll absorbed before anything was
    # declared lost (utils/retry.py) — a rising count with zero
    # fleet_replica_lost is the retry plane doing its job
    "fleet_poll_retries",
    # data flywheel, serving side (handyrl_tpu/flywheel/harvest.py folded
    # into the ServingServer's periodic record): per-session episode
    # assembly volume and the LOUD drop counters (malformed = protocol
    # breakage, truncated = abandoned/TTL'd/shed games), plus the pull
    # drain the learner ingest loop drives
    "flywheel_episodes", "flywheel_open", "flywheel_queued",
    "flywheel_dropped_malformed", "flywheel_dropped_truncated",
    "flywheel_pulled",
    # data flywheel, quality plane (handyrl_tpu/flywheel/quality.py):
    # gated promotions / gate refusals / sentinel demotions (cumulative),
    # live games booked, and the current candidate/incumbent epoch gauges
    # (null when none is staged / retained)
    "quality_promotions", "quality_gate_failures", "quality_demotions",
    "quality_games", "quality_candidate", "quality_incumbent",
    # data flywheel, learner side (handyrl_tpu/flywheel/ingest.py folded
    # into the per-epoch record): episodes fed into the EpisodeStore,
    # staleness/malformed drops at ingest, and quality-signal rollbacks
    # applied by the trainer
    "flywheel_ingested", "flywheel_ingest_stale",
    "flywheel_ingest_malformed", "flywheel_rollbacks",
    # league plane (handyrl_tpu/league): per-epoch population health from
    # LeagueLearner._epoch_hook — exact keys, like serve_*, so every new
    # league stat is reviewed here.  league_matches/forfeits/promotions
    # are cumulative; league_candidate_wp and league_elo_spread are null
    # until the respective books have games
    "league_population", "league_pool", "league_matches", "league_forfeits",
    "league_payoff_coverage", "league_candidate_wp", "league_elo_spread",
    "league_promotions",
    # low-precision fast path (models/quantize.py, docs/performance.md
    # §Low-precision): the serving plane's periodic record pins the
    # engine weight dtype and the publish-time MEASURED calibration
    # deviation — exact keys, like serve_*, so every new lowprec stat is
    # reviewed here
    "lowprec_weight_dtype", "lowprec_calib_batches",
    "lowprec_calib_max_dev", "lowprec_calib_mean_dev",
    # multi-process learner plane (parallel/distributed.py + health.py):
    # dist_processes is the run's process count; the rest are cumulative
    # cross-host health events — heartbeat misses observed, collective-
    # timeout watchdog aborts, and peer/coordinator-loss drains.  Written
    # by the coordinator's per-epoch record and, on a host fault, by the
    # final pre-exit drain record (runtime/learner.py)
    "dist_processes", "dist_heartbeat_misses", "dist_collective_timeouts",
    "dist_peer_loss_drains",
    # pod-slice actor tier (runtime/plane.py PlaneGateway): live producer
    # count at the epoch boundary plus cumulative disconnect-after-hello
    # losses (each one a degrade the surviving hosts absorbed — never a
    # wedge, by the fault matrix's asymmetry)
    "dist_actor_hosts", "dist_actor_host_losses",
    # observability plane (docs/observability.md): every record carries
    # both clocks from the single _write_metrics seam — ts (wall, absolute
    # cross-host alignment) and t_mono (monotonic, NTP-step-immune rate
    # math); readers prefer them over the record index for time axes
    "ts", "t_mono",
})
# key families written from the *_KEYS tuples (trainer/learner) and the
# per-epoch plane-health diffs; one prefix registers the family.
# rank_*: the coordinator's fold of per-rank metric snapshots relayed
# over health-plane heartbeats (HostHealthPlane.rank_aggregates — min/
# max/mean of epoch, steps, step rate, input_wait_frac, plus report
# staleness); trace_*: cumulative tracer health (spans recorded, ring
# drops) from utils/trace.trace_stats; quality_wp*: the flywheel quality
# ledger's per-snapshot live win-point family (quality_wp{epoch} — one
# gauge per epoch with reported games, from QualityLedger.snapshot)
METRIC_KEY_PREFIXES = (
    "pipe_", "plane_", "sentinel_", "rank_", "trace_", "quality_wp",
)


def append_metrics_record(path: str, record: Dict[str, Any]) -> None:
    """One flushed+fsynced appended line — the Learner._write_metrics
    discipline shared by every periodic metrics writer (serving server,
    fleet router): a kill mid-append leaves at most ONE truncated line,
    and only at the tail, which ``read_metrics`` tolerates.  Stamps the
    dual-clock seam (ts wall / t_mono monotonic) like the learner's
    records so readers align cross-host and rate-math safely."""
    import os
    import time

    record.setdefault("ts", round(time.time(), 6))
    record.setdefault("t_mono", round(time.monotonic(), 6))
    line = json.dumps(record, default=float) + "\n"
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:
            pass


def read_metrics(path: str, strict: bool = False) -> List[Dict[str, Any]]:
    """Parse a metrics.jsonl into a list of records.

    A truncated FINAL line (the one write a kill can interrupt) is skipped
    with a stderr note unless ``strict``; invalid JSON on any earlier line
    raises ``ValueError`` regardless.
    """
    with open(path) as f:
        lines = f.readlines()
    records: List[Dict[str, Any]] = []
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == last and not strict:
                print(
                    f"[handyrl_tpu] {path}: dropping truncated final line "
                    "(half-written record from a killed run)",
                    file=sys.stderr,
                )
                break
            raise
    return records
