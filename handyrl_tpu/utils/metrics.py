"""Crash-tolerant metrics.jsonl reading.

The learner appends one JSON record per epoch with a flush+fsync per
record (runtime/learner.py:_write_metrics), so a SIGKILL / power cut mid-
append leaves at most ONE half-written line — and only at the tail.  Every
reader of metrics.jsonl (the plot scripts via scripts/_logparse.py, the
soak/ablation tools) goes through ``read_metrics`` so that one truncated
final line is tolerated instead of breaking downstream parsing, while a
malformed line anywhere ELSE still raises: mid-file corruption is a real
integrity problem, not an artifact of the append protocol.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List


def read_metrics(path: str, strict: bool = False) -> List[Dict[str, Any]]:
    """Parse a metrics.jsonl into a list of records.

    A truncated FINAL line (the one write a kill can interrupt) is skipped
    with a stderr note unless ``strict``; invalid JSON on any earlier line
    raises ``ValueError`` regardless.
    """
    with open(path) as f:
        lines = f.readlines()
    records: List[Dict[str, Any]] = []
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == last and not strict:
                print(
                    f"[handyrl_tpu] {path}: dropping truncated final line "
                    "(half-written record from a killed run)",
                    file=sys.stderr,
                )
                break
            raise
    return records
