"""Structured hot-path span tracing: the run-wide observability plane.

Five planes (shm batchers, split actor/learner meshes, multi-host cadence,
serving, league) each report per-epoch COUNTERS into metrics.jsonl, but
counters cannot say *where time goes inside an epoch* — which plane is the
bottleneck on real chips is exactly the question the Podracer/Sebulba
disaggregated design keeps asking.  This module answers it with spans::

    from handyrl_tpu.utils.trace import trace_span

    with trace_span("train_step", plane="learner"):
        state, metrics = ctx.train_step(state, batch, lr)

Design constraints, in order:

1. **Off by default and provably free.**  ``trace_span`` with tracing
   disabled returns one shared no-op context manager — a single module
   attribute check, no allocation, no jax import, no syscalls.  The hot
   path is bit-identical with ``trace: false`` and the sanitizer suite
   pins zero added host syncs / recompiles (tests/test_trace.py).
2. **Lock-cheap, never blocking.**  Enabled spans append one small dict
   to a bounded in-process ring under a lock held for the append only; a
   full ring DROPS the span and counts it (``dropped``) — tracing load
   must never stall a dispatch.  A background flusher drains the ring to
   ``trace.jsonl``.
3. **Crash-tolerant output.**  One JSON line per span, batches written in
   a single ``write`` + flush (+ best-effort fsync), so a SIGKILL leaves
   at most one truncated FINAL line — the same tail discipline as
   metrics.jsonl, tolerated by ``read_trace`` exactly like
   ``utils.metrics.read_metrics``.
4. **Device-profile correlation.**  Each span also enters a
   ``jax.profiler.TraceAnnotation`` (when jax is importable and
   ``trace.annotate_device`` is true), so the host-side spans land inside
   XLA device profiles captured with ``profile_dir``.

``scripts/trace_export.py`` converts one or more trace.jsonl files (one
per rank in a multi-process run) into Chrome trace-event JSON that opens
directly in ``chrome://tracing`` / Perfetto.  Span catalog and workflow:
docs/observability.md.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "Tracer",
    "configure",
    "shutdown",
    "enabled",
    "trace_span",
    "trace_event",
    "trace_stats",
    "read_trace",
    "META_NAME",
]

TRACE_SCHEMA_VERSION = 1
# the first line of every trace.jsonl: wall-clock <-> monotonic anchor so
# the exporter can align ranks whose monotonic epochs differ (each process
# — and each HOST — has its own)
META_NAME = "__trace_meta__"


class _NullSpan:
    """The disabled-path context manager: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_attrs", "_ts", "_t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._ann = None

    def __enter__(self) -> "_Span":
        tracer = self._tracer
        ann_cls = tracer._annotation
        if ann_cls is not None:
            # enter the XLA annotation FIRST so the device profile's span
            # brackets the same wall window the host span records
            try:
                ann = ann_cls(self._name)
                ann.__enter__()
                self._ann = ann
            except Exception:
                tracer._annotation = None  # mis-matched jax: disarm once
        self._ts = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc: Any) -> bool:
        dur = time.monotonic() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._tracer._record(self._name, self._ts, self._t0, dur, self._attrs)
        return False


class Tracer:
    """In-process span recorder behind the module-level ``trace_span``.

    One instance per process (the module singleton); ``configure`` is
    called once by the entry points (Learner, ServingServer, tests) with
    ``train_args.trace``.  All public state is documented: ``spans`` /
    ``dropped`` are cumulative counters surfaced as ``trace_*`` metrics.
    """

    def __init__(self) -> None:
        self.enabled = False
        self.path: Optional[str] = None
        self.ring_size = 4096
        self.flush_interval = 0.5
        self.rank = 0
        self.spans = 0
        self.dropped = 0
        self._annotation = None      # jax.profiler.TraceAnnotation when armed
        self._ring: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        self._file = None
        self._atexit_registered = False

    # -- lifecycle -----------------------------------------------------------

    def configure(self, cfg: Optional[Dict[str, Any]], rank: int = 0) -> bool:
        """Arm (or disarm) tracing from a ``train_args.trace`` dict.

        Returns True when tracing came up enabled.  Raises ``ValueError``
        naming the knob when the trace path is not writable — a run asked
        to trace must fail at startup, not silently record nothing.  In a
        multi-process run every rank writes its OWN file: rank N > 0
        derives ``trace.jsonl`` -> ``trace.rankN.jsonl``.
        """
        self.shutdown()  # re-configuration replaces the previous plane
        cfg = dict(cfg or {})
        if not cfg.get("enabled"):
            return False
        path = str(cfg.get("path") or "trace.jsonl")
        rank = int(rank)
        if rank > 0:
            root, ext = os.path.splitext(path)
            path = f"{root}.rank{rank}{ext or '.jsonl'}"
        try:
            f = open(path, "a")
        except OSError as exc:
            raise ValueError(
                f"train_args.trace.path={path!r} is not writable "
                f"({type(exc).__name__}: {exc}) — tracing was requested, so "
                "an unwritable sink is a startup error, not a silent no-op"
            ) from exc
        self._file = f
        self.path = path
        self.rank = rank
        self.ring_size = max(1, int(cfg.get("ring_size", 4096)))
        self.flush_interval = max(0.01, float(cfg.get("flush_interval", 0.5)))
        self.spans = 0
        self.dropped = 0
        self._annotation = None
        if cfg.get("annotate_device", True):
            try:
                import jax.profiler

                self._annotation = jax.profiler.TraceAnnotation
            except Exception:
                self._annotation = None  # jax-free process: host spans only
        # the wall<->monotonic anchor rides the file, not the ring: it must
        # be the first line even if the ring later overflows
        meta = {
            "name": META_NAME,
            "version": TRACE_SCHEMA_VERSION,
            "ts": time.time(),
            "t_mono": time.monotonic(),
            "rank": self.rank,
            "pid": os.getpid(),
        }
        f.write(json.dumps(meta) + "\n")
        f.flush()
        self._stop = threading.Event()
        self.enabled = True
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True, name="trace-flusher"
        )
        self._flusher.start()
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self.shutdown)
        return True

    def shutdown(self) -> None:
        """Disarm and drain: stop the flusher, flush the ring tail, close
        the file.  Safe to call repeatedly (atexit + explicit callers)."""
        if not self.enabled and self._file is None:
            return
        self.enabled = False
        self._stop.set()
        flusher, self._flusher = self._flusher, None
        if flusher is not None and flusher is not threading.current_thread():
            flusher.join(timeout=2.0)
        self.flush()
        f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # -- recording -----------------------------------------------------------

    def _record(self, name: str, ts: float, t0: float, dur: float,
                attrs: Optional[Dict[str, Any]]) -> None:
        rec: Dict[str, Any] = {
            "name": name,
            "ts": round(ts, 6),
            "t_mono": round(t0, 6),
            "dur_s": round(dur, 9),
            "thread": threading.current_thread().name,
            "rank": self.rank,
        }
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            if len(self._ring) >= self.ring_size:
                # NEVER block a hot path on the flusher: drop + count
                self.dropped += 1
                return
            self._ring.append(rec)
            self.spans += 1

    def flush(self) -> None:
        """Drain the ring to disk: one write() for the whole batch (a kill
        mid-write truncates only the final line — the metrics.jsonl tail
        discipline), flushed, fsync best-effort."""
        with self._lock:
            if not self._ring:
                return
            batch, self._ring = self._ring, []
        f = self._file
        if f is None:
            return
        try:
            f.write("".join(json.dumps(r, default=float) + "\n" for r in batch))
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
        except (OSError, ValueError):
            pass  # a torn-down sink must not kill the instrumented thread

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()


_TRACER = Tracer()


def configure(cfg: Optional[Dict[str, Any]], rank: int = 0) -> bool:
    return _TRACER.configure(cfg, rank)


def shutdown() -> None:
    _TRACER.shutdown()


def enabled() -> bool:
    return _TRACER.enabled


def current_path() -> Optional[str]:
    """The armed tracer's sink path (rank suffix applied), or None."""
    return _TRACER.path if _TRACER.enabled else None


def trace_span(name: str, **attrs: Any):
    """Span context manager around a hot-path section.

    Disabled (the default): returns the shared no-op instance — the whole
    cost is this attribute check.  Enabled: records name, wall + monotonic
    start, duration, thread and rank into the ring, and brackets the body
    in a ``jax.profiler.TraceAnnotation`` so it shows inside XLA device
    profiles.  Keyword attrs must be cheap constants (they are evaluated
    at the call site either way)."""
    if not _TRACER.enabled:
        return _NULL_SPAN
    return _Span(_TRACER, name, attrs or None)


def trace_event(name: str, dur_s: float, t0: Optional[float] = None,
                **attrs: Any) -> None:
    """Record an already-measured duration as a span (for seams that time
    themselves anyway, and for async lifecycles like a serving request
    where enter/exit happen on different threads).  ``t0`` is the span's
    start on ``time.monotonic()``; omitted, it is derived as now - dur."""
    tracer = _TRACER
    if not tracer.enabled:
        return
    now = time.monotonic()
    start = now - dur_s if t0 is None else t0
    tracer._record(name, time.time() - (now - start), start, dur_s, attrs or None)


def trace_stats() -> Dict[str, int]:
    """Cumulative tracer health counters (the ``trace_*`` metrics keys)."""
    return {"trace_spans": _TRACER.spans, "trace_dropped": _TRACER.dropped}


def read_trace(path: str, strict: bool = False) -> List[Dict[str, Any]]:
    """Parse a trace.jsonl, tolerating exactly one truncated FINAL line
    (the write a kill can interrupt) unless ``strict``; invalid JSON on
    any earlier line raises — mid-file corruption is a real integrity
    problem, not an artifact of the append protocol."""
    with open(path) as f:
        lines = f.readlines()
    records: List[Dict[str, Any]] = []
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == last and not strict:
                print(
                    f"[handyrl_tpu] {path}: dropping truncated final trace "
                    "line (half-written record from a killed run)",
                    file=sys.stderr,
                )
                break
            raise
    return records
