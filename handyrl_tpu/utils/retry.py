"""Bounded retry with exponential backoff for transient transport faults.

One EINTR/ECONNRESET on a control-plane call (the actor host's record
ship, the fleet router's stats poll) must not cost an exit-75 or a
``replica_lost``: those are the responses to a PEER being gone, not to a
single flaky syscall.  ``retry_call`` is the shared discipline — bounded
attempts, exponential backoff, an ``on_retry`` hook for callers that
must re-establish state (reconnect a client) between attempts — and it
is deliberately injectable (``sleep``) so the retry schedule is pinned
socket-free in tests.

Only the listed ``retry_on`` exception types are retried; anything else
propagates immediately (a protocol error is not transient).  The final
failing exception propagates unchanged, so callers' existing peer-lost
handling (announce_fault + exit 75, ``_mark_lost``) keeps its meaning:
it now fires only after the bounded budget is spent.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type, TypeVar

__all__ = ["retry_call"]

T = TypeVar("T")


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (ConnectionError, OSError, TimeoutError),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn`` with up to ``attempts`` RETRIES after the first try
    (``attempts=0`` means one try, no retry).  Backoff before retry ``i``
    (0-based) is ``min(base_delay * factor**i, max_delay)``.

    ``on_retry(i, exc)`` runs after the backoff sleep and before the next
    attempt — the reconnect seam.  An exception it raises propagates (a
    failed reconnect IS the peer being gone, not a transient)."""
    attempts = max(0, int(attempts))
    for i in range(attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            if i >= attempts:
                raise
            sleep(min(max_delay, base_delay * (factor ** i)))
            if on_retry is not None:
                on_retry(i, exc)
    raise AssertionError("unreachable")  # pragma: no cover
