"""Pytree helpers for nested observation / hidden-state structures.

The reference implements its own recursive mappers (``map_r``/``bimap_r``/
``trimap_r``/``rotate``, handyrl/util.py:7-63) because torch has no pytree
story.  JAX does: everything here is a thin veneer over ``jax.tree`` so the
same helpers work on host-side numpy structures and on traced jax arrays.

``tree_stack`` replaces the reference's double-``rotate`` batching idiom
(handyrl/train.py:77-78): instead of transposing nested python lists, we
stack N structurally-identical pytrees leaf-wise into one pytree of
batched arrays.
"""

from __future__ import annotations

import jax
import numpy as np


def tree_map(fn, tree, *rest):
    """Map ``fn`` over one or more pytrees (None treated as a leaf)."""
    return jax.tree.map(fn, tree, *rest, is_leaf=lambda x: x is None)


def tree_stack(trees, axis=0):
    """Stack a sequence of structurally-identical pytrees leaf-wise.

    [{'a': (3,)}, {'a': (3,)}] -> {'a': (2, 3)}
    """
    trees = list(trees)
    return jax.tree.map(lambda *leaves: np.stack(leaves, axis=axis), *trees)


def tree_unstack(tree, axis=0):
    """Inverse of tree_stack: one pytree of batched arrays -> list of pytrees."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return []
    n = leaves[0].shape[axis]
    out = []
    for i in range(n):
        out.append(jax.tree.unflatten(treedef, [np.take(l, i, axis=axis) for l in leaves]))
    return out


def tree_index(tree, idx):
    """Index every leaf of a pytree along axis 0."""
    return jax.tree.map(lambda x: x[idx], tree)


def tree_zeros_like(tree):
    return jax.tree.map(lambda x: np.zeros_like(x), tree)


def tree_concat(trees, axis=0):
    trees = list(trees)
    return jax.tree.map(lambda *leaves: np.concatenate(leaves, axis=axis), *trees)


def softmax(x):
    """Numerically stable softmax over the last axis (numpy, host-side)."""
    x = np.asarray(x, dtype=np.float32)
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)
