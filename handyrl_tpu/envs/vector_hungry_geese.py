"""Vectorized Hungry Geese as pure jnp state transitions (device-resident).

The host env (envs/hungry_geese.py) is the canonical rules implementation;
this module expresses the SAME rules as batched, branch-free array ops so
whole populations of 4-goose games live and step on the accelerator — the
substrate for streaming on-device self-play of the north-star env
(runtime/device_rollout.py:StreamingDeviceRollout).  The reference reaches
this game only through host-side kaggle_environments
(reference hungry_geese.py:67), one process per actor; here one jit call
steps B games x 4 geese and runs GeeseNet on all of them at once.

Rules parity with the host env is enforced lock-step by
tests/test_device_rollout.py::TestVectorGeeseParity: every transition
(movement, reversal/self-collision/starvation deaths, hunger, food growth,
cross-goose head collisions, rank credit, episode end) is compared against
the host implementation with the device's food spawns injected into the
host, for hundreds of games.

State (per lane, batch-leading):
    cells     (B, P, MAXLEN) int32  circular body buffer; position
                                    (head_ptr + i) % MAXLEN = i-th cell
                                    from the head, valid for i < length
    head_ptr  (B, P) int32
    length    (B, P) int32          0 for dead geese
    occ       (B, P, C) int8        per-goose body occupancy (maintained
                                    incrementally; bodies never self-overlap)
    active    (B, P) bool
    last_action (B, P) int32        -1 before the first move (host: {})
    prev_head (B, P) int32          -1 when absent
    rank      (B, P) int32          (steps survived + 1) * 100 + length
    food      (B, C) int8           food occupancy mask
    step      (B,) int32            host step_count (completed steps)
    done      (B,) bool             game over; lane awaits reset

All transitions are total functions: stepping a finished lane is a no-op,
so a lax.scan can run lanes of different phases together (XLA-static
control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .hungry_geese import (
    COLS,
    HUNGER_RATE,
    MAX_STEPS,
    MIN_FOOD,
    NUM_AGENTS,
    NUM_CELLS,
    RANK_SCALE,
    ROWS,
    _MOVES,
)

MAXLEN = NUM_CELLS  # a goose can at most fill the board

# TRANS[cell, action] -> destination cell on the torus (host _translate)
_trans = np.zeros((NUM_CELLS, 4), np.int32)
for _c in range(NUM_CELLS):
    _r, _cc = divmod(_c, COLS)
    for _a, (_dr, _dc) in enumerate(_MOVES):
        _trans[_c, _a] = ((_r + _dr) % ROWS) * COLS + (_cc + _dc) % COLS
TRANS = jnp.asarray(_trans)
OPPOSITE = jnp.asarray([1, 0, 3, 2], jnp.int32)

# DIST[a, b] -> torus manhattan distance (host _torus_dist)
_dist = np.zeros((NUM_CELLS, NUM_CELLS), np.int32)
for _a in range(NUM_CELLS):
    _ar, _ac = divmod(_a, COLS)
    for _b in range(NUM_CELLS):
        _br, _bc = divmod(_b, COLS)
        _dist[_a, _b] = min((_ar - _br) % ROWS, (_br - _ar) % ROWS) + min(
            (_ac - _bc) % COLS, (_bc - _ac) % COLS
        )
DIST = jnp.asarray(_dist)


def _onehot_cell(cell):
    """one_hot over board cells; -1 (absent) maps to all zeros."""
    return jax.nn.one_hot(cell, NUM_CELLS, dtype=jnp.int8)


class VectorHungryGeese:
    """Stateless namespace of batched transition functions.

    ``simultaneous = True``: all active players act every step (the
    device-rollout driver dispatches on this, in contrast to
    VectorTicTacToe's strict turn alternation).
    """

    num_actions = 4
    num_players = NUM_AGENTS
    max_steps = MAX_STEPS
    simultaneous = True
    board_shape = (ROWS, COLS)

    # -- lane (re)initialization -------------------------------------------

    @staticmethod
    def init(n_lanes: int, key):
        """Fresh games: 4 goose spawns + MIN_FOOD food on distinct cells,
        uniformly (host reset: random.sample of NUM_AGENTS+MIN_FOOD cells).
        Gumbel top-k over equal logits == uniform ordered sample without
        replacement."""
        u = jax.random.uniform(key, (n_lanes, NUM_CELLS))
        _, picks = jax.lax.top_k(u, NUM_AGENTS + MIN_FOOD)  # (B, 6) distinct
        spawns = picks[:, :NUM_AGENTS]                      # (B, P)
        food_cells = picks[:, NUM_AGENTS:]                  # (B, MIN_FOOD)

        B = n_lanes
        cells = jnp.zeros((B, NUM_AGENTS, MAXLEN), jnp.int32)
        cells = cells.at[:, :, 0].set(spawns)
        occ = _onehot_cell(spawns)                          # (B, P, C)
        food = _onehot_cell(food_cells).sum(axis=1).astype(jnp.int8)
        return {
            "cells": cells,
            "head_ptr": jnp.zeros((B, NUM_AGENTS), jnp.int32),
            "length": jnp.ones((B, NUM_AGENTS), jnp.int32),
            "occ": occ,
            "active": jnp.ones((B, NUM_AGENTS), bool),
            "last_action": jnp.full((B, NUM_AGENTS), -1, jnp.int32),
            "prev_head": jnp.full((B, NUM_AGENTS), -1, jnp.int32),
            "rank": jnp.full((B, NUM_AGENTS), RANK_SCALE + 1, jnp.int32),
            "food": food,
            "step": jnp.zeros((B,), jnp.int32),
            "done": jnp.zeros((B,), bool),
        }

    @staticmethod
    def reset_done(state, key):
        """Re-init every lane whose game has finished (streaming auto-reset:
        the scan never wastes iterations on dead lanes)."""
        from .vector_common import reset_where_done

        fresh = VectorHungryGeese.init(state["done"].shape[0], key)
        return reset_where_done(fresh, state)

    # -- views --------------------------------------------------------------

    @staticmethod
    def head_cell(state):
        """(B, P) current head cell, -1 for empty geese."""
        head = jnp.take_along_axis(
            state["cells"], state["head_ptr"][..., None], axis=-1
        )[..., 0]
        return jnp.where(state["length"] > 0, head, -1)

    @staticmethod
    def tail_cell(state):
        """(B, P) current tail-tip cell, -1 for empty geese."""
        idx = (state["head_ptr"] + state["length"] - 1) % MAXLEN
        tail = jnp.take_along_axis(state["cells"], idx[..., None], axis=-1)[..., 0]
        return jnp.where(state["length"] > 0, tail, -1)

    @staticmethod
    def observation(state):
        """(B, P, 17, 7, 11) float32 — the host env's 17 planes for every
        player: head/tail/body/prev-head per goose with the goose axis
        rotated so the viewing player is channel 0, plus food
        (host observation(), envs/hungry_geese.py:242-256)."""
        heads = _onehot_cell(VectorHungryGeese.head_cell(state)).astype(jnp.float32)
        tails = _onehot_cell(VectorHungryGeese.tail_cell(state)).astype(jnp.float32)
        body = state["occ"].astype(jnp.float32)
        prev = _onehot_cell(state["prev_head"]).astype(jnp.float32)
        food = state["food"].astype(jnp.float32)[:, None, :]  # (B, 1, C)

        views = []
        for p in range(NUM_AGENTS):
            planes = jnp.concatenate(
                [
                    jnp.roll(heads, -p, axis=1),
                    jnp.roll(tails, -p, axis=1),
                    jnp.roll(body, -p, axis=1),
                    jnp.roll(prev, -p, axis=1),
                    food,
                ],
                axis=1,
            )  # (B, 17, C)
            views.append(planes)
        obs = jnp.stack(views, axis=1)  # (B, P, 17, C)
        return obs.reshape(obs.shape[:3] + (ROWS, COLS))

    # -- transition ---------------------------------------------------------

    @staticmethod
    def step(state, actions, key):
        """Play ``actions`` (B, P) int32 for every active goose; finished
        lanes pass through unchanged.  Mirrors host step()
        (envs/hungry_geese.py:92-142) phase for phase, including the
        SEQUENTIAL food semantics: when several geese reach the same food,
        only the lowest-indexed one eats (the host's per-goose loop removes
        the food first) — the losers pop their tails, which a bystander
        colliding with such a tail cell can observe."""
        tg = state["step"] + 1                                   # (B,)
        active = state["active"]                                 # (B, P)
        head0 = VectorHungryGeese.head_cell(state)               # (B, P)
        new_prev_head = jnp.where(state["length"] > 0, head0, -1)

        # phase 1: reversal deaths (into own neck, host:103-104)
        reversal = (
            active
            & (state["last_action"] >= 0)
            & (actions == OPPOSITE[jnp.clip(state["last_action"], 0, 3)])
        )
        movers = active & ~reversal

        # phase 2: movement + food + self-collision (host:106-113)
        new_head = TRANS[jnp.clip(head0, 0, NUM_CELLS - 1), jnp.clip(actions, 0, 3)]
        eat = movers & (jnp.take_along_axis(state["food"], new_head, axis=1) > 0)
        # contested food goes to the lowest-indexed goose only (host
        # processes geese in order and removes eaten food mid-loop): a
        # goose loses its claim if any lower-indexed mover eats the same
        # cell this step
        same_cell = (new_head[:, :, None] == new_head[:, None, :]) & eat[:, :, None] & eat[:, None, :]
        lower = jnp.tril(jnp.ones((NUM_AGENTS, NUM_AGENTS), bool), k=-1)  # q < p
        eat = eat & ~(same_cell & lower[None]).any(axis=2)
        pop = movers & ~eat
        tail0 = VectorHungryGeese.tail_cell(state)
        occ = state["occ"] - _onehot_cell(tail0) * pop[..., None].astype(jnp.int8)
        length = state["length"] - pop

        self_col = movers & (
            jnp.take_along_axis(occ, new_head[..., None], axis=-1)[..., 0] > 0
        )
        insert = movers & ~self_col
        head_ptr = jnp.where(insert, (state["head_ptr"] - 1) % MAXLEN, state["head_ptr"])
        slot = jax.nn.one_hot(head_ptr, MAXLEN, dtype=bool) & insert[..., None]
        cells = jnp.where(slot, new_head[..., None], state["cells"])
        occ = occ + _onehot_cell(new_head) * insert[..., None].astype(jnp.int8)
        length = length + insert

        # phase 3: hunger every HUNGER_RATE-th step, after the move
        # (host:115-119); starving to zero kills
        hunger = insert & (tg % HUNGER_RATE == 0)[:, None]
        tail1_idx = (head_ptr + length - 1) % MAXLEN
        tail1 = jnp.take_along_axis(cells, tail1_idx[..., None], axis=-1)[..., 0]
        occ = occ - _onehot_cell(tail1) * hunger[..., None].astype(jnp.int8)
        length = length - hunger
        starve = hunger & (length == 0)

        alive = active & ~(reversal | self_col | starve)
        occ = occ * alive[..., None].astype(jnp.int8)
        length = length * alive

        # phase 4: cross-goose collisions — any head on a cell covered by
        # >1 goose cells dies; dead bodies are already off the board
        # (host:121-128)
        total_occ = occ.sum(axis=1)                              # (B, C)
        collide = alive & (
            jnp.take_along_axis(total_occ, new_head, axis=1) > 1
        )
        alive = alive & ~collide
        occ = occ * alive[..., None].astype(jnp.int8)
        length = length * alive

        # food eaten this step is gone even if the eater then died (host:108)
        eaten = (_onehot_cell(new_head) * eat[..., None].astype(jnp.int8)).sum(axis=1)
        food = (state["food"] & ~(eaten > 0)).astype(jnp.int8)

        # phase 5: rank credit only for survivors of the whole step
        # (host:130-135)
        rank = jnp.where(alive, (tg + 1)[:, None] * RANK_SCALE + length, state["rank"])

        # phase 6: food respawn to MIN_FOOD on uniformly-random free cells
        # (host _spawn_food:148-154); two conditional Gumbel-max draws
        total_occ = occ.sum(axis=1)
        free = (total_occ == 0) & (food == 0)                    # (B, C)
        n_food = food.sum(axis=1, dtype=jnp.int32)               # (B,)
        k1, k2 = jax.random.split(key)
        g1 = jnp.where(free, jax.random.gumbel(k1, free.shape), -jnp.inf)
        cand1 = jnp.argmax(g1, axis=1)
        do1 = (n_food < MIN_FOOD) & free.any(axis=1)
        food = food | (_onehot_cell(cand1) * do1[:, None].astype(jnp.int8))
        free = free & ~((_onehot_cell(cand1) > 0) & do1[:, None])
        g2 = jnp.where(free, jax.random.gumbel(k2, free.shape), -jnp.inf)
        cand2 = jnp.argmax(g2, axis=1)
        do2 = (n_food + do1 < MIN_FOOD) & free.any(axis=1)
        food = food | (_onehot_cell(cand2) * do2[:, None].astype(jnp.int8))

        # phase 7: episode end — at most one survivor or step cap
        # (host:139-140 deactivates everyone)
        ended = (alive.sum(axis=1, dtype=jnp.int32) <= 1) | (tg >= MAX_STEPS)
        active_next = alive & ~ended[:, None]

        return {
            "cells": cells,
            "head_ptr": head_ptr,
            "length": length,
            "occ": occ,
            "active": active_next,
            # host keeps acted actions for every player, 0 for absent
            # (host:96,142); only active geese ever consult it again
            "last_action": jnp.where(active, actions, 0),
            "prev_head": new_prev_head,
            "rank": rank,
            "food": food,
            "step": tg,
            "done": state["done"] | ended,
        }

    # -- streaming-rollout hooks (runtime/device_rollout.py) ----------------

    @staticmethod
    def legal_mask_all(state):
        """(B, P, A) bool — every direction is always legal (reversal is
        legal-but-lethal, host legal_actions: envs/hungry_geese.py:201-202)."""
        B, P = state["active"].shape
        return jnp.ones((B, P, 4), bool)

    @staticmethod
    def rule_based_action_all(state, key):
        """(B, P) greedy food-seeker for every seat — device twin of the
        host ``rule_based_action`` (hungry_geese.py greedy: step toward
        the nearest food by torus manhattan distance, never reverse,
        avoid every goose cell; first direction wins ties, matching the
        host's strict-< scan over d in 0..3).  Boxed in -> uniform random
        non-reverse, like the host's random.choice branch.  Powers the
        on-device evaluator (runtime/device_eval.py)."""
        B, P = state["active"].shape
        head = VectorHungryGeese.head_cell(state)            # (B, P)
        occ_any = state["occ"].sum(axis=1) > 0               # (B, C)
        food = state["food"] > 0                             # (B, C)
        nxt = TRANS[jnp.clip(head, 0, NUM_CELLS - 1)]        # (B, P, 4)
        last = state["last_action"]                          # (B, P)
        dirs = jnp.arange(4, dtype=jnp.int32)
        reverse = (last >= 0)[..., None] & (
            dirs == OPPOSITE[jnp.clip(last, 0, 3)][..., None]
        )                                                    # (B, P, 4)
        lane = jnp.arange(B, dtype=jnp.int32)[:, None, None]
        blocked = occ_any[lane, nxt]                         # (B, P, 4)
        big = jnp.float32(1e9)
        fdist = jnp.where(
            food[:, None, None, :], DIST[nxt].astype(jnp.float32), big
        ).min(axis=-1)                                       # (B, P, 4)
        # host: min(..., default=0) — no food makes every dir distance 0
        fdist = jnp.where(food.any(axis=-1)[:, None, None], fdist, 0.0)
        valid = ~reverse & ~blocked
        best = jnp.argmin(jnp.where(valid, fdist, big), axis=-1)
        boxed = ~valid.any(axis=-1)                          # (B, P)
        g = jax.random.gumbel(key, (B, P, 4))
        rnd = jnp.argmax(jnp.where(reverse, -big, g), axis=-1)
        return jnp.where(boxed, rnd, best).astype(jnp.int32)

    @staticmethod
    def record(state):
        """Compact per-step fields from which the host rebuilds the
        17-plane observations (~40x smaller than the planes themselves)."""
        return {
            "occ": state["occ"],
            "head": VectorHungryGeese.head_cell(state).astype(jnp.int8),
            "tail": VectorHungryGeese.tail_cell(state).astype(jnp.int8),
            "prev_head": state["prev_head"].astype(jnp.int8),
            "food": state["food"],
        }

    @staticmethod
    def outcome_scores(state):
        """(B, P) pairwise rank outcome (+-1/(P-1) per beaten/losing
        opponent), identical to host outcome() (envs/hungry_geese.py:188-199);
        final scores where ``done``."""
        rank = state["rank"]
        gt = (rank[:, :, None] > rank[:, None, :]).sum(axis=2, dtype=jnp.int32)
        lt = (rank[:, :, None] < rank[:, None, :]).sum(axis=2, dtype=jnp.int32)
        return (gt - lt).astype(jnp.float32) / (NUM_AGENTS - 1)

    @staticmethod
    def view_obs(compact, player):
        """Device-side observation planes for ONE selected player per row:
        ``compact`` leaves are (N, T, ...) gathered training windows of the
        record() fields, ``player`` is (N,) int32.  Returns (N, T, 17, 7, 11)
        float32 — the same planes as observation()/episode_obs() for that
        player, built with a per-row player-axis rotation instead of
        stacking all P views (the device replay samples one target player
        per window, make_batch parity).  Unmasked: the caller applies the
        observation mask."""
        occ = compact["occ"].astype(jnp.float32)             # (N, T, P, C)
        heads = _onehot_cell(compact["head"].astype(jnp.int32)).astype(jnp.float32)
        tails = _onehot_cell(compact["tail"].astype(jnp.int32)).astype(jnp.float32)
        prev = _onehot_cell(compact["prev_head"].astype(jnp.int32)).astype(jnp.float32)
        food = compact["food"].astype(jnp.float32)           # (N, T, C)

        # jnp.roll(x, -p, axis) rotates player q -> (q + p) % P: gather that
        # order per row (player is traced, so a static roll cannot apply)
        order = (player[:, None] + jnp.arange(NUM_AGENTS)) % NUM_AGENTS  # (N, P)
        idx = order[:, None, :, None]                        # broadcast (N,T,P,C)
        roll_p = lambda x: jnp.take_along_axis(x, idx, axis=2)
        planes = jnp.concatenate(
            [roll_p(heads), roll_p(tails), roll_p(occ), roll_p(prev),
             food[:, :, None, :]],
            axis=2,
        )                                                    # (N, T, 17, C)
        return planes.reshape(planes.shape[:3] + (ROWS, COLS))

    @staticmethod
    def episode_obs(compact, active):
        """Rebuild (T, P, 17, 7, 11) observation planes from the compact
        record, exactly as the host env builds them
        (envs/hungry_geese.py:242-256); vectorized numpy scatter."""
        occ = compact["occ"].astype(np.float32)              # (T, P, C)
        head = compact["head"].astype(np.int32)
        tail = compact["tail"].astype(np.int32)
        prev = compact["prev_head"].astype(np.int32)
        food = compact["food"].astype(np.float32)            # (T, C)

        cell_ids = np.arange(NUM_CELLS, dtype=np.int32)
        heads_oh = (head[..., None] == cell_ids).astype(np.float32)
        tails_oh = (tail[..., None] == cell_ids).astype(np.float32)
        prev_oh = (prev[..., None] == cell_ids).astype(np.float32)
        food_pl = food[:, None, :]

        views = []
        for p in range(NUM_AGENTS):
            planes = np.concatenate(
                [
                    np.roll(heads_oh, -p, axis=1),
                    np.roll(tails_oh, -p, axis=1),
                    np.roll(occ, -p, axis=1),
                    np.roll(prev_oh, -p, axis=1),
                    food_pl,
                ],
                axis=1,
            )  # (T, 4*P+1, C)
            views.append(planes * active[:, p, None, None])
        obs = np.stack(views, axis=1)  # (T, P, planes, C)
        return obs.reshape(obs.shape[:3] + (ROWS, COLS))

    # -- host-side helpers (parity tests) -----------------------------------

    @staticmethod
    def body_list(state, lane: int, player: int):
        """Ordered body cells head-first, as the host env stores them."""
        cells = np.asarray(state["cells"])[lane, player]
        ptr = int(np.asarray(state["head_ptr"])[lane, player])
        length = int(np.asarray(state["length"])[lane, player])
        return [int(cells[(ptr + i) % MAXLEN]) for i in range(length)]
