"""Shared helpers for device-resident (vector) envs."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def reset_where_done(fresh, state):
    """Per-lane select: lanes flagged ``done`` in ``state`` take the
    corresponding ``fresh`` (re-initialized) leaves, others pass through —
    the streaming auto-reset primitive (runtime/device_rollout.py)."""
    done = state["done"]

    def pick(new, old):
        d = done.reshape((-1,) + (1,) * (old.ndim - 1))
        return jnp.where(d, new, old)

    return jax.tree.map(pick, fresh, state)
