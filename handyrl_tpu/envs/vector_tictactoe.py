"""Vectorized TicTacToe as pure jnp state transitions (device-resident).

The host env (envs/tictactoe.py) is the framework's canonical rules
implementation; this module expresses the SAME rules as batched,
branch-free array ops so whole populations of games can live and step on
the accelerator — the substrate for fully on-device self-play
(runtime/device_rollout.py), an actor-plane design point the reference's
process-per-actor architecture (worker.py:110-189) cannot reach.

Semantics parity is enforced by tests/test_device_rollout.py: every
device-generated game replays legally through the host env with the
identical outcome.

State (per game, batch-leading):
    cells  (B, 9) int8   0 empty / +1 first player / -1 second player
    winner (B,)   int8   0 none / +-1
All transitions are total functions — stepping a finished game is allowed
and ignored by callers via masks (XLA-static control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tictactoe import WIN_LINES

NUM_ACTIONS = 9
MAX_STEPS = 9
NUM_PLAYERS = 2


class VectorTicTacToe:
    """Stateless namespace of batched transition functions.

    Turn order is strict alternation (first player moves at even steps),
    so ``to_move`` is derived from the step index, not carried.
    """

    num_actions = NUM_ACTIONS
    max_steps = MAX_STEPS
    num_players = NUM_PLAYERS

    @staticmethod
    def init(n_games: int):
        return {
            "cells": jnp.zeros((n_games, 9), jnp.int8),
            "winner": jnp.zeros((n_games,), jnp.int8),
        }

    @staticmethod
    def color(step: int) -> int:
        """Stone color moving at ``step`` (host TicTacToe: BLACK first)."""
        return 1 if step % 2 == 0 else -1

    @staticmethod
    def turn_player(step: int) -> int:
        return step % 2

    @staticmethod
    def observation(state, step: int):
        """(B, 3, 3, 3) planes for the turn player — identical to the host
        env's turn-player view (tictactoe.py:107-118): [my-view ones,
        my stones, opponent stones]."""
        me = VectorTicTacToe.color(step)
        grid = state["cells"].reshape(-1, 3, 3)
        B = grid.shape[0]
        return jnp.stack(
            [
                jnp.ones((B, 3, 3), jnp.float32),
                (grid == me).astype(jnp.float32),
                (grid == -me).astype(jnp.float32),
            ],
            axis=1,
        )

    @staticmethod
    def legal_mask(state):
        """(B, 9) bool — empty cells."""
        return state["cells"] == 0

    @staticmethod
    def terminal(state, step: int):
        """(B,) bool — games finished BEFORE step ``step`` plays."""
        return (state["winner"] != 0) | (step >= MAX_STEPS)

    @staticmethod
    def apply(state, actions, step: int):
        """Play ``actions`` (B,) for the step's turn player in every
        non-finished game; finished games pass through unchanged."""
        me = VectorTicTacToe.color(step)
        live = ~VectorTicTacToe.terminal(state, step)
        onehot = jax.nn.one_hot(actions, 9, dtype=jnp.int8)
        cells = jnp.where(
            (onehot * live[:, None].astype(jnp.int8)) > 0,
            jnp.int8(me),
            state["cells"],
        )
        # win detection over the 8 line triples
        lines = cells[:, jnp.asarray(np.asarray(WIN_LINES))]     # (B, 8, 3)
        won = (lines.sum(axis=-1) == 3 * me).any(axis=-1) & live
        winner = jnp.where(won, jnp.int8(me), state["winner"])
        return {"cells": cells, "winner": winner}

    @staticmethod
    def outcome(state):
        """(B, 2) float32 — per-player score ordered like host players()."""
        w = state["winner"].astype(jnp.float32)
        return jnp.stack([w, -w], axis=1)
