"""Game environment protocol.

Mirrors the reference contract (handyrl/environment.py:41-145): the same 17
methods, so any HandyRL-style environment ports over directly.  Two
deliberate differences:

* Game logic here is pure numpy/python — environments never import a
  neural-net framework.  ``net()`` returns a Flax module (from
  ``handyrl_tpu.models``), loaded lazily.
* ``Environment`` subclasses may expose ``observation_spec()`` /
  ``action_size()`` so the runtime can pre-build fixed-shape device
  buffers without resetting a throwaway env.
"""

from __future__ import annotations

from typing import Any, Dict, List


class BaseEnvironment:
    """Abstract game interface.

    Shapes of the game loop (see runtime/generation.py):
        reset() -> while not terminal(): turns()/observers() -> observation(p)
        -> legal_actions(p) -> step({player: action}) -> reward() ... outcome()

    Network-battle / replica synchronisation uses ``diff_info``/``update``:
    a master env emits a per-player delta after every transition, replica
    envs apply it and must stay consistent (legal-action sets identical).
    """

    def __init__(self, args: Dict[str, Any] | None = None):
        self.args: Dict[str, Any] = dict(args or {})

    def __str__(self) -> str:
        return ""

    # -- core transitions ---------------------------------------------------

    def reset(self, args: Dict[str, Any] | None = None):
        """Start a new game. Return a truthy value on unrecoverable error."""
        raise NotImplementedError()

    def play(self, action: int, player: int | None = None):
        """Apply a single player's action (turn-based games)."""
        raise NotImplementedError()

    def step(self, actions: Dict[int, int | None]):
        """Apply a joint action dict. Default: sequentially play non-None actions."""
        for player, action in actions.items():
            if action is not None:
                self.play(action, player)

    # -- whose move ---------------------------------------------------------

    def turn(self) -> int:
        """Turn player (single-actor games)."""
        return 0

    def turns(self) -> List[int]:
        """Players who act this step (simultaneous games override)."""
        return [self.turn()]

    def observers(self) -> List[int]:
        """Non-acting players who should still observe (e.g. to feed RNNs)."""
        return []

    # -- termination & rewards ---------------------------------------------

    def terminal(self) -> bool:
        raise NotImplementedError()

    def reward(self) -> Dict[int, float]:
        """Immediate rewards after the last step ({} if none)."""
        return {}

    def outcome(self) -> Dict[int, float]:
        """Final outcome per player at a terminal state."""
        raise NotImplementedError()

    # -- actions & players --------------------------------------------------

    def legal_actions(self, player: int | None = None) -> List[int]:
        raise NotImplementedError()

    def players(self) -> List[int]:
        return [0]

    def observation(self, player: int | None = None):
        """Numpy feature pytree for ``player``'s point of view."""
        raise NotImplementedError()

    # -- string codecs (used by match records & network battles) -----------

    def action2str(self, a: int, player: int | None = None) -> str:
        return str(a)

    def str2action(self, s: str, player: int | None = None) -> int:
        return int(s)

    # -- replica synchronisation (network battle mode) ----------------------

    def diff_info(self, player: int | None = None):
        return ""

    def update(self, info, reset: bool):
        raise NotImplementedError()

    # -- model factory ------------------------------------------------------

    def net(self):
        """Return the Flax module for this game (policy/value net).

        Honors ``env_args['net'] == 'transformer'`` for every environment:
        the generic KV-cache memory family (models/transformer.py) sized by
        ``transformer_spec()``, with ``env_args['net_args']`` merged over
        the spec — so configs can scale the family (d_model, n_layers,
        n_heads, memory_len, mlp_ratio) without a new env subclass.
        Environments implement ``default_net()`` for their bespoke
        architecture.
        """
        if self.args.get("net") == "transformer":
            from ..models import TransformerNet

            spec = dict(self.transformer_spec())
            spec.update(self.args.get("net_args") or {})
            return TransformerNet(**spec)
        return self.default_net()

    def default_net(self):
        """The environment's bespoke policy/value module."""
        raise NotImplementedError()

    def transformer_spec(self) -> Dict[str, Any]:
        """Constructor kwargs for the generic TransformerNet family."""
        return {"num_actions": self.action_size()}

    def action_size(self) -> int:
        """Total policy-head size (maximum action index + 1)."""
        raise NotImplementedError()
