"""Twin-less env compiler: pure numpy single-game rules -> batched jnp
vector env.

Every device-speed game used to need a HAND-WRITTEN ``vector_*`` twin
(vector_tictactoe.py and friends): the same rules expressed a second time
as batched branch-free array ops, kept in lock-step with the host env by
parity tests.  That porting cost capped scenario diversity at whatever we
hand-built (ROADMAP item 4).  This module removes the twin: a user writes
their game ONCE as pure single-game numpy functions (the ``rules``
namespace below) and ``autovectorize`` lifts them into the episodic
vector-env contract (``VectorTicTacToe``'s API — the shape
``runtime/device_rollout.py`` drives) by

1. **rebinding numpy to jnp**: each rules function is rebuilt over a
   globals dict whose ``numpy`` module aliases point at a jnp shim, so
   the SAME source executes as host numpy (the testable reference
   semantics) or as traced jnp (the device program);
2. **shape/dtype tracing**: every lifted function is abstractly evaluated
   (``jax.eval_shape``) against the state template at lift time — a
   non-liftable op (data-dependent python control flow, in-place array
   mutation, a numpy API with no jnp equivalent) fails HERE, at
   construction, as an ``AutovecError`` naming the function and the rule
   it broke, not as a cryptic tracer error inside a rollout thread;
3. **vmap batching + totality**: the single-game functions are ``vmap``-ed
   across the game batch, and ``apply`` is made total the way every
   hand-written twin is (envs/vector_common.py): finished lanes pass
   through unchanged via a per-lane select, so the user's rules never
   need to reason about already-terminal games.

Liftability rules (the contract a ``rules`` namespace must satisfy —
quoted in every AutovecError):

* functions are PURE: same inputs -> same outputs, no mutation of the
  input state, no global state, no randomness (``np.random`` is refused;
  stochastic envs thread explicit keys through state instead);
* arrays are updated OUT-OF-PLACE (``np.where`` / arithmetic — never
  ``arr[i] = v``, jax arrays are immutable);
* no python control flow on ARRAY VALUES (``if board[x]:`` fails under
  tracing; branch with ``np.where``).  Control flow on the static
  ``step`` argument is fine — it is a python int;
* fixed shapes and dtypes: every function returns the same shapes for
  every step, and ``apply`` returns a state tree identical in structure,
  shape and dtype to its input;
* ``import numpy as np`` (module import); from-imports of individual
  numpy functions are not rebound.

The lifted class advertises ``__autovec__ = True`` and carries a
``verify(n_games, seed)`` step-parity driver (random games stepped
simultaneously through the numpy rules and the lifted device env, every
observable compared per step) — wired to the ``autovec_verify_games``
config knob so a run can self-check the lift at startup.  Scalar-env
parity (rules vs the 17-method host Environment) stays a test concern,
same as the hand-written twins (tests/test_device_rollout.py).
"""

from __future__ import annotations

import types
from typing import Any, Dict

import numpy as np

_RULES = (
    "autovec liftability rules: pure functions; out-of-place array "
    "updates only (jax arrays are immutable); no python control flow on "
    "array values (np.where instead); fixed shapes/dtypes per function; "
    "apply() returns a state tree identical in structure/shape/dtype to "
    "its input; 'import numpy as np' module imports only.  See "
    "docs/league.md §Autovec liftability."
)


class AutovecError(RuntimeError):
    """A rules namespace cannot be lifted (or failed step-parity)."""


class _JnpShim(types.ModuleType):
    """Stands in for the ``numpy`` module inside lifted functions: every
    attribute resolves to its jnp equivalent; APIs jnp does not carry
    fail loudly with the liftability rules instead of a bare
    AttributeError deep inside a trace."""

    def __init__(self):
        super().__init__("autovec_jnp_shim")

    def __getattr__(self, name: str):
        import jax.numpy as jnp

        if name == "random":
            raise AutovecError(
                "np.random is not liftable — randomness must come through "
                "explicit state carried by the rules (or stay out of the "
                f"rules entirely).  {_RULES}"
            )
        try:
            return getattr(jnp, name)
        except AttributeError:
            raise AutovecError(
                f"np.{name} has no jax.numpy equivalent; rewrite the rules "
                f"with liftable ops.  {_RULES}"
            ) from None


_SHIM = _JnpShim()


def _rule_functions(rules) -> Dict[str, Any]:
    """The plain functions defined on the rules namespace (staticmethods
    unwrapped), keyed by name."""
    fns: Dict[str, Any] = {}
    for name, attr in vars(rules).items():
        if name.startswith("__"):
            continue
        if isinstance(attr, staticmethod):
            fns[name] = attr.__func__
        elif isinstance(attr, types.FunctionType):
            fns[name] = attr
    return fns


def _lift_namespace(rules) -> types.SimpleNamespace:
    """Rebuild every rules function over a globals dict whose numpy
    module aliases point at the jnp shim.  Intra-namespace helper calls
    (``MyRules._helper(...)``) resolve to the LIFTED versions: the
    namespace binds itself under the rules class name in the shared
    globals."""
    fns = _rule_functions(rules)
    if not fns:
        raise AutovecError(
            f"{rules.__name__} defines no functions to lift.  {_RULES}"
        )
    base_globals = next(iter(fns.values())).__globals__
    lifted_globals = dict(base_globals)
    rebound = [k for k, v in base_globals.items() if v is np]
    for k in rebound:
        lifted_globals[k] = _SHIM
    if not rebound:
        # rules that never touch numpy are legal (pure python int state
        # would fail elsewhere with better diagnostics), but a module
        # that from-imported numpy functions is the common trap
        for k, v in base_globals.items():
            if getattr(v, "__module__", "").startswith("numpy"):
                raise AutovecError(
                    f"global {k!r} is a from-imported numpy function; only "
                    f"'import numpy as np' module aliases are rebound.  {_RULES}"
                )
    ns = types.SimpleNamespace()
    for name, fn in fns.items():
        new = types.FunctionType(
            fn.__code__, lifted_globals, fn.__name__, fn.__defaults__,
            fn.__closure__,
        )
        new.__kwdefaults__ = fn.__kwdefaults__
        setattr(ns, name, new)
    # self-reference: MyRules.helper(...) inside a lifted body must hit
    # the lifted helper, not the numpy original
    lifted_globals[rules.__name__] = ns
    return ns


def _state_template(rules) -> Dict[str, np.ndarray]:
    try:
        template = rules.init()
    except Exception as exc:
        raise AutovecError(
            f"{rules.__name__}.init() failed under host numpy: "
            f"{type(exc).__name__}: {exc}.  {_RULES}"
        ) from exc
    if not isinstance(template, dict) or not template:
        raise AutovecError(
            f"{rules.__name__}.init() must return a non-empty dict of "
            f"numpy arrays (got {type(template).__name__}).  {_RULES}"
        )
    out = {}
    for k, v in template.items():
        arr = np.asarray(v)
        if arr.dtype == object:
            raise AutovecError(
                f"{rules.__name__}.init()[{k!r}] is not a fixed-dtype "
                f"array.  {_RULES}"
            )
        out[k] = arr
    return out


def _trace(rules_name: str, fn_name: str, fn, *args):
    """jax.eval_shape with lift-aware diagnostics: the abstract trace is
    where in-place mutation, value-dependent branching and missing jnp
    APIs surface — re-raised as AutovecError naming the function."""
    import jax

    try:
        return jax.eval_shape(fn, *args)
    except AutovecError as exc:
        raise AutovecError(f"{rules_name}.{fn_name}: {exc}") from exc
    except TypeError as exc:
        hint = ""
        if "immutable" in str(exc) or "item assignment" in str(exc):
            hint = (
                " (in-place array assignment is not liftable; use "
                "np.where or arithmetic to build the new array)"
            )
        raise AutovecError(
            f"{rules_name}.{fn_name} is not liftable: {exc}{hint}.  {_RULES}"
        ) from exc
    except Exception as exc:
        hint = ""
        name = type(exc).__name__
        if "Tracer" in name or "Concretization" in name:
            hint = (
                " (python control flow on an array value — branch with "
                "np.where instead)"
            )
        raise AutovecError(
            f"{rules_name}.{fn_name} is not liftable: {name}: {exc}{hint}.  "
            f"{_RULES}"
        ) from exc


def _check_shapes(rules, lifted, template) -> None:
    """Abstractly evaluate every contract function against the state
    template; loud diagnostics for shape/dtype contract breaks."""
    import jax

    name = rules.__name__
    aval = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in template.items()
    }
    act = jax.ShapeDtypeStruct((), np.int32)
    A, P = int(rules.num_actions), int(rules.num_players)

    obs0 = _trace(name, "observation", lambda s: lifted.observation(s, 0), aval)
    obs1 = _trace(name, "observation", lambda s: lifted.observation(s, 1), aval)
    if obs0.shape != obs1.shape or obs0.dtype != obs1.dtype:
        raise AutovecError(
            f"{name}.observation changes shape/dtype with step "
            f"({obs0.shape}/{obs0.dtype} at step 0 vs {obs1.shape}/"
            f"{obs1.dtype} at step 1); the compiled rollout needs one "
            f"fixed observation spec.  {_RULES}"
        )
    legal = _trace(name, "legal_mask", lifted.legal_mask, aval)
    if legal.shape != (A,) or legal.dtype != np.bool_:
        raise AutovecError(
            f"{name}.legal_mask must return a ({A},) bool array "
            f"(num_actions), got {legal.shape} {legal.dtype}.  {_RULES}"
        )
    term = _trace(name, "terminal", lambda s: lifted.terminal(s, 0), aval)
    if term.shape != () or term.dtype != np.bool_:
        raise AutovecError(
            f"{name}.terminal must return a scalar bool, got "
            f"{term.shape} {term.dtype}.  {_RULES}"
        )
    new = _trace(name, "apply", lambda s, a: lifted.apply(s, a, 0), aval, act)
    if not isinstance(new, dict) or set(new) != set(aval):
        got = sorted(new) if isinstance(new, dict) else type(new).__name__
        raise AutovecError(
            f"{name}.apply must return the same state keys "
            f"{sorted(aval)}, got {got}.  {_RULES}"
        )
    for k in aval:
        if new[k].shape != aval[k].shape or new[k].dtype != aval[k].dtype:
            raise AutovecError(
                f"{name}.apply changes state[{k!r}] from "
                f"{aval[k].shape} {aval[k].dtype} to {new[k].shape} "
                f"{new[k].dtype}; state must be shape/dtype-stable or the "
                f"rollout scan cannot carry it.  {_RULES}"
            )
    outc = _trace(name, "outcome", lifted.outcome, aval)
    if outc.shape != (P,):
        raise AutovecError(
            f"{name}.outcome must return a ({P},) per-player score array "
            f"(num_players), got {outc.shape}.  {_RULES}"
        )


_LIFT_CACHE: Dict[type, type] = {}


def autovectorize(rules) -> type:
    """Lift a pure-numpy single-game ``rules`` namespace into an episodic
    vector env class (the ``VectorTicTacToe`` contract, consumed by
    ``runtime/device_rollout.py``) — no hand-written twin.

    ``rules`` is a class/namespace of pure functions over a single game:

        num_actions, max_steps, num_players  (ints)
        init() -> {name: np.ndarray}                      fresh game state
        observation(state, step) -> np.ndarray            turn player view
        legal_mask(state) -> (num_actions,) bool
        terminal(state, step) -> bool scalar
        apply(state, action, step) -> state               live games only
        outcome(state) -> (num_players,) float scores

    The lift is memoized per rules class (tracing is not free), validated
    at construction, and the returned class exposes
    ``verify(n_games, seed)`` for random-game step-parity against the
    numpy execution of the same rules.
    """
    cached = _LIFT_CACHE.get(rules)
    if cached is not None:
        return cached

    import jax
    import jax.numpy as jnp

    for attr in ("num_actions", "max_steps", "num_players"):
        if not isinstance(getattr(rules, attr, None), int):
            raise AutovecError(
                f"{getattr(rules, '__name__', rules)!r} needs int attribute "
                f"{attr!r}.  {_RULES}"
            )
    for fn in ("init", "observation", "legal_mask", "terminal", "apply",
               "outcome"):
        if not callable(getattr(rules, fn, None)):
            raise AutovecError(
                f"{rules.__name__} is missing rules function {fn!r}.  {_RULES}"
            )

    lifted = _lift_namespace(rules)
    template = _state_template(rules)
    _check_shapes(rules, lifted, template)

    def v_init(n_games: int):
        return {
            k: jnp.broadcast_to(jnp.asarray(v), (n_games,) + v.shape)
            for k, v in template.items()
        }

    def v_observation(state, step: int):
        return jax.vmap(lambda s: lifted.observation(s, step))(state)

    def v_legal_mask(state):
        return jax.vmap(lifted.legal_mask)(state)

    def v_terminal(state, step: int):
        return jax.vmap(lambda s: lifted.terminal(s, step))(state)

    def v_apply(state, actions, step: int):
        # totality wrapper (the vector_common contract): the user's apply
        # sees live games only in effect — finished lanes pass through
        # unchanged via a per-lane select, and whatever the traced apply
        # computed for them is discarded
        live = ~v_terminal(state, step)
        new = jax.vmap(lambda s, a: lifted.apply(s, a, step))(
            state, actions.astype(jnp.int32)
        )
        return jax.tree.map(
            lambda n, o: jnp.where(
                live.reshape((-1,) + (1,) * (o.ndim - 1)), n, o
            ),
            new,
            dict(state),
        )

    def v_outcome(state):
        return jax.vmap(lifted.outcome)(state).astype(jnp.float32)

    def verify(n_games: int, seed: int = 0) -> None:
        """Random-game step-parity: ``n_games`` games stepped through the
        host-numpy rules and the lifted env simultaneously; every
        observable (observation, legal mask, terminal flag, outcome)
        compared per step.  Raises AutovecError on the first divergence —
        the ``autovec_verify_games`` startup self-check."""
        rng = np.random.default_rng(seed)
        hosts = [
            {k: v.copy() for k, v in _state_template(rules).items()}
            for _ in range(n_games)
        ]
        done = np.zeros(n_games, bool)
        state = v_init(n_games)

        def bail(what, step):
            raise AutovecError(
                f"autovec step-parity failed for {rules.__name__}: {what} "
                f"diverged between the numpy rules and the lifted env at "
                f"step {step}"
            )

        for step in range(int(rules.max_steps)):
            h_term = np.array(
                [bool(rules.terminal(h, step)) for h in hosts]
            )
            if not np.array_equal(
                h_term, np.asarray(jax.device_get(v_terminal(state, step)))
            ):
                bail("terminal", step)
            h_legal = np.stack([np.asarray(rules.legal_mask(h)) for h in hosts])
            if not np.array_equal(
                h_legal, np.asarray(jax.device_get(v_legal_mask(state)))
            ):
                bail("legal_mask", step)
            h_obs = np.stack(
                [np.asarray(rules.observation(h, step)) for h in hosts]
            )
            d_obs = np.asarray(jax.device_get(v_observation(state, step)))
            if not np.allclose(h_obs, d_obs, atol=1e-6):
                bail("observation", step)
            done = h_term
            if done.all():
                break
            actions = np.zeros(n_games, np.int32)
            for i, h in enumerate(hosts):
                if done[i]:
                    continue
                legal = np.flatnonzero(h_legal[i])
                actions[i] = rng.choice(legal) if len(legal) else 0
                hosts[i] = rules.apply(h, int(actions[i]), step)
            state = v_apply(state, jnp.asarray(actions), step)
        h_out = np.stack([np.asarray(rules.outcome(h)) for h in hosts])
        d_out = np.asarray(jax.device_get(v_outcome(state)))
        if not np.allclose(h_out.astype(np.float32), d_out, atol=1e-6):
            bail("outcome", int(rules.max_steps))

    cls = type(
        f"AutoVec{rules.__name__}",
        (),
        {
            "__doc__": (
                f"Autovectorized device twin of {rules.__name__} "
                "(envs/autovec.py) — no hand-written vector env."
            ),
            "__autovec__": True,
            "rules": rules,
            "num_actions": int(rules.num_actions),
            "max_steps": int(rules.max_steps),
            "num_players": int(rules.num_players),
            "init": staticmethod(v_init),
            "observation": staticmethod(v_observation),
            "legal_mask": staticmethod(v_legal_mask),
            "terminal": staticmethod(v_terminal),
            "apply": staticmethod(v_apply),
            "outcome": staticmethod(v_outcome),
            "verify": staticmethod(verify),
        },
    )
    _LIFT_CACHE[rules] = cls
    return cls
