"""Hungry Geese — 4-player simultaneous-move survival game on a 7x11 torus.

The reference (handyrl/envs/kaggle/hungry_geese.py:60-231) wraps Kaggle's
``kaggle_environments`` simulator; this is a standalone numpy implementation
of the same rules so the framework has no external game dependency:

* 4 geese, each a list of cells on a 7x11 torus; 2 food on board.
* Per step, each active goose moves its head N/S/W/E.  Reversing the
  previous action, self-collision, or starving to length 0 kills a goose.
* Eating food grows the goose (tail not popped); every 40th step every
  goose loses a tail cell (hunger).
* After all moves, any head sharing a cell with any other goose cell dies.
* Game ends when at most one goose is active or after the step limit.
* Ranking reward: ``(steps survived) * 100 + length`` — survival dominates,
  length breaks ties, matching the Kaggle ranking semantics the reference
  feeds into its pairwise outcome (+-1/3 per beaten opponent,
  reference:168-180).

Observation parity: 17 planes (7, 11) — head / tail / whole-body /
previous-head per goose (channel-rotated so the acting player is channel 0)
plus food (reference:202-231).
"""

from __future__ import annotations

import random

import numpy as np

from .base import BaseEnvironment

ROWS, COLS = 7, 11
NUM_CELLS = ROWS * COLS
NUM_AGENTS = 4
HUNGER_RATE = 40
MIN_FOOD = 2
MAX_STEPS = 199  # kaggle episode_steps=200 includes the initial state
RANK_SCALE = 100  # > max goose length, so survival time dominates length

ACTIONS = ["NORTH", "SOUTH", "WEST", "EAST"]
_MOVES = [(-1, 0), (1, 0), (0, -1), (0, 1)]
_OPPOSITE = {0: 1, 1: 0, 2: 3, 3: 2}


def _translate(cell: int, direction: int) -> int:
    r, c = divmod(cell, COLS)
    dr, dc = _MOVES[direction]
    return ((r + dr) % ROWS) * COLS + (c + dc) % COLS


class Environment(BaseEnvironment):
    ACTION = ACTIONS  # kaggle-compatible name

    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    def reset(self, args=None):
        cells = random.sample(range(NUM_CELLS), NUM_AGENTS + MIN_FOOD)
        self.geese = [[c] for c in cells[:NUM_AGENTS]]
        self.food = list(cells[NUM_AGENTS:])
        self.active = [True] * NUM_AGENTS
        self.rank_rewards = [RANK_SCALE + 1] * NUM_AGENTS  # step 1 * scale + len 1
        self.step_count = 0
        self.last_actions: dict[int, int] = {}
        self.prev_heads = [None] * NUM_AGENTS

    # -- codecs -------------------------------------------------------------

    def action2str(self, a, player=None):
        return ACTIONS[a]

    def str2action(self, s, player=None):
        return ACTIONS.index(s)

    def __str__(self):
        glyph = np.full((ROWS, COLS), ".", dtype=object)
        for cell in self.food:
            glyph[divmod(cell, COLS)] = "f"
        for p, goose in enumerate(self.geese):
            for cell in goose[1:]:
                glyph[divmod(cell, COLS)] = str(p)
            if goose:
                glyph[divmod(goose[0], COLS)] = "@"
        lines = ["step %d" % self.step_count]
        lines += ["".join(row) for row in glyph]
        lines.append(" ".join(str(len(g) or "-") for g in self.geese))
        return "\n".join(lines)

    # -- transitions --------------------------------------------------------

    def step(self, actions):
        self.step_count += 1
        t = self.step_count
        self.prev_heads = [g[0] if g else None for g in self.geese]
        acted = {p: (actions.get(p) or 0) for p in self.players()}

        for p in self.players():
            if not self.active[p]:
                continue
            goose = self.geese[p]
            action = acted[p]
            if self.last_actions.get(p) is not None and action == _OPPOSITE[self.last_actions[p]]:
                self._kill(p)  # reversed into own neck
                continue
            head = _translate(goose[0], action)
            if head in self.food:
                self.food.remove(head)  # grow: keep tail
            else:
                goose.pop()
            if head in goose:
                self._kill(p)  # ran into own body
                continue
            goose.insert(0, head)
            if t % HUNGER_RATE == 0:
                goose.pop()
                if not goose:
                    self._kill(p)  # starved
                    continue

        # Cross-goose collisions: any head sharing a cell with any goose cell.
        occupancy = np.zeros(NUM_CELLS, dtype=np.int32)
        for goose in self.geese:
            for cell in goose:
                occupancy[cell] += 1
        for p in self.players():
            if self.active[p] and occupancy[self.geese[p][0]] > 1:
                self._kill(p)

        # Rank rewards are credited only after all deaths this step are
        # resolved (kaggle: "set rewards after deaths have been taken into
        # account") — a goose dying at step t keeps its step t-1 reward.
        for p in self.players():
            if self.active[p]:
                self.rank_rewards[p] = (t + 1) * RANK_SCALE + len(self.geese[p])

        self._spawn_food()

        if sum(self.active) <= 1 or self.step_count >= MAX_STEPS:
            self.active = [False] * NUM_AGENTS

        self.last_actions = acted

    def _kill(self, p):
        self.active[p] = False
        self.geese[p] = []

    def _spawn_food(self):
        occupied = {c for g in self.geese for c in g} | set(self.food)
        free = [c for c in range(NUM_CELLS) if c not in occupied]
        while len(self.food) < MIN_FOOD and free:
            cell = random.choice(free)
            free.remove(cell)
            self.food.append(cell)

    # -- replica sync -------------------------------------------------------

    def diff_info(self, player=None):
        return {
            "geese": [list(g) for g in self.geese],
            "food": list(self.food),
            "active": list(self.active),
            "rank_rewards": list(self.rank_rewards),
            "step_count": self.step_count,
            "last_actions": dict(self.last_actions),
            "prev_heads": list(self.prev_heads),
        }

    def update(self, info, reset):
        if reset:
            self.reset()
        self.geese = [list(g) for g in info["geese"]]
        self.food = list(info["food"])
        self.active = list(info["active"])
        self.rank_rewards = list(info["rank_rewards"])
        self.step_count = info["step_count"]
        self.last_actions = {int(k): v for k, v in info["last_actions"].items()}
        self.prev_heads = list(info["prev_heads"])

    # -- game state ---------------------------------------------------------

    def turns(self):
        return [p for p in self.players() if self.active[p]]

    def terminal(self):
        return not any(self.active)

    def outcome(self):
        """Pairwise rank outcome: +1/3 per beaten opponent, -1/3 per loss."""
        out = {p: 0.0 for p in self.players()}
        for p in self.players():
            for q in self.players():
                if p == q:
                    continue
                if self.rank_rewards[p] > self.rank_rewards[q]:
                    out[p] += 1 / (NUM_AGENTS - 1)
                elif self.rank_rewards[p] < self.rank_rewards[q]:
                    out[p] -= 1 / (NUM_AGENTS - 1)
        return out

    def legal_actions(self, player=None):
        return list(range(len(ACTIONS)))

    def players(self):
        return list(range(NUM_AGENTS))

    def rule_based_action(self, player, key=None):
        """Greedy food-seeker: step toward the nearest food, avoiding cells
        occupied by any goose body and never reversing (cf. the reference's
        use of kaggle's GreedyAgent, reference:189-197)."""
        goose = self.geese[player]
        if not goose:
            return 0
        head = goose[0]
        blocked = {c for g in self.geese for c in g}
        last = self.last_actions.get(player)
        best, best_dist = None, 10 ** 9
        for d in range(4):
            if last is not None and d == _OPPOSITE[last]:
                continue
            nxt = _translate(head, d)
            if nxt in blocked:
                continue
            dist = min((self._torus_dist(nxt, f) for f in self.food), default=0)
            if dist < best_dist:
                best, best_dist = d, dist
        if best is None:  # boxed in: any non-reverse move
            candidates = [d for d in range(4) if last is None or d != _OPPOSITE[last]]
            best = random.choice(candidates or [0])
        return best

    @staticmethod
    def _torus_dist(a, b):
        ar, ac = divmod(a, COLS)
        br, bc = divmod(b, COLS)
        dr = min((ar - br) % ROWS, (br - ar) % ROWS)
        dc = min((ac - bc) % COLS, (bc - ac) % COLS)
        return dr + dc

    # -- features -----------------------------------------------------------

    def observation(self, player=None):
        """(17, 7, 11) planes; acting player's channels come first."""
        if player is None:
            player = 0
        planes = np.zeros((NUM_AGENTS * 4 + 1, NUM_CELLS), dtype=np.float32)
        for p, goose in enumerate(self.geese):
            ch = (p - player) % NUM_AGENTS
            if goose:
                planes[ch, goose[0]] = 1          # head
                planes[4 + ch, goose[-1]] = 1     # tail tip
                planes[8 + ch, goose] = 1         # whole body
            if self.prev_heads[p] is not None:
                planes[12 + ch, self.prev_heads[p]] = 1
        planes[16, self.food] = 1
        return planes.reshape(-1, ROWS, COLS)

    def action_size(self):
        return 4

    @staticmethod
    def vector_env():
        """Device-resident batched rules (streaming on-device self-play,
        runtime/device_rollout.py)."""
        from .vector_hungry_geese import VectorHungryGeese

        return VectorHungryGeese

    def default_net(self):
        from ..models import GeeseNet

        return GeeseNet()


if __name__ == "__main__":
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
        print(e)
        print(e.outcome())
