"""Parallel (simultaneous-move) Tic-Tac-Toe.

Parity with reference handyrl/envs/parallel_tictactoe.py:13-59: both players
submit an action every step; a uniformly random one of the submitted actions
is applied for its submitter.  Exercises the simultaneous-move path
(``turns()`` returns every player) with the same observation/net as
TicTacToe.
"""

from __future__ import annotations

import random

import numpy as np

from .tictactoe import Environment as TicTacToe, ROWS, COLS, WIN_LINES


class Environment(TicTacToe):
    _COLOR_CHAR = {1: "O", -1: "X"}

    def __str__(self):
        grid = self.cells.reshape(3, 3)
        lines = ["  " + " ".join(COLS)]
        for r in range(3):
            lines.append(ROWS[r] + " " + " ".join(self._GLYPH[int(v)] for v in grid[r]))
        return "\n".join(lines)

    def step(self, actions):
        chooser = random.choice(list(actions.keys()))
        self._apply(actions[chooser], chooser)

    def _apply(self, action, player):
        color = (self.BLACK, self.WHITE)[player]
        self.cells[action] = color
        if any(self.cells[line].sum() == 3 * color for line in WIN_LINES[self._lines_through(action)]):
            self.winner = color
        self.history.append((color, action))

    def diff_info(self, player=None):
        if not self.history:
            return ""
        color, action = self.history[-1]
        return self.action2str(action) + ":" + self._COLOR_CHAR[color]

    def update(self, info, reset):
        if reset:
            self.reset()
        else:
            move, glyph = info.split(":")
            self._apply(self.str2action(move), "OX".index(glyph))

    def turn(self):
        raise NotImplementedError("simultaneous game: use turns()")

    def turns(self):
        return self.players()

    @staticmethod
    def vector_env():
        """Device-resident batched rules (streaming on-device self-play)."""
        from .vector_parallel_tictactoe import VectorParallelTicTacToe

        return VectorParallelTicTacToe

    def observation(self, player=None):
        """Per-player view: [always-acting plane, my stones, theirs].

        The reference inherits TicTacToe.observation, whose my-view check
        compares the player against turn()'s sentinel return (reference
        parallel_tictactoe.py:54) and silently picks the opponent view for
        everyone; here the simultaneous-move perspective is explicit."""
        color = self.BLACK if player in (None, 0) else self.WHITE
        grid = self.cells.reshape(3, 3)
        return np.stack(
            [np.ones((3, 3)), grid == color, grid == -color]
        ).astype(np.float32)


if __name__ == "__main__":
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
        print(e)
        print(e.outcome())
