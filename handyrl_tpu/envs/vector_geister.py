"""Vectorized Geister as pure jnp state transitions (device-resident).

The host env (envs/geister.py) is the canonical rules implementation;
this module expresses the SAME rules as batched, branch-free array ops:
whole populations of games — each possibly in a different phase (piece
placement at ply -2/-1, mid-game, finished) — step together under one
``lax.scan``, with every branch realized as a masked update.  Drives the
streaming device rollout (runtime/device_rollout.py) with the DRC
ConvLSTM net: the first turn-based + recurrent on-device self-play path.

Rules parity with the host (lock-step tested in
tests/test_device_rollout.py::TestVectorGeisterParity):

* action space 144 move (dir*36 + square in the MOVER's frame; White
  sees the board 180-degree rotated, frame_sq = 35 - sq, frame_dir =
  3 - d) + 70 placement layouts (C(8,4) blue assignments);
* captures disclose nothing here (the device is the omniscient master;
  information hiding happens in observation building, exactly like the
  host's per-player planes);
* win by goal escape / capturing all enemy blues / capturing all enemy
  reds (mover LOSES), 200-ply draw, -0.01 per-step reward for both
  players (host geister.py:183-214, 253-261).

State (per lane):
    board  (B, 36) int8   piece id 0..15 or -1 (6x6 in x*6+y order)
    pos    (B, 16) int8   square of each piece, -1 when off-board
    kind   (B, 16) int8   BLUE 0 / RED 1 (true kinds)
    alive  (B, 16) bool
    counts (B, 2, 2) int8 remaining per (color, kind)
    ply    (B,) int32     starts at -2 (two placement plies)
    win    (B,) int8      -1 none / 0 Black / 1 White / 2 draw
    active (B, 2) bool    one-hot of the player to act (zeros when done)
    done   (B,) bool
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

NUM_PLAYERS = 2
BLUE, RED = 0, 1
SIZE = 6
NUM_SQUARES = 36
NUM_MOVE_ACTIONS = 144
NUM_ACTIONS = 214
MAX_PLY = 200
STEP_REWARD = -0.01

# (x, y) deltas in host order [up, left, right, down] (geister.py:34)
_DIRS = np.array([(-1, 0), (0, -1), (0, 1), (1, 0)], np.int32)

# home squares (x*6+y) in placement order per color (host _HOME)
_HOME = np.array(
    [
        [1 * 6 + 1, 2 * 6 + 1, 3 * 6 + 1, 4 * 6 + 1, 1 * 6 + 0, 2 * 6 + 0, 3 * 6 + 0, 4 * 6 + 0],
        [4 * 6 + 4, 3 * 6 + 4, 2 * 6 + 4, 1 * 6 + 4, 4 * 6 + 5, 3 * 6 + 5, 2 * 6 + 5, 1 * 6 + 5],
    ],
    np.int32,
)

# layout index -> which of the 8 home slots hold blue pieces (host LAYOUTS)
_LAYOUT_BLUES = np.zeros((70, 8), bool)
for _i, _combo in enumerate(itertools.combinations(range(8), 4)):
    _LAYOUT_BLUES[_i, list(_combo)] = True

HOME = jnp.asarray(_HOME)
LAYOUT_BLUES = jnp.asarray(_LAYOUT_BLUES)
DIRX = jnp.asarray(_DIRS[:, 0])
DIRY = jnp.asarray(_DIRS[:, 1])


def _frame_sq(sq, color):
    """Board square <-> mover-frame square (White: 180-degree rotation)."""
    return jnp.where(color == 1, 35 - sq, sq)


def _frame_dir(d, color):
    return jnp.where(color == 1, 3 - d, d)


def _obs_from_fields(board, kind, counts, ply):
    """Both players' observation views from the raw state/record fields
    (board (M, 36), kind (M, 16), counts (M, 2, 2), ply (M,)) — shared by
    ``observation`` (live state) and ``view_obs_all`` (device-replay
    reconstruction from compact records), mirroring host observation()
    (geister.py:291-326): color bit, my-view bit, 4x onehot4 piece counts;
    7 planes with the opponent's piece types hidden; White sees the board
    180-degree rotated."""
    M = board.shape[0]
    c = (ply % 2).astype(jnp.int32)
    board = board.astype(jnp.int32)
    occupied = board >= 0
    owner = jnp.where(occupied, board // 8, -1)              # (M, 36)
    ptype = jnp.where(
        occupied, kind[jnp.arange(M)[:, None], jnp.clip(board, 0, 15)], -1
    )
    counts = counts.astype(jnp.int32)

    def onehot4(n):  # (M,) -> (M, 4) for values 1..4
        return (n[:, None] == jnp.arange(1, 5)[None, :]).astype(jnp.float32)

    scalars, boards = [], []
    for p in range(NUM_PLAYERS):
        me, opp = p, 1 - p
        my_view = (c == p).astype(jnp.float32)
        scalar = jnp.concatenate(
            [
                jnp.full((M, 1), 1.0 if me == 0 else 0.0),
                my_view[:, None],
                onehot4(counts[:, me, BLUE]),
                onehot4(counts[:, me, RED]),
                onehot4(counts[:, opp, BLUE]),
                onehot4(counts[:, opp, RED]),
            ],
            axis=1,
        )
        planes = jnp.stack(
            [
                jnp.ones((M, NUM_SQUARES), jnp.float32),
                (owner == me).astype(jnp.float32),
                (owner == opp).astype(jnp.float32),
                ((owner == me) & (ptype == BLUE)).astype(jnp.float32),
                ((owner == me) & (ptype == RED)).astype(jnp.float32),
                jnp.zeros((M, NUM_SQUARES), jnp.float32),
                jnp.zeros((M, NUM_SQUARES), jnp.float32),
            ],
            axis=1,
        )                                                    # (M, 7, 36)
        if p == 1:  # 180-degree rotation == reversed flat index
            planes = planes[:, :, ::-1]
        scalars.append(scalar)
        boards.append(planes.reshape(M, 7, SIZE, SIZE))
    return {
        "scalar": jnp.stack(scalars, axis=1),
        "board": jnp.stack(boards, axis=1),
    }


class VectorGeister:
    """Stateless namespace of batched transition functions."""

    num_actions = NUM_ACTIONS
    num_players = NUM_PLAYERS
    max_steps = MAX_PLY + 2
    simultaneous = False          # strict alternation, driver samples turn player
    step_reward = STEP_REWARD

    @staticmethod
    def init(n_lanes: int, key):
        del key  # placement layouts come from the policy, not env RNG
        B = n_lanes
        active = jnp.zeros((B, NUM_PLAYERS), bool).at[:, 0].set(True)
        return {
            "board": jnp.full((B, NUM_SQUARES), -1, jnp.int8),
            "pos": jnp.full((B, 16), -1, jnp.int8),
            "kind": jnp.zeros((B, 16), jnp.int8),
            "alive": jnp.zeros((B, 16), bool),
            "counts": jnp.zeros((B, 2, 2), jnp.int8),
            "ply": jnp.full((B,), -2, jnp.int32),
            "win": jnp.full((B,), -1, jnp.int8),
            "active": active,
            "done": jnp.zeros((B,), bool),
        }

    @staticmethod
    def reset_done(state, key):
        from .vector_common import reset_where_done

        fresh = VectorGeister.init(state["done"].shape[0], key)
        return reset_where_done(fresh, state)

    # -- transition ---------------------------------------------------------

    @staticmethod
    def step(state, actions, key):
        """Apply the turn player's action in every running lane; placement
        and move plies are handled as masked branches of one update
        (host play(), geister.py:183-214)."""
        del key
        B = actions.shape[0]
        rows = jnp.arange(B)
        live = ~state["done"] & (state["win"] == -1)
        c = (state["ply"] % 2).astype(jnp.int32)            # turn color
        a = jnp.take_along_axis(actions, c[:, None], axis=1)[:, 0]

        board, pos = state["board"], state["pos"]
        kind, alive, counts = state["kind"], state["alive"], state["counts"]
        win = state["win"]

        # ---- placement branch (ply < 0, host _place geister.py:163-175) ----
        setting = live & (state["ply"] < 0)
        layout = jnp.clip(a - NUM_MOVE_ACTIONS, 0, 69)
        blues = LAYOUT_BLUES[layout]                         # (B, 8)
        pids = c[:, None] * 8 + jnp.arange(8)[None, :]       # (B, 8)
        homes = HOME[c]                                      # (B, 8)
        sm = setting[:, None]
        pos = pos.at[rows[:, None], pids].set(
            jnp.where(sm, homes.astype(jnp.int8), jnp.take_along_axis(pos, pids, axis=1))
        )
        kind = kind.at[rows[:, None], pids].set(
            jnp.where(
                sm,
                jnp.where(blues, jnp.int8(BLUE), jnp.int8(RED)),
                jnp.take_along_axis(kind, pids, axis=1),
            )
        )
        alive = alive.at[rows[:, None], pids].set(
            sm | jnp.take_along_axis(alive, pids, axis=1)
        )
        board = board.at[rows[:, None], homes].set(
            jnp.where(sm, pids.astype(jnp.int8), jnp.take_along_axis(board, homes, axis=1))
        )
        counts = counts.at[rows, c].set(
            jnp.where(sm, jnp.int8(4), counts[rows, c])
        )

        # ---- move branch (ply >= 0, host play geister.py:187-211) ----------
        moving = live & (state["ply"] >= 0)
        sq = a % NUM_SQUARES
        d = jnp.clip(a // NUM_SQUARES, 0, 3)
        src = _frame_sq(sq, c)
        dr = _frame_dir(d, c)
        sx, sy = src // SIZE, src % SIZE
        nx, ny = sx + DIRX[dr], sy + DIRY[dr]
        onb = (nx >= 0) & (nx < SIZE) & (ny >= 0) & (ny < SIZE)
        dst = jnp.clip(nx, 0, SIZE - 1) * SIZE + jnp.clip(ny, 0, SIZE - 1)

        pid = jnp.take_along_axis(board, src[:, None], axis=1)[:, 0].astype(jnp.int32)
        pid_safe = jnp.clip(pid, 0, 15)

        # goal escape: mover removed, immediate win (host:191-194)
        escape = moving & ~onb
        # normal move, possibly capturing the enemy piece on dst
        normal = moving & onb
        victim = jnp.take_along_axis(board, dst[:, None], axis=1)[:, 0].astype(jnp.int32)
        cap = normal & (victim >= 0)
        victim_safe = jnp.clip(victim, 0, 15)
        vkind = kind[rows, victim_safe].astype(jnp.int32)

        # captures (host _capture:177-181): victim off board + counts--
        removed = jnp.where(cap, victim_safe, jnp.where(escape, pid_safe, 16))
        rem_valid = cap | escape
        rem_idx = jnp.clip(removed, 0, 15)
        pos = pos.at[rows, rem_idx].set(
            jnp.where(rem_valid, jnp.int8(-1), pos[rows, rem_idx])
        )
        alive = alive.at[rows, rem_idx].set(
            jnp.where(rem_valid, False, alive[rows, rem_idx])
        )
        rem_color = rem_idx // 8
        rem_kind = kind[rows, rem_idx].astype(jnp.int32)
        counts = counts.at[rows, rem_color, rem_kind].add(
            jnp.where(rem_valid, jnp.int8(-1), jnp.int8(0))
        )

        # board updates: clear src (escape or normal), place pid at dst
        board = board.at[rows, src].set(
            jnp.where(moving, jnp.int8(-1), board[rows, src])
        )
        board = board.at[rows, dst].set(
            jnp.where(normal, pid.astype(jnp.int8), board[rows, dst])
        )
        pos = pos.at[rows, pid_safe].set(
            jnp.where(normal, dst.astype(jnp.int8), pos[rows, pid_safe])
        )

        # wins (host:193-204): escape -> mover; last enemy blue captured ->
        # mover; last enemy red captured (fed) -> enemy wins
        enemy = c ^ 1
        wiped = cap & (counts[rows, enemy, vkind] == 0)
        win = jnp.where(escape, c.astype(jnp.int8), win)
        win = jnp.where(
            wiped & (vkind == BLUE), c.astype(jnp.int8), win
        )
        win = jnp.where(
            wiped & (vkind == RED), enemy.astype(jnp.int8), win
        )

        ply = state["ply"] + live.astype(jnp.int32)
        win = jnp.where(live & (ply >= MAX_PLY) & (win == -1), jnp.int8(2), win)

        ended = win != -1
        done = state["done"] | ended
        next_c = (ply % 2).astype(jnp.int32)
        active = (
            jax.nn.one_hot(next_c, NUM_PLAYERS, dtype=bool)
            & ~done[:, None]
        )
        return {
            "board": board,
            "pos": pos,
            "kind": kind,
            "alive": alive,
            "counts": counts,
            "ply": ply,
            "win": win,
            "active": active,
            "done": done,
        }

    # -- legality -----------------------------------------------------------

    @staticmethod
    def legal_mask_all(state):
        """(B, P, 214) bool.  The turn player's row is the true legal set
        (host legal_actions, geister.py:270-284); the idle player's row is
        all-True (sampled but never applied — the driver masks it out)."""
        B = state["board"].shape[0]
        rows = jnp.arange(B)
        c = (state["ply"] % 2).astype(jnp.int32)
        setting = state["ply"] < 0

        # move legality for all 16 pieces x 4 dirs, masked to the turn color
        pos = state["pos"].astype(jnp.int32)                 # (B, 16)
        owner = jnp.arange(16)[None, :] // 8                 # (1, 16)
        mine = state["alive"] & (owner == c[:, None])
        px, py = pos // SIZE, pos % SIZE
        nx = px[:, :, None] + DIRX[None, None, :]            # (B, 16, 4)
        ny = py[:, :, None] + DIRY[None, None, :]
        onb = (nx >= 0) & (nx < SIZE) & (ny >= 0) & (ny < SIZE)
        dst = jnp.clip(nx, 0, SIZE - 1) * SIZE + jnp.clip(ny, 0, SIZE - 1)
        dst_pid = state["board"][rows[:, None, None], dst].astype(jnp.int32)
        ok_onb = onb & ((dst_pid < 0) | (dst_pid // 8 != c[:, None, None]))
        # off-board: blues escaping through own goal squares
        # (host _GOALS: Black exits at y=5, White at y=0, via x=-1 or x=6)
        goal_y = jnp.where(c == 0, SIZE - 1, 0)[:, None, None]
        off_goal = (~onb) & ((nx == -1) | (nx == SIZE)) & (ny == goal_y)
        blue = state["kind"] == BLUE
        ok_off = off_goal & blue[:, :, None]
        valid = mine[:, :, None] & (ok_onb | ok_off)         # (B, 16, 4)

        fsq = _frame_sq(pos, c[:, None])                     # (B, 16)
        fdir = _frame_dir(jnp.arange(4)[None, None, :], c[:, None, None])
        idx = fdir * NUM_SQUARES + fsq[:, :, None]           # (B, 16, 4)
        idx = jnp.clip(idx, 0, NUM_MOVE_ACTIONS - 1)

        move_mask = jnp.zeros((B, NUM_ACTIONS), bool)
        move_mask = move_mask.at[rows[:, None, None], idx].max(valid)
        set_mask = (
            jnp.zeros((NUM_ACTIONS,), bool).at[NUM_MOVE_ACTIONS:].set(True)
        )[None, :] & setting[:, None]
        turn_row = jnp.where(setting[:, None], set_mask, move_mask)

        mask = jnp.ones((B, NUM_PLAYERS, NUM_ACTIONS), bool)
        return mask.at[rows, c].set(turn_row)

    # -- observation --------------------------------------------------------

    @staticmethod
    def observe_mask(state):
        """(B, P) — both players observe every step (the DRC hidden state
        must advance for the idle player too, host generation with
        ``observation: true``)."""
        return jnp.broadcast_to((~state["done"])[:, None], state["active"].shape)

    @staticmethod
    def observation(state):
        """{'scalar': (B, P, 18), 'board': (B, P, 7, 6, 6)} — per-player
        views mirroring host observation() (geister.py:291-326): color bit,
        my-view bit, 4x onehot4 piece counts; 7 planes with the opponent's
        piece types hidden; White sees the board 180-degree rotated."""
        return _obs_from_fields(
            state["board"], state["kind"], state["counts"], state["ply"]
        )

    @staticmethod
    def view_obs_all(compact):
        """Device twin of ``episode_obs``: rebuild BOTH players'
        {'scalar', 'board'} views from gathered compact records with any
        leading shape (N, T, ...) — the device-replay sampler's obs
        reconstruction (unmasked; the sampler applies observation_mask)."""
        lead = compact["board"].shape[:-1]                   # (N, T)
        flat = _obs_from_fields(
            compact["board"].reshape((-1, NUM_SQUARES)),
            compact["kind"].reshape((-1, 16)),
            compact["counts"].reshape((-1, 2, 2)),
            compact["ply"].reshape((-1,)),
        )
        return {k: v.reshape(lead + v.shape[1:]) for k, v in flat.items()}

    # -- streaming-rollout hooks --------------------------------------------

    @staticmethod
    def record(state):
        return {
            "board": state["board"],
            "kind": state["kind"],
            "counts": state["counts"],
            "ply": state["ply"],
        }

    @staticmethod
    def outcome_scores(state):
        """(B, P): +-1 for a win, zeros for a draw (host outcome(),
        geister.py:256-261)."""
        w = state["win"]
        black = (w == 0).astype(jnp.float32) - (w == 1).astype(jnp.float32)
        return jnp.stack([black, -black], axis=1)

    @staticmethod
    def episode_obs(compact, observing):
        """Rebuild the {'scalar', 'board'} pytree (T, P, ...) from the
        compact record, mirroring observation() in numpy."""
        board = compact["board"].astype(np.int32)            # (T, 36)
        kind = compact["kind"].astype(np.int32)              # (T, 16)
        counts = compact["counts"].astype(np.int32)          # (T, 2, 2)
        ply = compact["ply"].astype(np.int32)                # (T,)
        T = board.shape[0]
        c = ply % 2
        occupied = board >= 0
        owner = np.where(occupied, board // 8, -1)
        ptype = np.where(
            occupied, kind[np.arange(T)[:, None], np.clip(board, 0, 15)], -1
        )

        def onehot4(n):
            return (n[:, None] == np.arange(1, 5)[None, :]).astype(np.float32)

        scalars, boards = [], []
        for p in range(NUM_PLAYERS):
            me, opp = p, 1 - p
            scalar = np.concatenate(
                [
                    np.full((T, 1), 1.0 if me == 0 else 0.0, np.float32),
                    (c == p).astype(np.float32)[:, None],
                    onehot4(counts[:, me, BLUE]),
                    onehot4(counts[:, me, RED]),
                    onehot4(counts[:, opp, BLUE]),
                    onehot4(counts[:, opp, RED]),
                ],
                axis=1,
            )
            planes = np.stack(
                [
                    np.ones((T, NUM_SQUARES), np.float32),
                    (owner == me).astype(np.float32),
                    (owner == opp).astype(np.float32),
                    ((owner == me) & (ptype == BLUE)).astype(np.float32),
                    ((owner == me) & (ptype == RED)).astype(np.float32),
                    np.zeros((T, NUM_SQUARES), np.float32),
                    np.zeros((T, NUM_SQUARES), np.float32),
                ],
                axis=1,
            )
            if p == 1:
                planes = planes[:, :, ::-1]
            ob = observing[:, p, None]
            scalars.append(scalar * ob)
            boards.append(planes.reshape(T, 7, SIZE, SIZE) * ob[..., None, None])
        return {
            "scalar": np.stack(scalars, axis=1),
            "board": np.stack(boards, axis=1),
        }
