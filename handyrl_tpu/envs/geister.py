"""Geister — 2-player imperfect-information board game.

Behavioral parity with reference handyrl/envs/geister.py:169-537: same action
encoding (move = dir*36 + square in the mover's rotated frame, with
direction order [up, left, right, down]; set = 144 + layout index into the
70 = C(8,4) blue-piece layouts), same per-step reward (-0.01 both players),
200-ply draw, win by goal escape / capturing all enemy blues / being fed all
enemy reds, and the same 18-scalar + 7-plane observation with a 180-degree
rotated view for White.

Implementation is piece-table based: parallel arrays ``pos``/``kind``/
``alive`` indexed by piece id (0-7 Black, 8-15 White) plus a board of piece
ids as the single source of truth, rather than the reference's
board-of-codes + counts bookkeeping.  The net (DRC ConvLSTM) lives in
handyrl_tpu/models.
"""

from __future__ import annotations

import itertools
import random

import numpy as np

from .base import BaseEnvironment

BLACK, WHITE = 0, 1
BLUE, RED = 0, 1
SIZE = 6
NUM_MOVE_ACTIONS = 4 * SIZE * SIZE  # 144
NUM_SET_ACTIONS = 70

# Direction order matches the reference action encoding: up, left, right, down.
DIRS = np.array([(-1, 0), (0, -1), (0, 1), (1, 0)], dtype=np.int32)

# The 70 ways to pick which 4 of a player's 8 pieces are blue.
LAYOUTS = list(itertools.combinations(range(8), 4))

COL_CHARS, ROW_CHARS = "ABCDEF", "123456"

# Home squares (x, y) in placement order for each color.
_HOME = {
    BLACK: [(1, 1), (2, 1), (3, 1), (4, 1), (1, 0), (2, 0), (3, 0), (4, 0)],
    WHITE: [(4, 4), (3, 4), (2, 4), (1, 4), (4, 5), (3, 5), (2, 5), (1, 5)],
}

# Escape (goal) squares lie just off-board at each player's far corners.
_GOALS = {
    BLACK: ((-1, 5), (6, 5)),
    WHITE: ((-1, 0), (6, 0)),
}


def _on_board(x, y):
    return 0 <= x < SIZE and 0 <= y < SIZE


class Environment(BaseEnvironment):
    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    def reset(self, args=None):
        self.game_args = args or {}
        self.board = np.full((SIZE, SIZE), -1, dtype=np.int32)  # piece id or -1
        self.pos = np.full((16, 2), -1, dtype=np.int32)
        self.kind = np.zeros(16, dtype=np.int32)   # BLUE/RED (guess for hidden opponents)
        self.alive = np.zeros(16, dtype=bool)
        self.color = BLACK
        self.ply = -2                              # two placement plies before ply 0
        self.win_color = None                      # BLACK / WHITE / 2 (draw)
        self.moves: list[int] = []
        self.last_captured_kind = None
        self.layout_of = {}                        # color -> layout idx (-1 = hidden)
        # True remaining pieces per (color, kind).  Kept as explicit state —
        # NOT derived from guessed kinds — so replicas stay correct: every
        # layout has exactly 4 blue + 4 red, and captures are disclosed with
        # their true type, so these counts never rely on hidden information.
        self.counts = np.zeros((2, 2), dtype=np.int32)

    # -- coordinate/action codecs ------------------------------------------

    @staticmethod
    def _to_frame(p, color):
        """Map a board position into ``color``'s frame (White sees 180-rot)."""
        return (SIZE - 1 - p[0], SIZE - 1 - p[1]) if color == WHITE else (p[0], p[1])

    _from_frame = _to_frame  # the rotation is an involution

    @staticmethod
    def _frame_dir(d, color):
        return 3 - d if color == WHITE else d

    def _encode_move(self, board_pos, d, color):
        fx, fy = self._to_frame(board_pos, color)
        return self._frame_dir(d, color) * 36 + fx * 6 + fy

    def _decode_move(self, action, color):
        sq, d = action % 36, action // 36
        src = self._from_frame((sq // 6, sq % 6), color)
        d = self._frame_dir(d, color)
        dst = (src[0] + int(DIRS[d][0]), src[1] + int(DIRS[d][1]))
        return src, dst, d

    def action2str(self, a, player=None):
        if a >= NUM_MOVE_ACTIONS:
            return "s%d" % (a - NUM_MOVE_ACTIONS)
        src, dst, _ = self._decode_move(a, player)
        return self._pos_str(src) + self._pos_str(dst)

    def str2action(self, s, player=None):
        if s.startswith("s"):
            return NUM_MOVE_ACTIONS + int(s[1:])
        src = self._str_pos(s[:2])
        dst = self._str_pos(s[2:])
        if dst is None:  # goal escape: the unique goal square adjacent to src
            dst = next(
                g for g in _GOALS[player]
                if abs(g[0] - src[0]) + abs(g[1] - src[1]) == 1
            )
        delta = (dst[0] - src[0], dst[1] - src[1])
        d = next(i for i, dd in enumerate(DIRS) if (int(dd[0]), int(dd[1])) == delta)
        return self._encode_move(src, d, player)

    @staticmethod
    def _pos_str(p):
        return COL_CHARS[p[0]] + ROW_CHARS[p[1]] if _on_board(*p) else "**"

    @staticmethod
    def _str_pos(s):
        if s == "**":
            return None
        return (COL_CHARS.index(s[0]), ROW_CHARS.index(s[1]))

    # -- display ------------------------------------------------------------

    def __str__(self):
        glyphs = {(BLACK, BLUE): "B", (BLACK, RED): "R", (WHITE, BLUE): "b", (WHITE, RED): "r"}
        rows = ["  " + " ".join(ROW_CHARS)]
        for x in range(SIZE):
            cells = []
            for y in range(SIZE):
                pid = self.board[x, y]
                if pid < 0:
                    cells.append("_")
                else:
                    c = pid // 8
                    cells.append(glyphs[(c, int(self.kind[pid]))] if self.layout_of.get(c, -1) >= 0 else "*")
            rows.append(COL_CHARS[x] + " " + " ".join(cells))
        counts = self._piece_counts()
        rows.append(
            "remained = B:%d R:%d b:%d r:%d"
            % (counts[BLACK][BLUE], counts[BLACK][RED], counts[WHITE][BLUE], counts[WHITE][RED])
        )
        rows.append("turn = %-3d color = %s" % (self.ply, "BW"[self.color]))
        return "\n".join(rows)

    def _piece_counts(self):
        return {BLACK: list(self.counts[BLACK]), WHITE: list(self.counts[WHITE])}

    # -- transitions --------------------------------------------------------

    def _place(self, layout):
        """Apply a set action for the current color (layout < 0 = hidden/random)."""
        self.layout_of[self.color] = layout
        blues = set(LAYOUTS[layout if layout >= 0 else random.randrange(NUM_SET_ACTIONS)])
        for i, square in enumerate(_HOME[self.color]):
            pid = self.color * 8 + i
            self.pos[pid] = square
            self.kind[pid] = BLUE if i in blues else RED
            self.alive[pid] = True
            self.board[square] = pid
        self.counts[self.color] = (4, 4)
        self.color ^= 1
        self.ply += 1

    def _capture(self, pid):
        self.board[tuple(self.pos[pid])] = -1
        self.pos[pid] = (-1, -1)
        self.alive[pid] = False
        self.counts[pid // 8, int(self.kind[pid])] -= 1

    def play(self, action, player=None):
        if self.ply < 0:
            return self._place(action - NUM_MOVE_ACTIONS)

        src, dst, _ = self._decode_move(action, self.color)
        pid = int(self.board[src])
        self.last_captured_kind = None

        if not _on_board(*dst):
            # Escape through the goal: immediate win for the mover.
            self._capture(pid)
            self.win_color = self.color
        else:
            victim = int(self.board[dst])
            if victim >= 0:
                self._capture(victim)
                self.last_captured_kind = int(self.kind[victim])
                enemy = victim // 8
                if self.counts[enemy, int(self.kind[victim])] == 0:
                    # All enemy blues captured -> mover wins;
                    # all enemy reds captured -> mover loses (got baited).
                    self.win_color = self.color if self.kind[victim] == BLUE else enemy
            self.board[src] = -1
            self.board[dst] = pid
            self.pos[pid] = dst

        self.color ^= 1
        self.ply += 1
        self.moves.append(action)

        if self.ply >= 200 and self.win_color is None:
            self.win_color = 2  # draw

    # -- replica sync -------------------------------------------------------

    def diff_info(self, player=None):
        mover = (self.ply - 1) % 2
        info = {}
        if not self.moves:
            if self.ply > -2:  # at least one placement happened
                info["set"] = self.layout_of[mover] if player == mover else -1
        else:
            info["move"] = self.action2str(self.moves[-1], mover)
            if player == mover and self.last_captured_kind is not None:
                info["captured"] = "BR"[self.last_captured_kind]
        return info

    def update(self, info, reset):
        if reset:
            self.game_args = {**self.game_args, **info}
            self.reset(info)
        elif "set" in info:
            self._place(info["set"])
        elif "move" in info:
            action = self.str2action(info["move"], self.color)
            if "captured" in info:
                # Disclose the true type of the piece we just captured.
                _, dst, _ = self._decode_move(action, self.color)
                victim = int(self.board[dst])
                self.kind[victim] = "BR".index(info["captured"])
            self.play(action)

    # -- game state ---------------------------------------------------------

    def turn(self):
        return self.ply % 2

    def terminal(self):
        return self.win_color is not None

    def reward(self):
        return {p: -0.01 for p in self.players()}

    def outcome(self):
        if self.win_color == BLACK:
            return {0: 1, 1: -1}
        if self.win_color == WHITE:
            return {0: -1, 1: 1}
        return {0: 0, 1: 0}

    def _move_ok(self, color, ptype, src, dst):
        if _on_board(*dst):
            victim = int(self.board[dst])
            return victim < 0 or victim // 8 != color
        # Off-board moves are legal only for blues escaping through own goal.
        return ptype == BLUE and tuple(dst) in [tuple(g) for g in _GOALS[color]]

    def legal_actions(self, player=None):
        if self.ply < 0:
            return list(range(NUM_MOVE_ACTIONS, NUM_MOVE_ACTIONS + NUM_SET_ACTIONS))
        actions = []
        c = self.color
        for pid in range(c * 8, c * 8 + 8):
            if not self.alive[pid]:
                continue
            src = (int(self.pos[pid][0]), int(self.pos[pid][1]))
            ptype = int(self.kind[pid])
            for d in range(4):
                dst = (src[0] + int(DIRS[d][0]), src[1] + int(DIRS[d][1]))
                if self._move_ok(c, ptype, src, dst):
                    actions.append(self._encode_move(src, d, c))
        return actions

    def players(self):
        return [0, 1]

    # -- features -----------------------------------------------------------

    def observation(self, player=None):
        """{'scalar': (18,), 'board': (7, 6, 6)} from ``player``'s viewpoint."""
        my_view = player is None or player == self.turn()
        me = self.color if my_view else self.color ^ 1
        opp = me ^ 1
        counts = self._piece_counts()

        def onehot4(n):
            return [1.0 if n == i else 0.0 for i in range(1, 5)]

        scalar = np.array(
            [1.0 if me == BLACK else 0.0, 1.0 if my_view else 0.0]
            + onehot4(counts[me][BLUE]) + onehot4(counts[me][RED])
            + onehot4(counts[opp][BLUE]) + onehot4(counts[opp][RED]),
            dtype=np.float32,
        )

        owner = np.where(self.board >= 0, self.board // 8, -1)
        ptype = np.where(self.board >= 0, self.kind[np.clip(self.board, 0, 15)], -1)
        omniscient = player is None
        planes = np.stack(
            [
                np.ones((SIZE, SIZE)),
                owner == me,
                owner == opp,
                (owner == me) & (ptype == BLUE),
                (owner == me) & (ptype == RED),
                ((owner == opp) & (ptype == BLUE)) if omniscient else np.zeros((SIZE, SIZE), dtype=bool),
                ((owner == opp) & (ptype == RED)) if omniscient else np.zeros((SIZE, SIZE), dtype=bool),
            ]
        ).astype(np.float32)

        if me == WHITE:
            planes = np.rot90(planes, k=2, axes=(1, 2)).copy()

        return {"scalar": scalar, "board": planes}

    def action_size(self):
        return 214  # 144 move + 70 layout logits

    @staticmethod
    def vector_env():
        """Device-resident batched rules (streaming on-device self-play
        with the recurrent DRC net, runtime/device_rollout.py)."""
        from .vector_geister import VectorGeister

        return VectorGeister

    def transformer_spec(self):
        return {"num_actions": self.action_size(), "with_return": True}

    def default_net(self):
        from ..models import GeisterNet

        return GeisterNet()


if __name__ == "__main__":
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
