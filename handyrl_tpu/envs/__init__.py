"""Environment registry and factories.

Parity with handyrl/environment.py:9-36: known names map to modules, and an
unknown name is treated as a dotted import path so user environments plug in
without registration.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict

from .base import BaseEnvironment  # noqa: F401  (re-export)

ENVS = {
    "TicTacToe": "handyrl_tpu.envs.tictactoe",
    "Geister": "handyrl_tpu.envs.geister",
    "ParallelTicTacToe": "handyrl_tpu.envs.parallel_tictactoe",
    "HungryGeese": "handyrl_tpu.envs.hungry_geese",
    # the worked custom-env example, first-class so configs can say
    # `env: ConnectFour` — its device twin is autovec-lifted from pure
    # numpy rules (envs/autovec.py), no hand-written vector_* module
    "ConnectFour": "examples.connect_four",
}


def _resolve(env_args: Dict[str, Any]):
    name = env_args["env"]
    return importlib.import_module(ENVS.get(name, name))


def prepare_env(env_args: Dict[str, Any]) -> None:
    """Run a module-level ``prepare()`` hook once per process, if present."""
    module = _resolve(env_args)
    if hasattr(module, "prepare"):
        module.prepare()


def make_env(env_args: Dict[str, Any]) -> BaseEnvironment:
    module = _resolve(env_args)
    return module.Environment(env_args)
