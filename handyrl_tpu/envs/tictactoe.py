"""Tic-Tac-Toe — 2-player turn-based zero-sum game.

Behavioral parity with reference handyrl/envs/tictactoe.py:72-168 (same
action encoding 0..8 = row*3+col, same 'A1'-style strings, same 3x3x3
observation planes) but implemented on a flat 9-cell board with a
precomputed win-line table instead of per-move row/col/diag sums.
The net lives in handyrl_tpu/models (SimpleConvNet), not here.
"""

from __future__ import annotations

import random

import numpy as np

from .base import BaseEnvironment

# All 8 winning index triples on the flat board.
WIN_LINES = np.array(
    [
        [0, 1, 2], [3, 4, 5], [6, 7, 8],  # rows
        [0, 3, 6], [1, 4, 7], [2, 5, 8],  # cols
        [0, 4, 8], [2, 4, 6],             # diagonals
    ],
    dtype=np.int64,
)

ROWS, COLS = "ABC", "123"


class Environment(BaseEnvironment):
    BLACK, WHITE = 1, -1
    _GLYPH = {0: "_", 1: "O", -1: "X"}

    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    def reset(self, args=None):
        self.cells = np.zeros(9, dtype=np.int8)
        self.to_move = self.BLACK
        self.winner = 0  # +1 black, -1 white, 0 none
        self.history: list[int] = []

    # -- codecs -------------------------------------------------------------

    def action2str(self, a, player=None):
        return ROWS[a // 3] + COLS[a % 3]

    def str2action(self, s, player=None):
        return ROWS.index(s[0]) * 3 + COLS.index(s[1])

    def __str__(self):
        grid = self.cells.reshape(3, 3)
        lines = ["  " + " ".join(COLS)]
        for r in range(3):
            lines.append(ROWS[r] + " " + " ".join(self._GLYPH[int(v)] for v in grid[r]))
        lines.append("record = " + " ".join(self.action2str(a) for a in self.history))
        return "\n".join(lines)

    # -- transitions --------------------------------------------------------

    def play(self, action, player=None):
        self.cells[action] = self.to_move
        if any(self.cells[line].sum() == 3 * self.to_move for line in WIN_LINES[self._lines_through(action)]):
            self.winner = self.to_move
        self.to_move = -self.to_move
        self.history.append(action)

    @staticmethod
    def _lines_through(action):
        return [i for i, line in enumerate(WIN_LINES) if action in line]

    # -- replica sync -------------------------------------------------------

    def diff_info(self, player=None):
        return self.action2str(self.history[-1]) if self.history else ""

    def update(self, info, reset):
        if reset:
            self.reset()
        else:
            self.play(self.str2action(info))

    # -- game state ---------------------------------------------------------

    def turn(self):
        return len(self.history) % 2

    def terminal(self):
        return self.winner != 0 or len(self.history) == 9

    def outcome(self):
        score = {0: 0, 1: 0}
        if self.winner == self.BLACK:
            score = {0: 1, 1: -1}
        elif self.winner == self.WHITE:
            score = {0: -1, 1: 1}
        return score

    def legal_actions(self, player=None):
        return np.flatnonzero(self.cells == 0).tolist()

    def players(self):
        return [0, 1]

    @staticmethod
    def vector_env():
        """Device-resident twin (pure jnp transitions) for fully on-device
        self-play (runtime/device_rollout.py)."""
        from .vector_tictactoe import VectorTicTacToe

        return VectorTicTacToe

    def observation(self, player=None):
        """3 planes (C, 3, 3): [is-my-turn-view, my stones, opponent stones]."""
        my_view = player is None or player == self.turn()
        me = self.to_move if my_view else -self.to_move
        grid = self.cells.reshape(3, 3)
        return np.stack(
            [
                np.full((3, 3), 1.0 if my_view else 0.0),
                grid == me,
                grid == -me,
            ]
        ).astype(np.float32)

    def action_size(self):
        return 9

    def default_net(self):
        from ..models import SimpleConvNet

        return SimpleConvNet()


class TicTacToeRules:
    """Pure single-game numpy rules to the autovec liftability contract
    (envs/autovec.py) — the same rules as ``Environment`` and the
    hand-written ``VectorTicTacToe`` twin.

    This namespace exists as the apples-to-apples yardstick for the
    twin-less path: the ``league`` bench stage lifts it with
    ``autovectorize`` and measures per-chip self-play throughput against
    the hand-written ``vector_tictactoe.VectorTicTacToe`` — same game,
    same net, so the frac isolates the cost of the lift itself.
    Bit-parity of every observable against the hand twin is pinned by
    tests/test_autovec.py.

    State (one game): ``cells`` (9,) int8, ``winner`` () int8.
    """

    num_actions = 9
    max_steps = 9
    num_players = 2

    @staticmethod
    def _color(step: int) -> int:
        return 1 if step % 2 == 0 else -1

    @staticmethod
    def init():
        return {
            "cells": np.zeros(9, np.int8),
            "winner": np.zeros((), np.int8),
        }

    @staticmethod
    def observation(state, step: int):
        """(3, 3, 3) planes for the turn player — identical to
        ``VectorTicTacToe.observation``: [my-view ones, my stones,
        opponent stones]."""
        me = TicTacToeRules._color(step)
        grid = state["cells"].reshape(3, 3)
        return np.stack(
            [
                np.ones((3, 3), np.float32),
                (grid == me).astype(np.float32),
                (grid == -me).astype(np.float32),
            ]
        )

    @staticmethod
    def legal_mask(state):
        return state["cells"] == 0

    @staticmethod
    def terminal(state, step: int):
        return (state["winner"] != 0) | (step >= 9)

    @staticmethod
    def apply(state, action, step: int):
        me = TicTacToeRules._color(step)
        cells = np.where(np.arange(9) == action, np.int8(me), state["cells"])
        lines = cells[WIN_LINES]                              # (8, 3)
        won = (lines.sum(axis=-1) == 3 * me).any()
        winner = np.where(won, np.int8(me), state["winner"]).astype(np.int8)
        return {"cells": cells, "winner": winner}

    @staticmethod
    def outcome(state):
        w = state["winner"].astype(np.float32)
        return np.stack([w, -w])


if __name__ == "__main__":
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
