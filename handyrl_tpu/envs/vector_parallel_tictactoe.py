"""Vectorized Parallel (simultaneous-move) Tic-Tac-Toe as pure jnp
transitions — the simultaneous-move counterpart of vector_tictactoe.py,
driven by the streaming device rollout (runtime/device_rollout.py).

Rules parity with the host env (envs/parallel_tictactoe.py:29-38, itself
matching reference parallel_tictactoe.py:13-59): both players submit a
legal move every step; a uniformly random submitter's action is applied
with that player's color; the game ends on a completed line or a full
board.  Lock-step parity is enforced by tests/test_device_rollout.py
(device transitions replayed through the host ``_apply``).

State (per lane):
    cells        (B, 9) int8   0 empty / +1 player 0 / -1 player 1
    winner       (B,)   int8
    last_chooser (B,)   int8   whose action was applied last step (-1 none)
    active       (B, P) bool   both players until the game ends
    done         (B,)   bool
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tictactoe import WIN_LINES

NUM_PLAYERS = 2
NUM_ACTIONS = 9
COLORS = (1, -1)  # player index -> stone color (host BLACK, WHITE)

_LINES = np.asarray(WIN_LINES)  # (8, 3)


class VectorParallelTicTacToe:
    """Stateless namespace of batched transition functions."""

    num_actions = NUM_ACTIONS
    num_players = NUM_PLAYERS
    max_steps = 9
    simultaneous = True

    @staticmethod
    def init(n_lanes: int, key):
        del key  # the empty board is deterministic
        return {
            "cells": jnp.zeros((n_lanes, 9), jnp.int8),
            "winner": jnp.zeros((n_lanes,), jnp.int8),
            "last_chooser": jnp.full((n_lanes,), -1, jnp.int8),
            "active": jnp.ones((n_lanes, NUM_PLAYERS), bool),
            "done": jnp.zeros((n_lanes,), bool),
        }

    @staticmethod
    def reset_done(state, key):
        from .vector_common import reset_where_done

        fresh = VectorParallelTicTacToe.init(state["done"].shape[0], key)
        return reset_where_done(fresh, state)

    @staticmethod
    def observation(state):
        """(B, P, 3, 3, 3): per-player planes [always-acting ones, my
        stones, opponent stones] (host observation(),
        envs/parallel_tictactoe.py:59-70)."""
        grid = state["cells"].reshape(-1, 1, 3, 3)           # (B, 1, 3, 3)
        colors = jnp.asarray(COLORS, jnp.int8)[None, :, None, None]
        mine = (grid == colors).astype(jnp.float32)
        theirs = (grid == -colors).astype(jnp.float32)
        ones = jnp.ones_like(mine)
        return jnp.stack([ones, mine, theirs], axis=2)       # (B, P, 3, 3, 3)

    @staticmethod
    def legal_mask_all(state):
        """(B, P, 9) bool — empty cells, identical for both players."""
        empty = state["cells"] == 0                          # (B, 9)
        return jnp.broadcast_to(empty[:, None, :], empty.shape[:1] + (NUM_PLAYERS, 9))

    @staticmethod
    def step(state, actions, key):
        """Uniformly pick one player per lane and apply their action with
        their color (host step(), envs/parallel_tictactoe.py:29-38);
        finished lanes pass through unchanged."""
        B = actions.shape[0]
        live = ~state["done"] & (state["winner"] == 0)
        chooser = jax.random.bernoulli(key, 0.5, (B,)).astype(jnp.int32)  # 0/1
        action = jnp.take_along_axis(actions, chooser[:, None], axis=1)[:, 0]
        color = jnp.where(chooser == 0, jnp.int8(1), jnp.int8(-1))

        onehot = jax.nn.one_hot(action, 9, dtype=jnp.int8)
        place = onehot * live[:, None].astype(jnp.int8)
        cells = jnp.where(place > 0, color[:, None], state["cells"])

        lines = cells[:, jnp.asarray(_LINES)]                # (B, 8, 3)
        won = (lines.sum(axis=-1) == 3 * color[:, None].astype(jnp.int32)).any(axis=-1) & live
        winner = jnp.where(won, color, state["winner"])

        full = (cells != 0).all(axis=1)
        ended = (winner != 0) | full
        return {
            "cells": cells,
            "winner": winner,
            "last_chooser": jnp.where(live, chooser.astype(jnp.int8), state["last_chooser"]),
            "active": jnp.broadcast_to((~ended)[:, None], state["active"].shape),
            "done": state["done"] | ended,
        }

    # -- streaming-rollout hooks --------------------------------------------

    @staticmethod
    def record(state):
        return {"cells": state["cells"], "last_chooser": state["last_chooser"]}

    @staticmethod
    def outcome_scores(state):
        """(B, P): (+1, -1) for a player-0 win, (-1, +1) for player 1, zeros
        for a draw (host outcome(), envs/tictactoe.py:94-99)."""
        w = state["winner"].astype(jnp.float32)
        return jnp.stack([w, -w], axis=1)

    @staticmethod
    def view_obs(compact, player):
        """Device-side single-player observation planes per row:
        ``compact['cells']`` (N, T, 9) + ``player`` (N,) int32 ->
        (N, T, 3, 3, 3), the same planes as observation()/episode_obs()
        for that player (device-replay hook, runtime/device_replay.py).
        Unmasked: the caller applies the observation mask."""
        grid = compact["cells"].astype(jnp.int8).reshape(
            compact["cells"].shape[:2] + (3, 3)
        )                                                    # (N, T, 3, 3)
        color = jnp.asarray(COLORS, jnp.int8)[player][:, None, None, None]
        mine = (grid == color).astype(jnp.float32)
        theirs = (grid == -color).astype(jnp.float32)
        return jnp.stack([jnp.ones_like(mine), mine, theirs], axis=2)

    @staticmethod
    def episode_obs(compact, active):
        """(T, P, 3, 3, 3) from recorded cells, mirroring observation()."""
        cells = compact["cells"].astype(np.int8)             # (T, 9)
        grid = cells.reshape(-1, 1, 3, 3)
        colors = np.asarray(COLORS, np.int8)[None, :, None, None]
        mine = (grid == colors).astype(np.float32)
        theirs = (grid == -colors).astype(np.float32)
        obs = np.stack([np.ones_like(mine), mine, theirs], axis=2)
        return obs * active[..., None, None, None]
