"""CPU edge replica: the ONNX export path stood up as a serving backend.

The export artifacts (models/export.py) already freeze a policy into a
runtime-independent file; this module puts one behind the serving wire
protocol so the fleet router (router_tier.py) can register it as cheap
feed-forward capacity — registered with the ``edge`` capability tag, so
stateful routes (sessions / wire hidden state) and hot-swap propagation
never land here.  Any object with the ``inference_batch(obs, hidden)``
artifact API serves; ``edge_main`` loads an ``OnnxModel``
(onnxruntime CPUExecutionProvider — an optional dependency, absent from
the base image, so the loader gates on it with a clear error).

No continuous batcher on purpose: an edge artifact is a single-threaded
CPU session and the onnxruntime/TF runtimes batch internally poorly —
``edge_workers`` request threads each running batch-1 inference is the
honest shape of this capacity, and the router's load scoring (queue
depth via the stats frame) keeps it from being oversubscribed.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..runtime.connection import (
    QueueCommunicator,
    accept_socket_connections,
    open_socket_connection,
)
from ..utils import tree_map
from ..utils.trace import trace_event

__all__ = ["EdgeReplica", "edge_main"]


class EdgeReplica(QueueCommunicator):
    """Wire-compatible serving backend over one frozen artifact.

    Speaks the replica subset the router actually proxies: ``infer``
    (feed-forward only — a ``sid`` or wire ``hidden`` is refused loudly,
    the router's ``edge`` tag means they should never arrive) and
    ``stats`` (a serve_*-shaped record so the router's load scoring
    works unchanged).  ``swap``/``open_session`` are bad_request: an
    edge artifact is immutable and stateless by construction.
    """

    def __init__(self, model, port: int = 9995, workers: int = 2):
        super().__init__(recv_timeout=None, send_queue_size=1024)
        self.model = model
        self.port = int(port)
        self.workers = max(1, int(workers))
        self.bound_port: Optional[int] = None
        self._stats_lock = threading.Lock()
        self.requests_in = 0
        self.replies = 0
        self.errors: Dict[str, int] = {}
        self._depth = 0
        self._sock = None

    def run(self) -> "EdgeReplica":
        self._sock = open_socket_connection(self.port)
        self.bound_port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        for i in range(self.workers):
            threading.Thread(
                target=self._serve_loop, daemon=True, name=f"edge-worker-{i}"
            ).start()
        return self

    def shutdown(self) -> None:
        super().shutdown()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        for conn in accept_socket_connections(timeout=0.5, sock=self._sock):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)

    def _serve_loop(self) -> None:
        while not self.shutdown_flag:
            try:
                conn, frame = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            try:
                req, data = frame
            except (TypeError, ValueError):
                continue
            if req == "heartbeat" or req == "__hb__":
                continue
            if not isinstance(data, dict):
                data = {}
            rid = data.get("rid")
            try:
                if req == "infer":
                    self._handle_infer(conn, rid, data)
                elif req == "stats":
                    self.send(conn, ("stats",
                                     {"rid": rid, "stats": self.stats_record()}))
                else:
                    # swap / open_session / close_session / unknown: an
                    # edge artifact is immutable and stateless — say so
                    self._error(conn, rid, "bad_request",
                                f"edge replica cannot serve {req!r} "
                                "(frozen feed-forward artifact)")
            except Exception as exc:
                # worker threads are the serving capacity: no frame may
                # kill one (same contract as ServingServer._dispatch)
                self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_infer(self, conn, rid, data: Dict[str, Any]) -> None:
        with self._stats_lock:
            self.requests_in += 1
            self._depth += 1
        try:
            if data.get("sid") is not None or data.get("hidden") is not None:
                self._error(conn, rid, "bad_request",
                            "edge replica is feed-forward only (no session "
                            "cache, no recurrent state) — route stateful "
                            "requests to a full serving replica")
                return
            t0 = time.monotonic()
            obs = tree_map(lambda x: np.asarray(x)[None], data.get("obs"))
            out = self.model.inference_batch(obs)
            out = tree_map(lambda x: np.asarray(x)[0], out)
            trace_event("serve.request", time.monotonic() - t0, t0=t0,
                        plane="fleet", ok=True, edge=True)
            with self._stats_lock:
                self.replies += 1
            # model 0 = "the frozen artifact": edge capacity serves one
            # immutable version, there is no router generation to report
            self.send(conn, ("result", {"rid": rid, "model": 0, "out": out}))
        finally:
            with self._stats_lock:
                self._depth -= 1

    def _error(self, conn, rid, kind: str, msg: str) -> None:
        with self._stats_lock:
            self.errors[kind] = self.errors.get(kind, 0) + 1
        self.send(conn, ("error", {"rid": rid, "kind": kind, "msg": msg}))

    def stats_record(self) -> Dict[str, Any]:
        """serve_*-shaped so FleetRouter._Replica.score_from reads edge
        and full replicas identically; keys are the METRIC_KEYS subset an
        artifact backend can honestly report (no batcher, no swaps)."""
        with self._stats_lock:
            return {
                "serve_requests": self.requests_in,
                "serve_replies": self.replies,
                "serve_depth": self._depth,
                "serve_shed": 0,
                "serve_errors": sum(self.errors.values()),
                "serve_connections": self.connection_count(),
            }


def edge_main(args: Dict[str, Any]) -> None:
    """``main.py --edge <artifact.onnx>``: serve a frozen export artifact
    as fleet edge capacity (register it in ``fleet.replicas`` with the
    ``edge`` tag)."""
    train = args["train_args"]
    fleet_cfg = train.get("fleet", {})
    path = args.get("edge_model") or fleet_cfg.get("edge_model")
    if not path:
        raise ValueError(
            "no edge artifact: pass it on the command line "
            "(main.py --edge model.onnx) or set fleet.edge_model"
        )
    from ..models.export import ExportedModel, OnnxModel

    # .onnx needs the optional onnxruntime; the jax.export artifact
    # (.jaxm) runs on the baked-in toolchain — both serve identically.
    # Quantized exports (model.int8.onnx, scripts/export_model.py) land
    # in the same branch: the dequantize rides inside the graph as
    # Cast/Mul nodes, so the ~2x-smaller artifact needs no loader support
    model = OnnxModel(path) if str(path).endswith(".onnx") else ExportedModel(path)
    replica = EdgeReplica(
        model,
        port=int(fleet_cfg.get("edge_port", 9995)),
        workers=int(fleet_cfg.get("edge_workers", 2)),
    ).run()
    print(f"edge: serving {path} on port {replica.bound_port} "
          f"({replica.workers} workers)")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        print("edge: shutting down")
    finally:
        replica.shutdown()
