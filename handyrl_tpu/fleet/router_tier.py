"""Fleet front-end: session-affinity routing over N serving replicas.

The horizontal tier ROADMAP item 2 names (docs/serving.md §Fleet tier):
one entry port accepts thousands of ``ServingClient`` connections and
proxies their rid-pipelined frames to backend ``ServingServer`` replicas
— the plane-split discipline (front-end vs compute) applied to
inference.  Composition of machinery already banked, nothing novel on
the wire:

* transport: the framed-socket hub (``QueueCommunicator``) on the client
  side, one pipelined ``ServingClient`` per backend replica — the proxy
  speaks the replica protocol as an ordinary client, so replicas need no
  fleet awareness;
* balancing: new sessions and stateless requests land on the live
  replica with the lowest load score — queue depth + shed rate from the
  existing ``stats`` frame, polled on ``stats_poll_s``;
* affinity: an ``infer`` carrying a ``sid`` follows the session to the
  replica that owns its hidden state (fleet/sessions.py).  When that
  replica dies the session is re-pointed to a survivor, which serves it
  fresh-state and counts the affinity miss — degraded loudly, never a
  hang;
* failure: a replica that drops its connection (or goes silent past the
  client stall deadline) fails every in-flight proxied request with a
  loud ``replica_lost`` error kind, is reaped from rotation, and is
  re-joined with exponential backoff (the PR 2 rejoin discipline);
* fleet-wide hot-swap: one ``swap`` frame at the front propagates
  replica-by-replica — each replica runs its own zero-drop
  warm-then-flip while the others keep serving, so the tier as a whole
  drops nothing;
* capabilities: replicas registered with the ``edge`` tag (the ONNX CPU
  backend, fleet/edge.py) receive only feed-forward traffic — stateful
  routes (sessions / wire hidden) and swap propagation skip them.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ..runtime.connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    open_socket_connection,
)
from ..serving.client import ServingClient, ServingError
from ..utils.metrics import append_metrics_record
from ..utils.trace import trace_event

__all__ = ["FleetRouter", "ReplicaSpec", "fleet_main"]

# stats-frame shed rate is weighted against raw queue depth when scoring
# replicas: one shed in the last window outweighs ~100 queued requests,
# because shedding proves the replica is ALREADY past its SLO capacity
_SHED_WEIGHT = 100.0


class ReplicaSpec:
    """One backend's address + capability tags (config-registered)."""

    __slots__ = ("host", "port", "tags", "name")

    def __init__(self, host: str, port: int, tags=()):
        self.host = str(host)
        self.port = int(port)
        self.tags = frozenset(str(t) for t in tags)
        self.name = f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, entry) -> "ReplicaSpec":
        """'host:port' strings or {'host', 'port', 'tags'?} dicts — the
        two spellings ``fleet.replicas`` accepts (config.py validates)."""
        if isinstance(entry, cls):
            return entry
        if isinstance(entry, str):
            host, _, port = entry.rpartition(":")
            return cls(host or "127.0.0.1", int(port))
        return cls(entry["host"], entry["port"], entry.get("tags", ()))


class _Replica:
    """Live state for one backend: its proxy client, liveness, and the
    last-polled load score."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.client: Optional[ServingClient] = None
        self.alive = False
        self.load = 0.0
        self.picked = 0  # tie-break: spread equal-load picks round-robin
        self._last_stats: Dict[str, Any] = {}
        self.lock = threading.Lock()

    @property
    def is_edge(self) -> bool:
        return "edge" in self.spec.tags

    def score_from(self, stats: Dict[str, Any]) -> float:
        """Load score from a stats-frame record: instantaneous queue depth
        plus the shed rate over the window since the previous poll."""
        prev = self._last_stats
        self._last_stats = stats
        depth = float(stats.get("serve_depth") or 0.0)
        shed = float(stats.get("serve_shed") or 0.0)
        requests = float(stats.get("serve_requests") or 0.0)
        d_shed = max(0.0, shed - float(prev.get("serve_shed") or 0.0))
        d_req = max(1.0, requests - float(prev.get("serve_requests") or 0.0))
        return depth + _SHED_WEIGHT * (d_shed / d_req)


class FleetRouter(QueueCommunicator):
    """Entry-port front-end proxying infer/stats/swap/session frames to a
    fleet of serving replicas."""

    def __init__(
        self,
        fleet_cfg: Dict[str, Any],
        metrics_path: Optional[str] = None,
    ):
        cfg = dict(fleet_cfg or {})
        super().__init__(
            recv_timeout=None,
            # same reasoning as ServingServer: reply bursts to a pipelining
            # client are the product, not a fault signal
            send_queue_size=1024,
        )
        self.port = int(cfg.get("port", 9996))
        self.bound_port: Optional[int] = None
        self.stats_poll_s = float(cfg.get("stats_poll_s", 2.0))
        self.replica_stall_s = float(cfg.get("replica_stall_s", 30.0))
        self.backoff_s = float(cfg.get("rejoin_backoff_s", 1.0))
        self.backoff_max_s = float(cfg.get("rejoin_backoff_max_s", 30.0))
        self.stats_interval = float(cfg.get("stats_interval", 30.0))
        self._metrics_path = metrics_path
        self.replicas: List[_Replica] = [
            _Replica(ReplicaSpec.parse(e)) for e in cfg.get("replicas", ())
        ]
        if not self.replicas:
            raise ValueError("fleet.replicas is empty — nothing to route to")
        # sid -> replica owning its hidden state.  Entries re-point to a
        # survivor when the owner dies (the new owner then counts an
        # affinity miss and serves the session fresh-state)
        self._affinity: Dict[str, _Replica] = {}
        self._affinity_lock = threading.Lock()
        # blocking control ops (session open/close, swap propagation,
        # stats fan-out) run here, never on the dispatch thread
        self._ctl_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-ctl"
        )
        self._rejoining: set = set()
        self._stats_lock = threading.Lock()
        self.requests_in = 0
        self.replies = 0
        self.errors: Dict[str, int] = {}
        self.sessions_routed = 0
        self.replicas_lost = 0
        self.hot_swaps = 0
        self._stats_t0 = time.monotonic()
        self._stats_served0 = 0
        self._sock = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def run(self, connect_timeout: float = 30.0) -> "FleetRouter":
        """Connect the replica fleet (each with retry — replicas may still
        be booting), then bind the entry port and start serving."""
        for rep in self.replicas:
            try:
                self._connect(rep, retry_seconds=connect_timeout)
            except OSError as exc:
                # a replica down at boot is the same as one lost later:
                # route around it and let the rejoin loop chase it
                print(f"fleet: replica {rep.spec.name} unreachable at start "
                      f"({exc}); rejoining in background")
                self._mark_lost(rep)
        if not any(r.alive for r in self.replicas):
            raise ConnectionError("fleet: no replica reachable at startup")
        self._sock = open_socket_connection(self.port)
        self._sock.listen(1024)
        self.bound_port = self._sock.getsockname()[1]
        targets = [self._accept_loop, self._dispatch, self._poll_loop]
        if self._metrics_path and self.stats_interval > 0:
            targets.append(self._metrics_loop)
        for target in targets:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        super().shutdown()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._ctl_pool.shutdown(wait=False)
        for rep in self.replicas:
            with rep.lock:
                client, rep.client, rep.alive = rep.client, None, False
            if client is not None:
                client.close()

    def _accept_loop(self) -> None:
        for conn in accept_socket_connections(timeout=0.5, sock=self._sock):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)

    # -- replica fleet ------------------------------------------------------

    def _connect(self, rep: _Replica, retry_seconds: float = 0.0) -> None:
        client = ServingClient(
            rep.spec.host, rep.spec.port,
            retry_seconds=retry_seconds,
            # the stall deadline turns a silent replica into a named
            # failure on every pending proxied request — bounded failover
            stall_timeout=self.replica_stall_s or None,
        )
        with rep.lock:
            rep.client = client
            rep.alive = True
            rep.load = 0.0

    def _mark_lost(self, rep: _Replica) -> None:
        """Reap a dead replica: fail-fast state, count the loss, schedule
        the backoff rejoin.  Idempotent under racing reporters (the poll
        loop, several reply callbacks)."""
        with rep.lock:
            was_alive, rep.alive = rep.alive, False
            client, rep.client = rep.client, None
        if client is not None:
            client.close()
        if was_alive:
            with self._stats_lock:
                self.replicas_lost += 1
            print(f"fleet: replica {rep.spec.name} lost; "
                  f"re-routing its sessions, rejoining with backoff")
        with self._stats_lock:
            if rep in self._rejoining:
                return
            self._rejoining.add(rep)
        threading.Thread(
            target=self._rejoin_loop, args=(rep,), daemon=True,
            name=f"fleet-rejoin-{rep.spec.name}",
        ).start()

    def _rejoin_loop(self, rep: _Replica) -> None:
        """PR 2 discipline: exponential backoff, capped, forever — a
        replica that restarts rejoins the rotation without operator help."""
        backoff = self.backoff_s
        try:
            while not self.shutdown_flag:
                time.sleep(backoff)
                if self.shutdown_flag:
                    return
                try:
                    self._connect(rep)
                    print(f"fleet: replica {rep.spec.name} rejoined")
                    return
                except OSError:
                    backoff = min(backoff * 2.0, self.backoff_max_s)
        finally:
            with self._stats_lock:
                self._rejoining.discard(rep)

    def _live(self, stateful: bool) -> List[_Replica]:
        return [
            r for r in self.replicas
            if r.alive and not (stateful and r.is_edge)
        ]

    def _pick(self, stateful: bool) -> Optional[_Replica]:
        """Lowest-load live replica (capability-filtered); None when the
        whole (eligible) fleet is down."""
        t0 = time.monotonic()
        candidates = self._live(stateful)
        if not candidates:
            return None
        rep = min(candidates, key=lambda r: (r.load, r.picked))
        rep.picked += 1
        trace_event("fleet.route", time.monotonic() - t0, t0=t0,
                    plane="fleet", replicas=len(candidates))
        return rep

    def _poll_loop(self) -> None:
        """The balancing signal: shed-rate/queue-depth via the existing
        stats frame, each replica polled on its own pool task so one
        stalled replica never delays the others' scores."""
        while not self.shutdown_flag:
            time.sleep(self.stats_poll_s)
            if self.shutdown_flag:
                return
            for rep in self.replicas:
                if rep.alive:
                    self._ctl_pool.submit(self._poll_one, rep)

    def _poll_one(self, rep: _Replica) -> None:
        client = rep.client
        if client is None:
            return
        try:
            stats = client.stats(timeout=max(self.stats_poll_s * 4, 10.0))
        except Exception:
            self._mark_lost(rep)
            return
        rep.load = rep.score_from(stats or {})

    # -- request dispatch ----------------------------------------------------

    def _dispatch(self) -> None:
        while not self.shutdown_flag:
            try:
                conn, frame = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            try:
                req, data = frame
            except (TypeError, ValueError):
                continue
            if req == "heartbeat" or req == "__hb__":
                continue
            if not isinstance(data, dict):
                data = {}
            rid = data.get("rid")
            try:
                if req == "infer":
                    self._handle_infer(conn, data)
                elif req == "open_session":
                    self._ctl_pool.submit(self._handle_open_session, conn, data)
                elif req == "close_session":
                    self._ctl_pool.submit(self._handle_close_session, conn, data)
                elif req == "stats":
                    self._ctl_pool.submit(self._handle_stats, conn, rid)
                elif req == "swap":
                    self._ctl_pool.submit(self._handle_swap, conn, data)
                else:
                    self._error(conn, rid, "bad_request",
                                f"unknown request {req!r}")
            except Exception as exc:
                # THE dispatch thread: no frame may kill it (see
                # ServingServer._dispatch — same contract)
                self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_infer(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        with self._stats_lock:
            self.requests_in += 1
        arrival = time.monotonic()
        rid = data.get("rid")
        sid = data.get("sid")
        stateful = sid is not None or data.get("hidden") is not None
        rep = None
        if sid is not None:
            with self._affinity_lock:
                rep = self._affinity.get(sid)
            if rep is not None and not rep.alive:
                rep = None  # owner died: re-route below
        if rep is None:
            rep = self._pick(stateful)
            if rep is None:
                self._error(conn, rid, "no_replica",
                            "no live replica can serve this request "
                            f"(stateful={stateful})")
                return
            if sid is not None:
                # session re-pointed (first infer, or owner lost): the new
                # owner serves fresh-state and counts the affinity miss
                with self._affinity_lock:
                    self._affinity[sid] = rep
        client = rep.client
        if client is None:
            self._error(conn, rid, "replica_lost",
                        f"replica {rep.spec.name} lost before proxy")
            return
        fut = client.submit(
            data.get("obs"), data.get("model", -1), data.get("hidden"),
            data.get("slo_ms"), sid=sid,
        )
        fut.add_done_callback(
            lambda f, c=conn, r=rid, p=rep, a=arrival: self._relay(c, r, p, f, a)
        )

    def _relay(self, conn: FramedConnection, rid, rep: _Replica, fut: Future,
               arrival: float) -> None:
        """Reply callback for a proxied infer: forward the result/error to
        the fronted client under ITS rid; a transport-level failure means
        the replica itself is gone — loud replica_lost, never a hang."""
        exc = fut.exception()
        trace_event("fleet.proxy", time.monotonic() - arrival, t0=arrival,
                    plane="fleet", ok=exc is None, replica=rep.spec.name)
        if exc is None:
            d = fut.result()
            reply = {"rid": rid, "model": d.get("model"), "out": d.get("out")}
            if "sid" in d:
                reply["sid"] = d["sid"]
            with self._stats_lock:
                self.replies += 1
            self.send(conn, ("result", reply))
            return
        if isinstance(exc, ServingError) and exc.kind != "stalled":
            # a request-level failure (shed/deadline/bad_request/...) is
            # the replica WORKING as designed: forward it verbatim
            self._error(conn, rid, exc.kind, str(exc))
            return
        # connection loss or stall deadline: the replica is gone
        self._mark_lost(rep)
        self._error(conn, rid, "replica_lost",
                    f"replica {rep.spec.name} lost mid-request "
                    f"({type(exc).__name__}: {exc})")

    # -- control frames (pool) ----------------------------------------------

    def _handle_open_session(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        rid = data.get("rid")
        try:
            rep = self._pick(stateful=True)
            if rep is None or rep.client is None:
                self._error(conn, rid, "no_replica",
                            "no live stateful replica to host the session")
                return
            sid = rep.client.open_session(model=data.get("model", -1))
            with self._affinity_lock:
                self._affinity[sid] = rep
            with self._stats_lock:
                self.sessions_routed += 1
            self.send(conn, ("session", {"rid": rid, "sid": sid}))
        except Exception as exc:
            self._error(conn, rid, "replica_lost",
                        f"open_session failed: {type(exc).__name__}: {exc}")

    def _handle_close_session(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        rid = data.get("rid")
        sid = data.get("sid")
        with self._affinity_lock:
            rep = self._affinity.pop(sid, None)
        existed = False
        try:
            if rep is not None and rep.alive and rep.client is not None:
                existed = bool(
                    rep.client.close_session(sid).get("existed", False)
                )
        except Exception:
            pass  # owner died with the session: it is closed by definition
        self.send(conn, ("session_closed",
                         {"rid": rid, "sid": sid, "existed": existed}))

    def _handle_stats(self, conn: FramedConnection, rid) -> None:
        try:
            per_replica = {}
            for rep in self.replicas:
                client = rep.client
                if rep.alive and client is not None:
                    try:
                        per_replica[rep.spec.name] = client.stats(timeout=10.0)
                    except Exception:
                        self._mark_lost(rep)
            stats = dict(self.stats_record(), replicas=per_replica)
            self.send(conn, ("stats", {"rid": rid, "stats": stats}))
        except Exception as exc:
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_swap(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        """Fleet-wide hot-swap: warm-then-flip propagated replica-by-
        replica.  Sequential on purpose — each replica's standby engine
        warms and flips with zero drops while every OTHER replica keeps
        serving at full capacity; a parallel fan-out would have the whole
        fleet paying warm-up compile pressure at once."""
        rid = data.get("rid")
        sid = data.get("id")
        warm_ms_total = 0.0
        flipped = 0
        try:
            for rep in self.replicas:
                if rep.is_edge or not rep.alive:
                    continue  # edge artifacts don't take jax params
                client = rep.client
                if client is None:
                    continue
                reply = client.swap(sid, data.get("params"))
                warm_ms_total += float(reply.get("warm_ms") or 0.0)
                flipped += 1
            if flipped == 0:
                self._error(conn, rid, "swap_failed",
                            "no live swap-capable replica")
                return
            with self._stats_lock:
                self.hot_swaps += 1
            self.send(conn, ("swapped", {
                "rid": rid, "id": sid, "warm_ms": warm_ms_total,
                "replicas": flipped,
            }))
        except Exception as exc:
            # a mixed-version fleet is an operator problem: loud, with the
            # partial progress in the message
            self._error(conn, rid, "swap_failed",
                        f"{flipped} replica(s) flipped, then "
                        f"{type(exc).__name__}: {exc}")

    def _error(self, conn: FramedConnection, rid, kind: str, msg: str) -> None:
        with self._stats_lock:
            self.errors[kind] = self.errors.get(kind, 0) + 1
        self.send(conn, ("error", {"rid": rid, "kind": kind, "msg": msg}))

    # -- stats / metrics -----------------------------------------------------

    def stats_record(self, advance_window: bool = False) -> Dict[str, Any]:
        """One metrics.jsonl-shaped record of the fleet front-end's health;
        every key registered in utils.metrics.METRIC_KEYS (MET006)."""
        now = time.monotonic()
        with self._stats_lock:
            requests_in = self.requests_in
            replies = self.replies
            errors = sum(self.errors.values())
            sessions = self.sessions_routed
            lost = self.replicas_lost
            swaps = self.hot_swaps
            dt = max(now - self._stats_t0, 1e-6)
            served_delta = replies - self._stats_served0
            if advance_window:
                self._stats_t0 = now
                self._stats_served0 = replies
        record: Dict[str, Any] = {
            "fleet_requests": requests_in,
            "fleet_replies": replies,
            "fleet_errors": errors,
            "fleet_qps": round(served_delta / dt, 2),
            "fleet_replicas": len(self.replicas),
            "fleet_replicas_live": sum(1 for r in self.replicas if r.alive),
            "fleet_replica_lost": lost,
            "fleet_sessions": sessions,
            "fleet_hot_swaps": swaps,
        }
        return record

    def _metrics_loop(self) -> None:
        while not self.shutdown_flag:
            time.sleep(self.stats_interval)
            if self.shutdown_flag:
                return
            try:
                append_metrics_record(
                    self._metrics_path, self.stats_record(advance_window=True)
                )
            except Exception as exc:
                print(f"fleet: metrics write failed: {type(exc).__name__}: {exc}")


def fleet_main(args: Dict[str, Any]) -> None:
    """``main.py --fleet``: the front-end tier over a configured replica
    fleet (``fleet.replicas`` — start each backend with ``--serve`` or
    ``--edge`` first)."""
    from ..utils import trace

    train = args["train_args"]
    fleet_cfg = train.get("fleet", {})
    if trace.configure(train.get("trace")):
        print(f"fleet: trace spans -> {trace.current_path()}")
    router = FleetRouter(
        fleet_cfg, metrics_path=train.get("metrics_path")
    ).run()
    specs = ", ".join(
        r.spec.name + ("[edge]" if r.is_edge else "")
        for r in router.replicas
    )
    print(f"fleet: entry port {router.bound_port} over replicas {specs}")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        print("fleet: shutting down")
    finally:
        router.shutdown()
