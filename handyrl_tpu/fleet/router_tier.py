"""Fleet front-end: session-affinity routing over N serving replicas.

The horizontal tier ROADMAP item 2 names (docs/serving.md §Fleet tier):
one entry port accepts thousands of ``ServingClient`` connections and
proxies their rid-pipelined frames to backend ``ServingServer`` replicas
— the plane-split discipline (front-end vs compute) applied to
inference.  Composition of machinery already banked, nothing novel on
the wire:

* transport: the framed-socket hub (``QueueCommunicator``) on the client
  side, one pipelined ``ServingClient`` per backend replica — the proxy
  speaks the replica protocol as an ordinary client, so replicas need no
  fleet awareness;
* balancing: new sessions and stateless requests land on the live
  replica with the lowest load score — queue depth + shed rate from the
  existing ``stats`` frame, polled on ``stats_poll_s``;
* affinity: an ``infer`` carrying a ``sid`` follows the session to the
  replica that owns its hidden state (fleet/sessions.py).  When that
  replica dies the session is re-pointed to a survivor, which serves it
  fresh-state and counts the affinity miss — degraded loudly, never a
  hang;
* failure: a replica that drops its connection (or goes silent past the
  client stall deadline) fails every in-flight proxied request with a
  loud ``replica_lost`` error kind, is reaped from rotation, and is
  re-joined with exponential backoff (the PR 2 rejoin discipline);
* fleet-wide hot-swap: one ``swap`` frame at the front propagates
  replica-by-replica — each replica runs its own zero-drop
  warm-then-flip while the others keep serving, so the tier as a whole
  drops nothing;
* capabilities: replicas registered with the ``edge`` tag (the ONNX CPU
  backend, fleet/edge.py) receive only feed-forward traffic — stateful
  routes (sessions / wire hidden) and swap propagation skip them.

Elastic fleet (docs/serving.md §Elastic fleet):

* warm-then-admit: a replica is connected the moment it answers TCP but
  receives NO traffic until its warm probe passes (``serve_models`` >= 1
  — the engine published and warmed its buckets).  A scaling-up fleet
  therefore never sheds a request into a cold engine's compile pause;
* autoscaling: ``fleet.autoscale.*`` arms an `Autoscaler`
  (fleet/autoscale.py) fed by the same windowed shed-rate/queue-depth
  records the balancer polls — spawn on load swings via a
  ``ReplicaFactory``, retire through the migration path below, with
  hysteresis and min/max bounds;
* zero-loss retire: a planned retire SEALS the replica (no new picks),
  parks incoming session infers, drains its in-flight requests, pulls
  its whole `SessionCache` over the wire (``export_sessions``), lands it
  in the successor's spill ring (``import_sessions`` — restored
  bit-identically through the counted ``session_restored`` path), flips
  affinity, and replays the parked infers on the successor.  The miss
  counter does not move;
* preemption: a SIGTERM'd replica broadcasts a ``draining`` notice
  (serving/server.py ``begin_drain``); the client delivers it through
  ``on_notice`` and the router runs the same migration inside the
  replica's ``drain_deadline_seconds``, then lets the process exit 75;
* bounded failover retry: when a replica is lost mid-request, in-flight
  STATELESS (no-sid) requests are retried once on a survivor after a
  short backoff; stateful requests keep the loud ``replica_lost`` error
  — at-most-once is the session contract, the router must not guess.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List, Optional

from ..runtime.connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    open_socket_connection,
)
from ..serving.client import ServingClient, ServingError
from ..utils.metrics import append_metrics_record
from ..utils.retry import retry_call
from ..utils.trace import trace_event

__all__ = ["FleetRouter", "ReplicaSpec", "fleet_main"]

# stats-frame shed rate is weighted against raw queue depth when scoring
# replicas: one shed in the last window outweighs ~100 queued requests,
# because shedding proves the replica is ALREADY past its SLO capacity
_SHED_WEIGHT = 100.0

# stateless failover retry: the short pause before re-submitting a
# replica_lost request on a survivor (lets the loss bookkeeping settle;
# a zero-delay retry tends to land on the same dying replica's scores)
_RETRY_BACKOFF_S = 0.05

# session infers parked during their owner's migration window; beyond
# this the router degrades loudly to a re-route instead of buffering
# without bound (the parked window is tens of ms, not a second tier)
_PARK_BOUND = 1024


class ReplicaSpec:
    """One backend's address + capability tags (config-registered)."""

    __slots__ = ("host", "port", "tags", "name")

    def __init__(self, host: str, port: int, tags=()):
        self.host = str(host)
        self.port = int(port)
        self.tags = frozenset(str(t) for t in tags)
        self.name = f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, entry) -> "ReplicaSpec":
        """'host:port' strings or {'host', 'port', 'tags'?} dicts — the
        two spellings ``fleet.replicas`` accepts (config.py validates)."""
        if isinstance(entry, cls):
            return entry
        if isinstance(entry, str):
            host, _, port = entry.rpartition(":")
            return cls(host or "127.0.0.1", int(port))
        return cls(entry["host"], entry["port"], entry.get("tags", ()))


class _Replica:
    """Live state for one backend: its proxy client, liveness, and the
    last-polled load score."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.client: Optional[ServingClient] = None
        self.alive = False
        # warm-then-admit: connected but admitted=False replicas receive
        # no traffic until the warm probe sees a published, warmed engine
        self.admitted = False
        # sealed: excluded from every new pick (retiring / draining)
        self.sealed = False
        # migrating: session infers for sids this replica owns are parked
        # (under the router's affinity lock) until affinity flips to the
        # successor — the ordering guarantee bit-identical migration needs
        self.migrating = False
        # spawned by the autoscaler's ReplicaFactory (retire stops the
        # process too); config-registered replicas are the operator's
        self.spawned = False
        self.parked: List = []
        self.load = 0.0
        self.picked = 0  # tie-break: spread equal-load picks round-robin
        self._last_stats: Dict[str, Any] = {}
        self.lock = threading.Lock()

    @property
    def is_edge(self) -> bool:
        return "edge" in self.spec.tags

    def score_from(self, stats: Dict[str, Any]) -> float:
        """Load score from a stats-frame record: instantaneous queue depth
        plus the shed rate over the window since the previous poll."""
        prev = self._last_stats
        self._last_stats = stats
        depth = float(stats.get("serve_depth") or 0.0)
        shed = float(stats.get("serve_shed") or 0.0)
        requests = float(stats.get("serve_requests") or 0.0)
        d_shed = max(0.0, shed - float(prev.get("serve_shed") or 0.0))
        d_req = max(1.0, requests - float(prev.get("serve_requests") or 0.0))
        return depth + _SHED_WEIGHT * (d_shed / d_req)


class FleetRouter(QueueCommunicator):
    """Entry-port front-end proxying infer/stats/swap/session frames to a
    fleet of serving replicas."""

    def __init__(
        self,
        fleet_cfg: Dict[str, Any],
        metrics_path: Optional[str] = None,
        replica_factory=None,
    ):
        cfg = dict(fleet_cfg or {})
        super().__init__(
            recv_timeout=None,
            # same reasoning as ServingServer: reply bursts to a pipelining
            # client are the product, not a fault signal
            send_queue_size=1024,
        )
        self.port = int(cfg.get("port", 9996))
        self.bound_port: Optional[int] = None
        self.stats_poll_s = float(cfg.get("stats_poll_s", 2.0))
        # transient-fault budget for the stats poll (utils/retry.py): one
        # flaky syscall must not cost a replica_lost + re-routing storm
        self.poll_retry_attempts = int(cfg.get("poll_retry_attempts", 3))
        self.poll_retry_backoff_s = float(cfg.get("poll_retry_backoff_s", 0.1))
        self.replica_stall_s = float(cfg.get("replica_stall_s", 30.0))
        self.backoff_s = float(cfg.get("rejoin_backoff_s", 1.0))
        self.backoff_max_s = float(cfg.get("rejoin_backoff_max_s", 30.0))
        self.stats_interval = float(cfg.get("stats_interval", 30.0))
        self.migrate_timeout_s = float(cfg.get("migrate_timeout_s", 30.0))
        self.autoscale_cfg = dict(cfg.get("autoscale") or {})
        self._factory = replica_factory
        self._autoscaler = None
        self._metrics_path = metrics_path
        self._replicas_lock = threading.Lock()
        self.replicas: List[_Replica] = [
            _Replica(ReplicaSpec.parse(e)) for e in cfg.get("replicas", ())
        ]
        if not self.replicas and not (
            self.autoscale_cfg.get("enabled") and replica_factory is not None
        ):
            raise ValueError("fleet.replicas is empty — nothing to route to "
                             "(and no autoscale factory to spawn from)")
        # sid -> replica owning its hidden state.  Entries re-point to a
        # survivor when the owner dies (the new owner then counts an
        # affinity miss and serves the session fresh-state)
        self._affinity: Dict[str, _Replica] = {}
        self._affinity_lock = threading.Lock()
        # blocking control ops (session open/close, swap propagation,
        # stats fan-out) run here, never on the dispatch thread
        self._ctl_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-ctl"
        )
        self._rejoining: set = set()
        self._stats_lock = threading.Lock()
        self.requests_in = 0
        self.replies = 0
        self.errors: Dict[str, int] = {}
        self.sessions_routed = 0
        self.replicas_lost = 0
        self.hot_swaps = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.migrations = 0
        self.sessions_migrated = 0
        self.last_migration_ms = 0.0
        self.failover_retries = 0
        self.preempt_drains = 0
        self.poll_retries = 0
        self._stats_t0 = time.monotonic()
        self._stats_served0 = 0
        self._sock = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def run(self, connect_timeout: float = 30.0) -> "FleetRouter":
        """Connect the replica fleet (each with retry — replicas may still
        be booting), warm-probe it, then bind the entry port and start
        serving.  With autoscaling armed, spawn up to ``min_replicas``
        from the factory first."""
        if self.autoscale_cfg.get("enabled") and self._factory is not None:
            want = int(self.autoscale_cfg.get("min_replicas", 1))
            have = sum(1 for r in self._reps() if not r.is_edge)
            for _ in range(max(0, want - have)):
                self._spawn_replica()
        for rep in self._reps():
            if rep.alive:
                continue  # already connected by _spawn_replica
            try:
                self._connect(rep, retry_seconds=connect_timeout)
            except OSError as exc:
                # a replica down at boot is the same as one lost later:
                # route around it and let the rejoin loop chase it
                print(f"fleet: replica {rep.spec.name} unreachable at start "
                      f"({exc}); rejoining in background")
                self._mark_lost(rep)
                continue
            threading.Thread(
                target=self._admit_loop, args=(rep,), daemon=True,
                name=f"fleet-admit-{rep.spec.name}",
            ).start()
        if not any(r.alive for r in self._reps()):
            raise ConnectionError("fleet: no replica reachable at startup")
        # warm-then-admit gate: serve only once at least one replica has a
        # published, warmed engine — binding earlier would shed the very
        # first requests into cold engines, the exact failure this removes
        deadline = time.monotonic() + connect_timeout
        while (not any(r.admitted for r in self._reps())
               and time.monotonic() < deadline):
            time.sleep(0.02)
        if not any(r.admitted for r in self._reps()):
            raise ConnectionError(
                "fleet: no replica became warm (admitted) within "
                f"{connect_timeout:.0f}s — is a model published?"
            )
        self._sock = open_socket_connection(self.port)
        self._sock.listen(1024)
        self.bound_port = self._sock.getsockname()[1]
        targets = [self._accept_loop, self._dispatch, self._poll_loop]
        if self._metrics_path and self.stats_interval > 0:
            targets.append(self._metrics_loop)
        for target in targets:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        if self.autoscale_cfg.get("enabled") and self._factory is not None:
            from .autoscale import Autoscaler

            self._autoscaler = Autoscaler(self, self.autoscale_cfg).start()
        return self

    def shutdown(self) -> None:
        super().shutdown()
        if self._autoscaler is not None:
            self._autoscaler.stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._ctl_pool.shutdown(wait=False)
        for rep in self._reps():
            with rep.lock:
                client, rep.client, rep.alive = rep.client, None, False
            if client is not None:
                client.close()

    def _accept_loop(self) -> None:
        for conn in accept_socket_connections(timeout=0.5, sock=self._sock):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)

    # -- replica fleet ------------------------------------------------------

    def _reps(self) -> List[_Replica]:
        """Snapshot of the (autoscaler-mutable) replica list — every
        iteration goes through here so list churn never races a loop."""
        with self._replicas_lock:
            return list(self.replicas)

    def _connect(self, rep: _Replica, retry_seconds: float = 0.0) -> None:
        client = ServingClient(
            rep.spec.host, rep.spec.port,
            retry_seconds=retry_seconds,
            # the stall deadline turns a silent replica into a named
            # failure on every pending proxied request — bounded failover
            stall_timeout=self.replica_stall_s or None,
            # rid-less server pushes (the preemption "draining" notice)
            # land here off the client's receiver thread: hand off only
            on_notice=lambda kind, data, r=rep: self._on_replica_notice(
                r, kind, data
            ),
        )
        with rep.lock:
            rep.client = client
            rep.alive = True
            # a (re)connected replica re-earns admission via the warm
            # probe — a relaunched preempted process comes back cold
            rep.admitted = False
            rep.sealed = False
            rep.migrating = False
            rep.parked = []
            rep.load = 0.0

    def _replica_stats(self, rep: _Replica) -> Optional[Dict[str, Any]]:
        """One replica's stats frame under the shared transient-fault
        discipline (utils/retry.py): transport-shaped failures (reset,
        EINTR, a missed reply deadline) retry with backoff inside the
        ``poll_retry_attempts`` budget before the caller may declare the
        peer lost.  A server-REPORTED failure (``ServingError``) is the
        peer misbehaving, not flaking — it propagates immediately."""
        client = rep.client
        if client is None:
            raise ConnectionError("replica has no client")

        def _count(i, exc):
            with self._stats_lock:
                self.poll_retries += 1

        return retry_call(
            lambda: client.stats(timeout=max(self.stats_poll_s * 4, 10.0)),
            attempts=self.poll_retry_attempts,
            base_delay=self.poll_retry_backoff_s,
            retry_on=(ConnectionError, OSError, TimeoutError, FuturesTimeout),
            on_retry=_count,
        )

    def _admit_loop(self, rep: _Replica) -> None:
        """Warm-then-admit probe: poll the replica's stats until its
        engine is published and warm (``serve_models`` >= 1; an edge
        artifact is warm by construction the moment stats answer), then
        open it to traffic.  Bounded by ``autoscale.warm_timeout_s`` —
        a replica that never warms is marked lost (loudly) and cycles
        through the rejoin backoff instead of squatting forever."""
        warm_timeout = float(self.autoscale_cfg.get("warm_timeout_s", 120.0))
        deadline = time.monotonic() + warm_timeout
        poll = max(0.05, min(self.stats_poll_s, 0.5))
        while not self.shutdown_flag and rep.alive and not rep.sealed:
            if rep.client is None:
                return
            try:
                stats = self._replica_stats(rep)
            except Exception:
                self._mark_lost(rep)
                return
            stats = stats or {}
            if rep.is_edge or float(stats.get("serve_models") or 0) >= 1:
                rep.load = rep.score_from(stats)
                rep.admitted = True
                print(f"fleet: replica {rep.spec.name} admitted (warm)")
                return
            if time.monotonic() > deadline:
                print(f"fleet: replica {rep.spec.name} never became warm "
                      f"within {warm_timeout:.0f}s — marking lost")
                self._mark_lost(rep)
                return
            time.sleep(poll)

    def _mark_lost(self, rep: _Replica) -> None:
        """Reap a dead replica: fail-fast state, count the loss, schedule
        the backoff rejoin.  Idempotent under racing reporters (the poll
        loop, several reply callbacks)."""
        with rep.lock:
            was_alive, rep.alive = rep.alive, False
            client, rep.client = rep.client, None
        if client is not None:
            client.close()
        if was_alive:
            with self._stats_lock:
                self.replicas_lost += 1
            print(f"fleet: replica {rep.spec.name} lost; "
                  f"re-routing its sessions, rejoining with backoff")
        with self._stats_lock:
            if rep in self._rejoining:
                return
            self._rejoining.add(rep)
        threading.Thread(
            target=self._rejoin_loop, args=(rep,), daemon=True,
            name=f"fleet-rejoin-{rep.spec.name}",
        ).start()

    def _rejoin_loop(self, rep: _Replica) -> None:
        """PR 2 discipline: exponential backoff, capped, forever — a
        replica that restarts rejoins the rotation without operator help."""
        backoff = self.backoff_s
        try:
            while not self.shutdown_flag:
                time.sleep(backoff)
                if self.shutdown_flag:
                    return
                try:
                    self._connect(rep)
                    print(f"fleet: replica {rep.spec.name} rejoined "
                          "(warming before re-admission)")
                    # already on a background thread: probe inline — the
                    # rejoined replica re-enters rotation only once warm
                    self._admit_loop(rep)
                    return
                except OSError:
                    backoff = min(backoff * 2.0, self.backoff_max_s)
        finally:
            with self._stats_lock:
                self._rejoining.discard(rep)

    def _live(self, stateful: bool) -> List[_Replica]:
        return [
            r for r in self._reps()
            if r.alive and r.admitted and not r.sealed
            and not (stateful and r.is_edge)
        ]

    def _pick(self, stateful: bool) -> Optional[_Replica]:
        """Lowest-load live replica (capability-filtered); None when the
        whole (eligible) fleet is down."""
        t0 = time.monotonic()
        candidates = self._live(stateful)
        if not candidates:
            return None
        rep = min(candidates, key=lambda r: (r.load, r.picked))
        rep.picked += 1
        trace_event("fleet.route", time.monotonic() - t0, t0=t0,
                    plane="fleet", replicas=len(candidates))
        return rep

    def _poll_loop(self) -> None:
        """The balancing signal: shed-rate/queue-depth via the existing
        stats frame, each replica polled on its own pool task so one
        stalled replica never delays the others' scores."""
        while not self.shutdown_flag:
            time.sleep(self.stats_poll_s)
            if self.shutdown_flag:
                return
            for rep in self._reps():
                if rep.alive and not rep.sealed:
                    self._ctl_pool.submit(self._poll_one, rep)

    def _poll_one(self, rep: _Replica) -> None:
        if rep.client is None:
            return
        try:
            stats = self._replica_stats(rep)
        except Exception:
            self._mark_lost(rep)
            return
        rep.load = rep.score_from(stats or {})

    # -- request dispatch ----------------------------------------------------

    def _dispatch(self) -> None:
        while not self.shutdown_flag:
            try:
                conn, frame = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            try:
                req, data = frame
            except (TypeError, ValueError):
                continue
            if req == "heartbeat" or req == "__hb__":
                continue
            if not isinstance(data, dict):
                data = {}
            rid = data.get("rid")
            try:
                if req == "infer":
                    self._handle_infer(conn, data)
                elif req == "open_session":
                    self._ctl_pool.submit(self._handle_open_session, conn, data)
                elif req == "close_session":
                    self._ctl_pool.submit(self._handle_close_session, conn, data)
                elif req == "stats":
                    self._ctl_pool.submit(self._handle_stats, conn, rid)
                elif req == "swap":
                    self._ctl_pool.submit(self._handle_swap, conn, data)
                else:
                    self._error(conn, rid, "bad_request",
                                f"unknown request {req!r}")
            except Exception as exc:
                # THE dispatch thread: no frame may kill it (see
                # ServingServer._dispatch — same contract)
                self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_infer(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        with self._stats_lock:
            self.requests_in += 1
        arrival = time.monotonic()
        rid = data.get("rid")
        sid = data.get("sid")
        stateful = sid is not None or data.get("hidden") is not None
        rep = None
        if sid is not None:
            # affinity read + migration park are ONE atomic step: a
            # migrating owner's session infers park under the lock the
            # retire path flips affinity under, so no request can slip
            # through to the old owner after its state was exported
            with self._affinity_lock:
                rep = self._affinity.get(sid)
                if rep is not None and rep.migrating:
                    if len(rep.parked) < _PARK_BOUND:
                        rep.parked.append((conn, data))
                        return
                    rep = None  # park overflow: degrade loudly, re-route
            if rep is not None and (not rep.alive or rep.sealed):
                rep = None  # owner died or is retiring: re-route below
        if rep is None:
            rep = self._pick(stateful)
            if rep is None:
                self._error(conn, rid, "no_replica",
                            "no live replica can serve this request "
                            f"(stateful={stateful})")
                return
            if sid is not None:
                # session re-pointed (first infer, or owner lost): the new
                # owner serves fresh-state and counts the affinity miss
                with self._affinity_lock:
                    self._affinity[sid] = rep
        self._proxy(conn, rep, data, arrival)

    def _proxy(self, conn: FramedConnection, rep: _Replica,
               data: Dict[str, Any], arrival: float,
               retried: bool = False) -> None:
        rid = data.get("rid")
        client = rep.client
        if client is None:
            self._error(conn, rid, "replica_lost",
                        f"replica {rep.spec.name} lost before proxy")
            return
        fut = client.submit(
            data.get("obs"), data.get("model", -1), data.get("hidden"),
            data.get("slo_ms"), sid=data.get("sid"),
        )
        fut.add_done_callback(
            lambda f, c=conn, p=rep, d=data, a=arrival, rt=retried:
                self._relay(c, p, f, d, a, rt)
        )

    def _relay(self, conn: FramedConnection, rep: _Replica, fut: Future,
               data: Dict[str, Any], arrival: float,
               retried: bool = False) -> None:
        """Reply callback for a proxied infer: forward the result/error to
        the fronted client under ITS rid; a transport-level failure means
        the replica itself is gone — retry once on a survivor if the
        request is stateless, loud replica_lost otherwise."""
        rid = data.get("rid")
        exc = fut.exception()
        trace_event("fleet.proxy", time.monotonic() - arrival, t0=arrival,
                    plane="fleet", ok=exc is None, replica=rep.spec.name)
        if exc is None:
            d = fut.result()
            reply = {"rid": rid, "model": d.get("model"), "out": d.get("out")}
            if "sid" in d:
                reply["sid"] = d["sid"]
            with self._stats_lock:
                self.replies += 1
            self.send(conn, ("result", reply))
            return
        if isinstance(exc, ServingError) and exc.kind != "stalled":
            # a request-level failure (shed/deadline/bad_request/...) is
            # the replica WORKING as designed: forward it verbatim
            self._error(conn, rid, exc.kind, str(exc))
            return
        # connection loss or stall deadline: the replica is gone
        self._mark_lost(rep)
        if data.get("sid") is None and not retried:
            # stateless in-flight requests are safe to re-run (no server-
            # side session state moved): one bounded retry on a survivor.
            # Stateful requests keep the loud error — the session contract
            # is at-most-once, and the router must not guess whether the
            # lost replica applied the store before dying
            with self._stats_lock:
                self.failover_retries += 1
            self._ctl_pool.submit(self._retry_stateless, conn, data, arrival)
            return
        self._error(conn, rid, "replica_lost",
                    f"replica {rep.spec.name} lost mid-request "
                    f"({type(exc).__name__}: {exc})")

    def _retry_stateless(self, conn: FramedConnection, data: Dict[str, Any],
                         arrival: float) -> None:
        time.sleep(_RETRY_BACKOFF_S)
        rep = self._pick(stateful=data.get("hidden") is not None)
        if rep is None:
            self._error(conn, data.get("rid"), "replica_lost",
                        "stateless retry found no live replica")
            return
        self._proxy(conn, rep, data, arrival, retried=True)

    # -- elastic fleet: migration / preemption / scaling ---------------------

    def _on_replica_notice(self, rep: _Replica, kind: str,
                           data: Dict[str, Any]) -> None:
        """Server-pushed notice from a replica's proxy client (called on
        that client's receiver thread — hand off, never block).  The
        ``draining`` notice is a preempting replica asking for its
        sessions to be rescued inside its drain deadline."""
        if kind != "draining":
            return
        with self._stats_lock:
            self.preempt_drains += 1
        print(f"fleet: replica {rep.spec.name} is draining (preempted) — "
              "migrating its sessions to a survivor")
        # a dedicated thread, not the ctl pool: the handoff can legally
        # take up to migrate_timeout_s, and the pool is the proxy path
        threading.Thread(
            target=self._retire_replica, args=(rep,),
            kwargs={"reason": "preempted", "remove": False}, daemon=True,
            name=f"fleet-drain-{rep.spec.name}",
        ).start()

    def retire(self, rep: _Replica) -> int:
        """Planned retire (operator/scale-down): seal → drain → migrate
        sessions to a successor → stop.  Returns sessions migrated."""
        return self._retire_replica(rep, reason="retire", remove=True)

    def _retire_replica(self, rep: _Replica, reason: str = "retire",
                        remove: bool = True) -> int:
        """The zero-loss retire sequence.  Ordering is the whole story:

        1. seal + mark migrating (atomically with the affinity map) — no
           new picks, session infers for its sids PARK;
        2. drain its in-flight proxied requests (their session stores
           land server-side before the reply frame, so the export below
           sees every applied step);
        3. export its whole SessionCache over the wire and land it in
           the successor's spill ring;
        4. flip affinity to the successor and release the parked infers
           (served from migrated state via the session_restored path —
           bit-identical, session_affinity_miss does not move);
        5. drop the replica (scale-down: stop the spawned process too;
           preemption: keep the slot, the rejoin loop chases a relaunch).
        """
        t_start = time.monotonic()
        with self._affinity_lock:
            if rep.sealed:
                return 0  # already retiring/draining (idempotent)
            rep.sealed = True
            rep.migrating = True
        migrated = 0
        succ: Optional[_Replica] = None
        client = rep.client
        try:
            if client is not None and rep.alive:
                deadline = time.monotonic() + self.migrate_timeout_s
                while (client.pending_count() > 0
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                exported = client.export_sessions(
                    timeout=self.migrate_timeout_s
                )
                sessions = exported.get("sessions") or {}
                fresh = exported.get("fresh") or []
                if sessions or fresh:
                    succ = self._pick(stateful=True)
                    if succ is not None and succ.client is not None:
                        succ.client.import_sessions(
                            sessions, fresh, timeout=self.migrate_timeout_s
                        )
                        migrated = len(sessions)
                    else:
                        succ = None
                        print(f"fleet: retire of {rep.spec.name}: no live "
                              f"successor for {len(sessions)} session(s) — "
                              "they will re-open fresh (counted misses)")
        except Exception as exc:
            succ = None
            print(f"fleet: session migration off {rep.spec.name} failed "
                  f"({type(exc).__name__}: {exc}) — its sessions will "
                  "re-open fresh (counted misses)")
        # flip affinity and release the parked infers under the SAME lock
        # the park decision takes: after this block no request can reach
        # the exported (now stale) owner
        with self._affinity_lock:
            parked, rep.parked = rep.parked, []
            for s, owner in list(self._affinity.items()):
                if owner is rep:
                    if succ is not None:
                        self._affinity[s] = succ
                    else:
                        del self._affinity[s]
            rep.migrating = False
        handoff_ms = (time.monotonic() - t_start) * 1000.0
        with self._stats_lock:
            self.migrations += 1
            self.sessions_migrated += migrated
            self.last_migration_ms = handoff_ms
        trace_event("fleet.migrate", handoff_ms / 1000.0, t0=t_start,
                    plane="fleet", sessions=migrated, reason=reason)
        for pconn, pdata in parked:
            self._ctl_pool.submit(self._handle_infer, pconn, pdata)
        print(f"fleet: replica {rep.spec.name} retired ({reason}): "
              f"{migrated} session(s) migrated"
              + (f" to {succ.spec.name}" if succ is not None else "")
              + f" in {handoff_ms:.0f}ms, {len(parked)} parked infer(s) "
              "replayed")
        if remove:
            with self._replicas_lock:
                try:
                    self.replicas.remove(rep)
                except ValueError:
                    pass
            with rep.lock:
                client, rep.client, rep.alive = rep.client, None, False
            if client is not None:
                client.close()
            if rep.spawned and self._factory is not None:
                try:
                    self._factory.stop(rep.spec)
                except Exception as exc:
                    print(f"fleet: factory stop of {rep.spec.name} failed: "
                          f"{type(exc).__name__}: {exc}")
        else:
            # preempted configured replica: keep its slot and let the
            # rejoin loop chase the relaunched process (which re-earns
            # admission through the warm probe)
            self._mark_lost(rep)
        return migrated

    def _spawn_replica(self) -> Optional[_Replica]:
        """Factory-spawn one replica and start warming it.  It joins the
        rotation only when its admit probe passes — never cold."""
        if self._factory is None:
            return None
        try:
            spec = self._factory.spawn()
        except Exception as exc:
            print(f"fleet: replica spawn failed: {type(exc).__name__}: {exc}")
            return None
        rep = _Replica(ReplicaSpec.parse(spec))
        rep.spawned = True
        try:
            self._connect(rep, retry_seconds=10.0)
        except OSError as exc:
            print(f"fleet: spawned replica {rep.spec.name} unreachable "
                  f"({exc}); stopping it")
            try:
                self._factory.stop(rep.spec)
            except Exception:
                pass
            return None
        with self._replicas_lock:
            self.replicas.append(rep)
        threading.Thread(
            target=self._admit_loop, args=(rep,), daemon=True,
            name=f"fleet-admit-{rep.spec.name}",
        ).start()
        return rep

    def scale_up(self, reason: str = "") -> bool:
        rep = self._spawn_replica()
        if rep is None:
            return False
        with self._stats_lock:
            self.scale_ups += 1
        print(f"fleet: scale-up -> {rep.spec.name} (warming; admitted when "
              f"warm){reason}")
        return True

    def scale_down(self, reason: str = "") -> bool:
        """Retire the newest autoscaler-spawned replica through the
        migration path.  Config-registered replicas are the operator's
        floor — the autoscaler never retires them."""
        cands = [
            r for r in self._reps()
            if r.spawned and r.alive and not r.sealed
        ]
        if not cands:
            return False
        rep = cands[-1]
        with self._stats_lock:
            self.scale_downs += 1
        print(f"fleet: scale-down -> retiring {rep.spec.name}{reason}")
        self._retire_replica(rep, reason="scale-down", remove=True)
        return True

    # -- control frames (pool) ----------------------------------------------

    def _handle_open_session(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        rid = data.get("rid")
        try:
            rep = self._pick(stateful=True)
            if rep is None or rep.client is None:
                self._error(conn, rid, "no_replica",
                            "no live stateful replica to host the session")
                return
            sid = rep.client.open_session(model=data.get("model", -1))
            with self._affinity_lock:
                self._affinity[sid] = rep
            with self._stats_lock:
                self.sessions_routed += 1
            self.send(conn, ("session", {"rid": rid, "sid": sid}))
        except Exception as exc:
            self._error(conn, rid, "replica_lost",
                        f"open_session failed: {type(exc).__name__}: {exc}")

    def _handle_close_session(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        rid = data.get("rid")
        sid = data.get("sid")
        with self._affinity_lock:
            rep = self._affinity.pop(sid, None)
        existed = False
        try:
            if rep is not None and rep.alive and rep.client is not None:
                existed = bool(
                    rep.client.close_session(sid).get("existed", False)
                )
        except Exception:
            pass  # owner died with the session: it is closed by definition
        self.send(conn, ("session_closed",
                         {"rid": rid, "sid": sid, "existed": existed}))

    def _handle_stats(self, conn: FramedConnection, rid) -> None:
        try:
            per_replica = {}
            for rep in self._reps():
                client = rep.client
                if rep.alive and client is not None:
                    try:
                        per_replica[rep.spec.name] = client.stats(timeout=10.0)
                    except Exception:
                        self._mark_lost(rep)
            stats = dict(self.stats_record(), replicas=per_replica)
            self.send(conn, ("stats", {"rid": rid, "stats": stats}))
        except Exception as exc:
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_swap(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        """Fleet-wide hot-swap: warm-then-flip propagated replica-by-
        replica.  Sequential on purpose — each replica's standby engine
        warms and flips with zero drops while every OTHER replica keeps
        serving at full capacity; a parallel fan-out would have the whole
        fleet paying warm-up compile pressure at once."""
        rid = data.get("rid")
        sid = data.get("id")
        warm_ms_total = 0.0
        flipped = 0
        try:
            for rep in self._reps():
                if rep.is_edge or not rep.alive or rep.sealed:
                    continue  # edge artifacts don't take jax params; a
                    # retiring replica's engine dies with it anyway
                client = rep.client
                if client is None:
                    continue
                reply = client.swap(sid, data.get("params"))
                warm_ms_total += float(reply.get("warm_ms") or 0.0)
                flipped += 1
            if flipped == 0:
                self._error(conn, rid, "swap_failed",
                            "no live swap-capable replica")
                return
            with self._stats_lock:
                self.hot_swaps += 1
            self.send(conn, ("swapped", {
                "rid": rid, "id": sid, "warm_ms": warm_ms_total,
                "replicas": flipped,
            }))
        except Exception as exc:
            # a mixed-version fleet is an operator problem: loud, with the
            # partial progress in the message
            self._error(conn, rid, "swap_failed",
                        f"{flipped} replica(s) flipped, then "
                        f"{type(exc).__name__}: {exc}")

    def _error(self, conn: FramedConnection, rid, kind: str, msg: str) -> None:
        with self._stats_lock:
            self.errors[kind] = self.errors.get(kind, 0) + 1
        self.send(conn, ("error", {"rid": rid, "kind": kind, "msg": msg}))

    # -- stats / metrics -----------------------------------------------------

    def stats_record(self, advance_window: bool = False) -> Dict[str, Any]:
        """One metrics.jsonl-shaped record of the fleet front-end's health;
        every key registered in utils.metrics.METRIC_KEYS (MET006)."""
        now = time.monotonic()
        with self._stats_lock:
            requests_in = self.requests_in
            replies = self.replies
            errors = sum(self.errors.values())
            sessions = self.sessions_routed
            lost = self.replicas_lost
            swaps = self.hot_swaps
            scale_ups = self.scale_ups
            scale_downs = self.scale_downs
            migrations = self.migrations
            migrated = self.sessions_migrated
            migration_ms = self.last_migration_ms
            retries = self.failover_retries
            preempts = self.preempt_drains
            poll_retries = self.poll_retries
            dt = max(now - self._stats_t0, 1e-6)
            served_delta = replies - self._stats_served0
            if advance_window:
                self._stats_t0 = now
                self._stats_served0 = replies
        reps = self._reps()
        record: Dict[str, Any] = {
            "fleet_requests": requests_in,
            "fleet_replies": replies,
            "fleet_errors": errors,
            "fleet_qps": round(served_delta / dt, 2),
            "fleet_replicas": len(reps),
            "fleet_replicas_live": sum(1 for r in reps if r.alive),
            "fleet_replicas_warming": sum(
                1 for r in reps if r.alive and not r.admitted
            ),
            "fleet_replica_lost": lost,
            "fleet_sessions": sessions,
            "fleet_hot_swaps": swaps,
            "fleet_scale_ups": scale_ups,
            "fleet_scale_downs": scale_downs,
            "fleet_migrations": migrations,
            "fleet_sessions_migrated": migrated,
            "fleet_migration_ms": round(migration_ms, 2),
            "fleet_failover_retries": retries,
            "fleet_preempt_drains": preempts,
            "fleet_poll_retries": poll_retries,
        }
        return record

    def _metrics_loop(self) -> None:
        while not self.shutdown_flag:
            time.sleep(self.stats_interval)
            if self.shutdown_flag:
                return
            try:
                append_metrics_record(
                    self._metrics_path, self.stats_record(advance_window=True)
                )
            except Exception as exc:
                print(f"fleet: metrics write failed: {type(exc).__name__}: {exc}")


def fleet_main(args: Dict[str, Any]) -> None:
    """``main.py --fleet``: the front-end tier over a configured replica
    fleet (``fleet.replicas`` — start each backend with ``--serve`` or
    ``--edge`` first).  With ``fleet.autoscale.enabled`` the router also
    spawns/retires local serving processes against the shed-rate SLO."""
    from ..utils import trace

    train = args["train_args"]
    fleet_cfg = train.get("fleet", {})
    if trace.configure(train.get("trace")):
        print(f"fleet: trace spans -> {trace.current_path()}")
    factory = None
    if (fleet_cfg.get("autoscale") or {}).get("enabled"):
        from .autoscale import ProcessReplicaFactory

        factory = ProcessReplicaFactory(args)
        print("fleet: autoscale armed (local process replicas)")
    router = FleetRouter(
        fleet_cfg, metrics_path=train.get("metrics_path"),
        replica_factory=factory,
    ).run()
    specs = ", ".join(
        r.spec.name + ("[edge]" if r.is_edge else "")
        for r in router._reps()
    )
    print(f"fleet: entry port {router.bound_port} over replicas {specs}")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        print("fleet: shutting down")
    finally:
        router.shutdown()
        if factory is not None:
            factory.close()
