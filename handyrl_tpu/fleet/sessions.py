"""Server-resident RNN session cache (docs/serving.md §Fleet tier).

PR 10's serving plane ships recurrent hidden state both ways on every
request (client keeps it, wire carries it) — for a DRC-sized state that
is ~25x the observation bytes.  A *session* pins that state next to the
model instead: ``open_session`` mints a session id, every ``infer``
carrying that sid reads its hidden from this cache and writes the next
step's state back, and the wire carries only the observation and the
policy/value outputs.

Residency discipline:

* resident entries live device-side (``jax.device_put`` onto the serving
  engine's device) so the next batch stacks them without a fresh host
  upload;
* over ``capacity`` the least-recently-used session is EVICTED to a
  host-side spill ring (bounded, ``spill_capacity``): device memory is
  the scarce resource, host RAM is the cheap second tier;
* a spilled session's next infer re-uploads it (counted
  ``session_restored``, traced as ``session.restore``) — bit-identical,
  pinned by the fleet tests;
* a session absent from BOTH tiers (spill overflow, or a request routed
  to a replica that never saw the sid — the front-end re-routes sessions
  off a dead replica) is an *affinity miss*: the cache re-adopts the sid
  with fresh initial state so the client keeps playing, and counts it —
  ONE miss per loss event (the re-adopted sid is fresh again, so a
  pipelined burst on a lost session cannot inflate the counter);
* planned retires move sessions instead of losing them:
  ``export_all`` realizes both tiers host-side and clears the cache
  (ownership transfer — a straggler infer after export is a counted
  miss, never a silent fork), ``adopt`` lands migrated sessions in the
  spill tier so their next infer re-uploads through the SAME
  ``session_restored`` path the spill ring already pins bit-identical.

The cache is transport-free and device-optional (``device=None`` keeps
everything host-side — the CPU edge replica's mode), so its semantics
pin socket-free in tests/test_fleet.py.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils import tree_map
from ..utils.trace import trace_event

__all__ = ["SessionCache"]


class SessionCache:
    """LRU session store: device-resident hidden state keyed by session id,
    with a bounded host-side spill ring as the second tier."""

    def __init__(self, capacity: int = 1024, spill_capacity: int = 4096,
                 device=None):
        self.capacity = max(1, int(capacity))
        self.spill_capacity = max(0, int(spill_capacity))
        # the pin target; the serving server adopts the engine's device on
        # first use (the router owns engine placement, not this cache)
        self.device = device
        # sid -> hidden pytree (device arrays when a device is set)
        self._resident: "OrderedDict[str, Any]" = OrderedDict()
        # sid -> host numpy pytree (evicted, awaiting restore or overflow)
        self._spill: "OrderedDict[str, Any]" = OrderedDict()
        # opened but not yet stored: their first lookup is a FRESH start,
        # not an affinity miss — the miss counter must mean "state lost",
        # or re-route diagnostics drown in session-open noise
        self._fresh: set = set()
        self._lock = threading.Lock()
        # sids are opaque strings unique ACROSS replicas: the front-end
        # keys its affinity map by sid alone, so two replicas minting
        # colliding ids would cross their sessions' routing
        self._prefix = os.urandom(4).hex()
        self._next = 0
        self.opened = 0
        self.closed = 0
        self.evictions = 0
        self.restored = 0
        self.affinity_misses = 0
        self.spill_drops = 0
        self.migrated_in = 0
        self.migrated_out = 0

    # -- lifecycle -----------------------------------------------------------

    def open(self) -> str:
        """Mint a session id.  No capacity is consumed until the first
        ``store`` — an opened-but-never-inferred session costs nothing."""
        with self._lock:
            self._next += 1
            self.opened += 1
            sid = f"s{self._prefix}-{self._next}"
            self._fresh.add(sid)
            return sid

    def close(self, sid: str) -> bool:
        """Release the session's slot (both tiers); True if it existed."""
        with self._lock:
            was_fresh = sid in self._fresh
            self._fresh.discard(sid)
            hit = bool(
                (self._resident.pop(sid, None) is not None)
                | (self._spill.pop(sid, None) is not None)
            ) or was_fresh
            # only real closes count: a double-close (or a stale sid) is a
            # no-op, and the counter must stay opened-minus-live honest
            self.closed += 1 if hit else 0
            return hit

    # -- the infer seams -----------------------------------------------------

    def lookup(self, sid: str) -> Tuple[Optional[Any], str]:
        """Fetch the session's hidden state for the next infer.

        Returns ``(hidden, status)`` with status one of ``resident`` /
        ``restored`` / ``fresh`` / ``miss``.  ``fresh`` (opened here, not
        yet stored) and ``miss`` (state lost: spill overflow or a session
        re-routed from a dead replica) both return ``hidden=None`` — the
        engine then uses the model's initial state — but only a miss is
        counted; the following ``store`` (re-)adopts the sid either way.
        """
        with self._lock:
            hidden = self._resident.get(sid)
            if hidden is not None:
                self._resident.move_to_end(sid)
                return hidden, "resident"
            spilled = self._spill.pop(sid, None)
            if spilled is None and sid in self._fresh:
                return None, "fresh"
        if spilled is None:
            with self._lock:
                self.affinity_misses += 1
                # the sid is re-adopted FRESH: exactly one counted miss
                # per loss event.  A pipelined second lookup before the
                # re-adopting store (or the re-opened session's eventual
                # close) now counts as a fresh open, not another miss
                self._fresh.add(sid)
            return None, "miss"
        t0 = time.monotonic()
        hidden = self._pin(spilled)
        trace_event("session.restore", time.monotonic() - t0, t0=t0,
                    plane="fleet")
        with self._lock:
            self.restored += 1
            self._resident[sid] = hidden
            self._resident.move_to_end(sid)
            self._evict_over_capacity()
        return hidden, "restored"

    def store(self, sid: str, hidden: Any) -> None:
        """Write the session's next-step hidden (the engine's output tree
        already lives host-side after the batch fetch; it is re-pinned to
        the device here, off the engine's dispatch path)."""
        if hidden is None:
            return
        pinned = self._pin(hidden)
        with self._lock:
            self._fresh.discard(sid)
            # a stateless-override infer (wire hidden wins over the cache)
            # can land while an older copy sits in the spill ring: drop the
            # stale copy so it neither inflates the spilled gauge nor
            # occupies ring capacity another session then drops for
            self._spill.pop(sid, None)
            self._resident[sid] = pinned
            self._resident.move_to_end(sid)
            self._evict_over_capacity()

    def _pin(self, hidden: Any) -> Any:
        if self.device is None:
            return tree_map(np.asarray, hidden)
        import jax

        return jax.device_put(hidden, self.device)

    def _evict_over_capacity(self) -> None:
        """Caller holds the lock.  LRU residents spill to the host ring;
        the ring itself drops ITS oldest beyond spill_capacity (those
        sessions resurface as affinity misses — counted, never a hang)."""
        while len(self._resident) > self.capacity:
            old_sid, old_hidden = self._resident.popitem(last=False)
            self.evictions += 1
            if self.spill_capacity <= 0:
                self.spill_drops += 1
                continue
            # host copy: np.asarray realizes device arrays — eviction is
            # the documented spill cost, paid off the engine's hot loop
            self._spill[old_sid] = tree_map(np.asarray, old_hidden)
            self._spill.move_to_end(old_sid)
            while len(self._spill) > self.spill_capacity:
                self._spill.popitem(last=False)
                self.spill_drops += 1

    # -- migration (docs/serving.md §Elastic fleet) --------------------------

    def export_all(self) -> Dict[str, Any]:
        """Realize every session host-side and CLEAR the cache — ownership
        transfer to a successor replica.  Returns ``{"sessions": {sid:
        numpy hidden tree}, "fresh": [sid, ...]}``: opened-but-never-
        stored sids travel too (with no state), so their first infer on
        the successor stays a fresh start, not a counted miss.  Clearing
        is the fork guard: a straggler infer landing here after export is
        a loud affinity miss, never a silently diverging second copy."""
        with self._lock:
            resident = list(self._resident.items())
            spilled = list(self._spill.items())
            fresh = sorted(self._fresh)
            self._resident.clear()
            self._spill.clear()
            self._fresh.clear()
            self.migrated_out += len(resident) + len(spilled)
        sessions: Dict[str, Any] = {}
        # spill-ring entries first, residents last: the successor's adopt
        # keeps insertion order, so the hotter tier stays newest in ITS ring
        for sid, hidden in spilled + resident:
            sessions[sid] = tree_map(np.asarray, hidden)
        return {"sessions": sessions, "fresh": fresh}

    def adopt(self, sessions: Dict[str, Any], fresh=()) -> int:
        """Land migrated sessions from a retiring replica's ``export_all``.
        State goes to the SPILL tier: the next infer re-uploads it through
        the counted ``session_restored`` path — the bit-identity mechanism
        the spill ring already pins — instead of this thread paying device
        uploads for sessions that may never speak again.  Returns the
        number of stateful sessions adopted."""
        t0 = time.monotonic()
        with self._lock:
            for sid in fresh:
                self._fresh.add(sid)
            for sid, hidden in (sessions or {}).items():
                self._fresh.discard(sid)
                if self.spill_capacity > 0:
                    self._spill[sid] = tree_map(np.asarray, hidden)
                    self._spill.move_to_end(sid)
                else:
                    # no spill ring configured: adopt straight to resident
                    self._resident[sid] = self._pin(hidden)
                    self._resident.move_to_end(sid)
            self.migrated_in += len(sessions or {})
            # over-capacity imports overflow EXACTLY like local spills:
            # oldest dropped, counted — a too-small ring is loud, not wedged
            while len(self._spill) > self.spill_capacity:
                self._spill.popitem(last=False)
                self.spill_drops += 1
            self._evict_over_capacity()
            n = len(sessions or {})
        trace_event("session.migrate", time.monotonic() - t0, t0=t0,
                    plane="fleet", sessions=n)
        return n

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "session_resident": len(self._resident),
                "session_spilled": len(self._spill),
                "session_opened": self.opened,
                "session_closed": self.closed,
                "session_evictions": self.evictions,
                "session_restored": self.restored,
                "session_affinity_miss": self.affinity_misses,
                "session_spill_drops": self.spill_drops,
                "session_migrated_in": self.migrated_in,
                "session_migrated_out": self.migrated_out,
            }
