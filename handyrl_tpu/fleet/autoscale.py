"""Fleet autoscaler: replica count driven by the shed-rate SLO
(docs/serving.md §Elastic fleet).

The signals are the ones already flowing: ``FleetRouter``'s stats polls
leave each replica's last ``serve_*`` record on its ``_Replica``; the
autoscaler windows those per-tick (shed delta over request delta =
the fleet shed RATE, mean queue depth = pressure before shedding
starts) and turns them into scale decisions with hysteresis:

* UP when the windowed shed rate crosses ``shed_slo`` or mean depth
  per replica crosses ``depth_high`` — but never while a previous
  spawn is still warming (stacking cold replicas is how thundering
  herds are made), and never inside ``cooldown_s`` of the last action;
* DOWN only after the fleet has been calm (zero sheds, mean depth
  under ``depth_low``) for ``scale_down_after_s`` straight — load
  storms are spiky, and a scale-down mid-lull that forces a scale-up
  seconds later pays two migrations for nothing.

A spawned replica is connected immediately but NOT routed to until its
warm probe passes (warm-then-admit, router_tier.py): a scaling-up fleet
never sheds a request into a cold engine's compile pause.  Scale-down
retires through the router's seal → drain → migrate → stop path, so it
loses zero sessions.

``ReplicaFactory`` is the pluggable "where do replicas come from" seam
— anything with ``spawn() -> ReplicaSpec`` / ``stop(spec)`` / ``close()``
serves.  ``ProcessReplicaFactory`` is the built-in: local serving-plane
processes (spawn context — a JAX parent must never fork), the shape
``main.py --fleet`` and the bench use; a cloud deployment would back the
same protocol with its instance API.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .router_tier import ReplicaSpec

__all__ = ["AutoscaleDecider", "Autoscaler", "ProcessReplicaFactory"]


# defaults mirrored in config.py DEFAULT_TRAIN_ARGS["fleet"]["autoscale"]
# (config validates; this module must also run with a bare dict in tests)
_DEFAULTS: Dict[str, Any] = {
    "enabled": False,
    "min_replicas": 1,
    "max_replicas": 4,
    "interval_s": 1.0,
    "shed_slo": 0.01,
    "depth_high": 64.0,
    "depth_low": 1.0,
    "scale_down_after_s": 30.0,
    "cooldown_s": 10.0,
    "warm_timeout_s": 120.0,
}


def _knob(cfg: Dict[str, Any], key: str):
    return cfg.get(key, _DEFAULTS[key])


class AutoscaleDecider:
    """The pure decision core — windowed signals in, ``"up"`` /
    ``"down"`` / ``None`` out.  No sockets, no threads, no clock of its
    own (``now`` is an argument), so the hysteresis contract pins
    socket-free in tests/test_fleet_elastic.py."""

    def __init__(self, cfg: Dict[str, Any]):
        cfg = dict(cfg or {})
        self.min_replicas = int(_knob(cfg, "min_replicas"))
        self.max_replicas = int(_knob(cfg, "max_replicas"))
        self.shed_slo = float(_knob(cfg, "shed_slo"))
        self.depth_high = float(_knob(cfg, "depth_high"))
        self.depth_low = float(_knob(cfg, "depth_low"))
        self.scale_down_after_s = float(_knob(cfg, "scale_down_after_s"))
        self.cooldown_s = float(_knob(cfg, "cooldown_s"))
        self._last_action_t: Optional[float] = None
        self._calm_since: Optional[float] = None

    def decide(self, now: float, replicas: int, warming: int,
               shed_rate: float, depth_mean: float) -> Optional[str]:
        """One tick: ``replicas`` counts every non-edge replica (warming
        included — it is capacity already paid for), ``warming`` the
        connected-but-not-yet-admitted subset."""
        if replicas < self.min_replicas:
            # below the floor (lost replicas, first tick): restore it
            # regardless of load or cooldown — the floor IS the contract
            self._calm_since = None
            self._last_action_t = now
            return "up"
        in_cooldown = (
            self._last_action_t is not None
            and now - self._last_action_t < self.cooldown_s
        )
        overloaded = shed_rate > self.shed_slo or depth_mean > self.depth_high
        if overloaded:
            self._calm_since = None
            if replicas < self.max_replicas and warming == 0 and not in_cooldown:
                self._last_action_t = now
                return "up"
            return None
        calm = shed_rate <= 0.0 and depth_mean < self.depth_low
        if not calm:
            self._calm_since = None
            return None
        if self._calm_since is None:
            self._calm_since = now
        if (
            replicas > self.min_replicas
            and warming == 0
            and not in_cooldown
            and now - self._calm_since >= self.scale_down_after_s
        ):
            self._last_action_t = now
            self._calm_since = None
            return "down"
        return None


class Autoscaler:
    """The loop thread: windows the router's polled stats into
    (shed_rate, depth_mean), asks the decider, and drives the router's
    scale_up / scale_down.  Owned and started by ``FleetRouter.run``."""

    def __init__(self, router, cfg: Dict[str, Any]):
        self.router = router
        self.cfg = dict(cfg or {})
        self.interval_s = float(_knob(self.cfg, "interval_s"))
        self.decider = AutoscaleDecider(self.cfg)
        # per-replica previous cumulative counters, keyed by spec name —
        # a replica's window survives list churn around it
        self._prev: Dict[str, Dict[str, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-autoscale"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def signals(self):
        """(replicas, warming, shed_rate, depth_mean) over the window
        since the previous call, from the routers' last polled stats."""
        reps = [r for r in self.router._reps() if not r.is_edge]
        live = [r for r in reps if r.alive and not r.sealed]
        warming = sum(1 for r in live if not r.admitted)
        shed_d = 0.0
        req_d = 0.0
        depths: List[float] = []
        seen = set()
        for rep in live:
            if not rep.admitted:
                continue
            stats = dict(rep._last_stats)
            name = rep.spec.name
            seen.add(name)
            prev = self._prev.get(name, {})
            shed_d += max(
                0.0,
                float(stats.get("serve_shed") or 0.0)
                - float(prev.get("serve_shed") or 0.0),
            )
            req_d += max(
                0.0,
                float(stats.get("serve_requests") or 0.0)
                - float(prev.get("serve_requests") or 0.0),
            )
            depths.append(float(stats.get("serve_depth") or 0.0))
            self._prev[name] = stats
        for name in list(self._prev):
            if name not in seen:
                del self._prev[name]
        shed_rate = shed_d / max(1.0, req_d)
        depth_mean = sum(depths) / len(depths) if depths else 0.0
        return len(live), warming, shed_rate, depth_mean

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            if self.router.shutdown_flag:
                return
            try:
                self.tick()
            except Exception as exc:
                # the autoscaler must never die silently mid-run: a fleet
                # stuck at the wrong size is an SLO breach, say so
                print(f"fleet: autoscale tick failed: "
                      f"{type(exc).__name__}: {exc}")

    def tick(self) -> Optional[str]:
        replicas, warming, shed_rate, depth_mean = self.signals()
        action = self.decider.decide(
            time.monotonic(), replicas, warming, shed_rate, depth_mean,
        )
        if action == "up":
            self.router.scale_up(
                reason=f" (shed_rate={shed_rate:.3f} depth={depth_mean:.1f})"
            )
        elif action == "down":
            self.router.scale_down(
                reason=f" (calm: depth={depth_mean:.1f})"
            )
        return action


# -- process-backed replica factory ------------------------------------------


def _spawned_replica_main(pipe, args: Dict[str, Any]) -> None:
    """Child entry (spawn context): one serving replica on an ephemeral
    port.  Binds FIRST and reports the port, THEN publishes/warms — the
    honest cold window warm-then-admit exists for: the router connects
    and probes while the engine compiles, and admits only once
    ``serve_models`` goes live."""
    from ..envs import make_env, prepare_env
    from ..models import init_variables
    from ..runtime.checkpoint import latest_verified_epoch, load_verified_params
    from ..serving.router import ModelRouter
    from ..serving.server import ServingServer

    train = args["train_args"]
    env_args = args["env_args"]
    prepare_env(env_args)
    env = make_env(env_args)
    module = env.net()
    env.reset()
    template_obs = env.observation(env.players()[0])
    model_dir = train.get("model_dir", "models")
    serving_cfg = dict(train.get("serving") or {}, port=0)

    router = ModelRouter(module, template_obs, serving_cfg, model_dir=model_dir)
    server = ServingServer(router, serving_cfg).run()
    pipe.send(server.bound_port)
    newest = 0
    try:
        newest = latest_verified_epoch(model_dir)
    except Exception:
        pass
    if newest > 0:
        template = init_variables(module, env)["params"]
        params = load_verified_params(model_dir, newest, template,
                                      pre_verified=True)
        router.publish(newest, params)
    else:
        router.publish(0, init_variables(module, env)["params"])
    try:
        pipe.recv()  # blocks until the factory says stop (or dies)
    except (EOFError, OSError):
        pass
    server.shutdown()


class ProcessReplicaFactory:
    """Spawn-context serving processes on this host — the built-in
    ``ReplicaFactory``.  ``spawn()`` blocks until the child reports its
    bound port (listening, NOT yet warm: admission is the router's
    probe), ``stop(spec)`` asks the child to exit and reaps it."""

    def __init__(self, args: Dict[str, Any], spawn_timeout_s: float = 120.0):
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        self.args = args
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._procs: Dict[str, Any] = {}  # spec name -> (process, pipe)
        self._lock = threading.Lock()

    def spawn(self) -> ReplicaSpec:
        parent, child = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_spawned_replica_main, args=(child, self.args), daemon=True
        )
        proc.start()
        child.close()
        if not parent.poll(self.spawn_timeout_s):
            proc.terminate()
            raise OSError(
                f"spawned replica reported no port within "
                f"{self.spawn_timeout_s:.0f}s"
            )
        port = int(parent.recv())
        spec = ReplicaSpec("127.0.0.1", port)
        with self._lock:
            self._procs[spec.name] = (proc, parent)
        return spec

    def stop(self, spec: ReplicaSpec) -> None:
        with self._lock:
            entry = self._procs.pop(spec.name, None)
        if entry is None:
            return
        proc, pipe = entry
        try:
            pipe.send("stop")
        except (BrokenPipeError, OSError):
            pass
        pipe.close()
        proc.join(timeout=10.0)
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=5.0)

    def close(self) -> None:
        with self._lock:
            procs, self._procs = dict(self._procs), {}
        for name, (proc, pipe) in procs.items():
            try:
                pipe.send("stop")
            except (BrokenPipeError, OSError):
                pass
            pipe.close()
        for name, (proc, _pipe) in procs.items():
            proc.join(timeout=10.0)
            if proc.is_alive():
                proc.terminate()
