"""Fleet serving tier: session-affinity router over N serving replicas,
the server-resident RNN session cache, and the CPU edge-replica backend.

Import order matters: ``serving.server`` imports ``fleet.sessions``, and
``router_tier``/``edge`` import from ``serving.client`` — keeping
``sessions`` first (and everything here importing serving SUBMODULES,
never the ``serving`` package) is what keeps the cycle open.
"""

from .sessions import SessionCache
from .edge import EdgeReplica, edge_main
from .router_tier import FleetRouter, ReplicaSpec, fleet_main
from .autoscale import AutoscaleDecider, Autoscaler, ProcessReplicaFactory

__all__ = [
    "AutoscaleDecider",
    "Autoscaler",
    "EdgeReplica",
    "FleetRouter",
    "ProcessReplicaFactory",
    "ReplicaSpec",
    "SessionCache",
    "edge_main",
    "fleet_main",
]
