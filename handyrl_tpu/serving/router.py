"""Multi-model router: N verified snapshots (or ensembles of them) served
concurrently, with zero-downtime hot-swap.

Routing contract (mirrors ``LocalModelServer.get`` so training-side model
ids keep their meaning on the serving plane):

* ``-1`` (or any id newer than the latest) — the latest published model;
* ``0`` — the zero-output RandomModel (an instant, device-free route:
  the well-defined baseline opponent, and a useful shed-free yardstick);
* a concrete epoch — that snapshot's resident engine, loaded
  digest-verified from the checkpoint manifest on first use (PR 2
  machinery); a snapshot that is missing/corrupt substitutes the latest
  engine and INCREMENTS ``substituted`` — never a silent swap;
* a list of ids — an ensemble route: one inference per member engine,
  outputs mean-pooled (the ensemble-first dispatch of ``agents.py``).

Hot-swap sequence (docs/serving.md §Hot-swap): ``publish`` builds the new
engine OFF the hot path, warms its power-of-two buckets (compiles
finish before any client can reach it), then flips the latest pointer
under the routing lock — one atomic reference swap.  The old engine
stays resident and keeps serving its queued + explicitly-routed
requests on the OLD params; when ``max_models`` evicts it, retirement is
``drain_and_stop`` (seal, complete everything admitted, then stop) on a
background thread — zero requests dropped, pinned by
tests/test_serving.py::test_hot_swap_under_load_drops_nothing.

Device placement: engines round-robin over the router's device list, so
distinct models land on distinct chips where available and their
dispatches (disjoint ``dispatch_serialized`` scopes) overlap; co-located
engines serialize their enqueues, which is exactly the DL002 invariant.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..agents import mean_pool_outputs
from ..models import InferenceModel, RandomModel, build_inference_model
from ..runtime.checkpoint import latest_verified_epoch, load_verified_params
from .batcher import BadRequest, ContinuousBatcher, ServeError, percentiles_ms

__all__ = ["ModelRouter", "EnsembleRoute", "RouteError", "ColdRoute"]

ModelId = Union[int, Sequence[int]]


class RouteError(ServeError):
    """No servable route for the requested model id."""

    kind = "bad_request"


class ColdRoute(Exception):
    """Control flow, not an error: resolving this id needs cold work (disk
    load / warm compiles / waiting on another loader).  Raised only under
    ``allow_cold=False`` so a latency-critical caller (the server's
    dispatch thread) can hand the request to a worker instead — closing
    the check-then-resolve race a separate is_resident probe would leave
    open."""


class _InstantRoute:
    """Model id 0: the zero-output RandomModel, resolved host-side with no
    device round-trip (its futures complete synchronously)."""

    def __init__(self, random_model: RandomModel):
        self._random = random_model

    def submit(self, obs, hidden=None, deadline=None) -> Future:
        fut: Future = Future()
        fut.set_result(self._random.inference(obs, hidden))
        return fut


class EnsembleRoute:
    """Mean-pooled multi-member route (Agent._forward semantics): one
    submit per member engine — they batch independently, possibly on
    different chips — and the combined future resolves when the last
    member lands.  Hidden state is not pooled (pooling recurrent state is
    meaningless); ensemble replies omit it."""

    def __init__(self, members: List[Tuple[int, ContinuousBatcher]]):
        self.members = members

    def submit(self, obs, hidden=None, deadline=None) -> Future:
        out: Future = Future()
        if hidden is not None:
            # cannot be honored (per-member recurrent state lives with the
            # caller, Agent-style) — refusing beats silently running every
            # member from initial state and returning wrong outputs
            out.set_exception(BadRequest(
                "ensemble routes cannot thread recurrent state; track "
                "per-member hidden client-side and submit per member"
            ))
            return out
        futs = [engine.submit(obs, None, deadline) for _, engine in self.members]
        # a member that failed SYNCHRONOUSLY (sealed engine racing an
        # eviction, shed) fails the combined future now, while the server's
        # re-resolve-once retry can still see it — waiting for the slow
        # members would surface the same failure asynchronously, past the
        # retry window
        for f in futs:
            exc = f.exception() if f.done() else None
            if exc is not None:
                out.set_exception(exc)
                return out
        pending = [len(futs)]
        lock = threading.Lock()

        def _one_done(_f):
            with lock:
                pending[0] -= 1
                if pending[0]:
                    return
            for f in futs:
                exc = f.exception()
                if exc is not None:
                    if not out.done():
                        out.set_exception(exc)
                    return
            pooled = mean_pool_outputs([f.result() for f in futs])
            if not out.done():
                out.set_result(pooled)

        for f in futs:
            f.add_done_callback(_one_done)
        return out


class ModelRouter:
    """Routes request model-ids to resident ContinuousBatcher engines."""

    def __init__(
        self,
        module,
        template_obs,
        serving_cfg: Dict[str, Any],
        model_dir: str = "models",
        devices=None,
    ):
        import jax

        self.module = module
        self.model_dir = model_dir
        self._template_obs = template_obs
        cfg = dict(serving_cfg or {})
        self.max_models = max(1, int(cfg.get("max_models", 4)))
        self.warm_buckets = [int(b) for b in cfg.get("warm_buckets", (1, 8))]
        self._engine_cfg = {
            "max_batch": int(cfg.get("max_batch", 64)),
            "max_wait_ms": float(cfg.get("max_wait_ms", 2.0)),
            "slo_ms": float(cfg.get("slo_ms", 200.0)),
            "shed_policy": cfg.get("shed_policy", "deadline"),
            "queue_bound": int(cfg.get("queue_bound", 1024)),
        }
        # engine param residency (models/quantize.py): every engine this
        # router builds — publish, cold resolve — goes through
        # build_inference_model, so 'int8' reaches the serving plane, the
        # fleet replicas, and the frozen league opponents from ONE knob
        self.weight_dtype = cfg.get("weight_dtype", "float32")
        self.calibration_batches = int(cfg.get("calibration_batches", 4))
        # optional replay-obs source (callable -> list of batched obs
        # pytrees) wired by owners that hold stored episodes; publish
        # then records the MEASURED fp32-vs-int8 output deviation
        self.calibration_source = None
        self.last_calibration: Optional[Dict[str, float]] = None
        # the fp32 checkpoint-shaped template publish() stores host-side:
        # int8 engines hold a restructured variables tree, so manifest
        # loads (serialization.from_bytes needs the fp32 structure) must
        # never read it back out of an engine
        self._template_params = None
        self._devices = list(devices) if devices is not None else list(jax.devices())
        self._spawned = 0
        self._lock = threading.Lock()
        self._engines: Dict[int, ContinuousBatcher] = {}
        self._touched: Dict[int, float] = {}
        self._latest_id: Optional[int] = None
        self._random: Optional[_InstantRoute] = None
        self._retiring: List[threading.Thread] = []
        # engines popped from the routing table but still draining: stats
        # must keep counting them (a popped engine's 10k served requests
        # vanishing for the drain window would read as a negative qps
        # downstream), and their FINAL counters fold into _retired_totals
        # once the serve thread has fully exited
        self._draining: List[ContinuousBatcher] = []
        self._retired_totals: Dict[str, int] = {}
        # one loader per cold snapshot id: a burst of requests for the
        # same non-resident epoch must pay ONE disk load + warm, not N
        self._loading: Dict[int, Future] = {}
        # terminal flag: a cold load or publish racing stop() must not
        # re-register a live engine into the cleared routing table (a
        # serve-thread + device-memory leak), nor surface as a KeyError
        self._stopped = False
        self.hot_swaps = 0
        self.substituted = 0
        self.last_warm_ms: Optional[float] = None
        # promotion-gate state (flywheel/quality.py): a STAGED candidate
        # is resident and addressable but latest does not flip until the
        # live-traffic verdict; after a promotion the displaced incumbent
        # stays resident as the quality sentinel's demote target.  Both
        # are exempt from LRU eviction while they hold these roles.
        self._candidate_id: Optional[int] = None
        self._incumbent_id: Optional[int] = None

    # -- engine construction / hot-swap --------------------------------------

    def _spawn(self, model: InferenceModel) -> ContinuousBatcher:
        device = self._devices[self._spawned % len(self._devices)]
        self._spawned += 1
        return ContinuousBatcher(
            model, [device], template_obs=self._template_obs, **self._engine_cfg
        ).start()

    def publish(self, model_id: int, params, warm: bool = True) -> float:
        """Serve ``params`` as ``model_id`` and make it the latest: build +
        warm the standby engine off the hot path, then flip atomically.
        Returns the warm-up wall ms (the pre-paid part of
        time-to-first-response)."""
        model = build_inference_model(self.module, params, self.weight_dtype)
        engine = self._spawn(model)
        warm_ms = engine.warm(self.warm_buckets, self._template_obs) if warm else 0.0
        self._maybe_calibrate(params)
        with self._lock:
            self._template_params = params
            if self._stopped:
                displaced = None
            else:
                prev = self._latest_id
                displaced = self._engines.pop(int(model_id), None)
                if displaced is not None:
                    self._draining.append(displaced)  # atomic with the pop
                self._engines[int(model_id)] = engine
                self._touched[int(model_id)] = time.monotonic()
                self._latest_id = int(model_id)
                if prev is not None and prev != int(model_id):
                    self.hot_swaps += 1
                self.last_warm_ms = warm_ms
                # a direct publish supersedes any in-flight gate: the id
                # just published stops being a candidate, and a newer
                # latest obsoletes the previous promotion's incumbent pin
                if self._candidate_id == int(model_id):
                    self._candidate_id = None
                if prev is not None and prev != int(model_id):
                    self._incumbent_id = None
            stopped = self._stopped
        if stopped:  # raced shutdown: nothing may re-register
            engine.stop()
            raise RouteError("router stopped")
        if displaced is not None:  # republished id: retire the old engine
            self._retire(displaced)
        self._evict_over_capacity()
        return warm_ms

    def maybe_refresh(self) -> Optional[int]:
        """Publish the newest manifest-verified snapshot if it is newer
        than the served latest (the checkpoint-watcher entry point).
        Returns the epoch published, or None."""
        newest = latest_verified_epoch(self.model_dir)
        with self._lock:
            current = self._latest_id
        if newest <= 0 or (current is not None and newest <= current):
            return None
        params = load_verified_params(
            self.model_dir, newest, self._params_template(), pre_verified=True
        )
        self.publish(newest, params)
        return newest

    # -- promotion gate (flywheel/quality.py drives these) --------------------

    def candidate_id(self) -> Optional[int]:
        with self._lock:
            return self._candidate_id

    def incumbent_id(self) -> Optional[int]:
        with self._lock:
            return self._incumbent_id

    def stage(self, model_id: int, params, warm: bool = True) -> float:
        """publish() minus the flip: build + warm an engine for
        ``model_id`` and register it as the CANDIDATE route.  Latest-
        addressed traffic keeps hitting the incumbent except for the
        shadow slice the server explicitly rewrites; the candidate is
        individually addressable by its epoch id."""
        model = build_inference_model(self.module, params, self.weight_dtype)
        engine = self._spawn(model)
        warm_ms = engine.warm(self.warm_buckets, self._template_obs) if warm else 0.0
        with self._lock:
            if self._stopped:
                displaced = None
            else:
                displaced = self._engines.pop(int(model_id), None)
                if displaced is not None:
                    self._draining.append(displaced)  # atomic with the pop
                self._engines[int(model_id)] = engine
                self._touched[int(model_id)] = time.monotonic()
                self._candidate_id = int(model_id)
                self.last_warm_ms = warm_ms
            stopped = self._stopped
        if stopped:  # raced shutdown: nothing may re-register
            engine.stop()
            raise RouteError("router stopped")
        if displaced is not None:
            self._retire(displaced)
        self._evict_over_capacity()
        return warm_ms

    def promote_candidate(self) -> Optional[int]:
        """Flip latest to the staged candidate (the gate cleared).  The
        displaced incumbent STAYS resident as the sentinel's demote
        target.  Returns the promoted id, or None without a candidate."""
        with self._lock:
            candidate = self._candidate_id
            if candidate is None or candidate not in self._engines:
                self._candidate_id = None
                return None
            prev = self._latest_id
            self._latest_id = candidate
            self._candidate_id = None
            self._incumbent_id = prev if prev != candidate else None
            self._touched[candidate] = time.monotonic()
            if prev is not None and prev != candidate:
                self.hot_swaps += 1
        return candidate

    def demote_candidate(self) -> Optional[int]:
        """Drop the staged candidate (the gate failed): unregister and
        retire its engine; latest never flipped, so traffic is untouched.
        Returns the demoted id, or None without a candidate."""
        with self._lock:
            candidate = self._candidate_id
            self._candidate_id = None
            engine = None
            if candidate is not None:
                engine = self._engines.pop(candidate, None)
                if engine is not None:
                    self._draining.append(engine)  # atomic with the pop
                self._touched.pop(candidate, None)
        if engine is not None:
            self._retire(engine)
        return candidate

    def demote_latest(self) -> Optional[int]:
        """Quality sentinel verdict: flip latest BACK to the resident
        incumbent and retire the regressed engine.  Returns the restored
        incumbent id, or None when there is no resident incumbent (then
        the bad latest keeps serving — a degraded model beats no model)."""
        with self._lock:
            incumbent = self._incumbent_id
            if incumbent is None or incumbent not in self._engines:
                return None
            bad = self._latest_id
            self._latest_id = incumbent
            self._incumbent_id = None
            self._touched[incumbent] = time.monotonic()
            self.hot_swaps += 1
            engine = None
            if bad is not None and bad != incumbent:
                engine = self._engines.pop(bad, None)
                if engine is not None:
                    self._draining.append(engine)  # atomic with the pop
                self._touched.pop(bad, None)
        if engine is not None:
            self._retire(engine)
        return incumbent

    def _maybe_calibrate(self, params) -> None:
        """Publish-time calibration for the int8 rung: replay stored
        observations (calibration_source, wired by owners with an episode
        store) through the fp32 and int8 applies and record the measured
        output deviation — never a weight-space bound."""
        if (
            self.weight_dtype != "int8"
            or self.calibration_batches <= 0
            or self.calibration_source is None
        ):
            return
        from ..models.quantize import calibration_report

        batches = list(self.calibration_source())[: self.calibration_batches]
        if batches:
            self.last_calibration = calibration_report(
                self.module, params, batches
            )

    def _params_template(self):
        """The fp32 checkpoint-shaped param tree manifest loads
        deserialize against.  Stored by publish() — an int8 engine's
        resident ``variables['params']`` no longer matches the fp32
        checkpoint structure, so reading it back out of an engine would
        break ``serialization.from_bytes``."""
        with self._lock:
            if self._template_params is None:
                raise RouteError("no model published yet")
            return self._template_params

    _COUNTER_KEYS = (
        "requests_admitted", "requests_served", "requests_shed",
        "deadline_misses", "batches_served",
    )

    def _fold_retired(self, engine: ContinuousBatcher) -> None:
        stats = engine.stats()
        with self._lock:
            # atomic hand-off from live-summed to folded: an engine must
            # never be counted in both places, or in neither
            if engine in self._draining:
                self._draining.remove(engine)
            for key in self._COUNTER_KEYS:
                self._retired_totals[key] = (
                    self._retired_totals.get(key, 0) + stats[key]
                )

    def _retire(self, engine: ContinuousBatcher) -> None:
        """Start the drain-then-fold for an engine the caller has ALREADY
        moved from ``_engines`` into ``_draining`` under the routing lock —
        the pop and the append must share one acquisition, or a stats()
        reader in between sees the engine's counters nowhere."""
        def _drain_then_fold():
            engine.drain_and_stop()
            # join the serve thread before reading final counters: its last
            # requests_served increment happens after the drain wait's
            # depth/inflight condition can already observe zero
            engine.join()
            self._fold_retired(engine)

        t = threading.Thread(target=_drain_then_fold, daemon=True,
                             name="serve-retire")
        with self._lock:
            # prune finished retirements: a server following a training run
            # retires one engine per swap for its whole life
            self._retiring = [x for x in self._retiring if x.is_alive()]
            self._retiring.append(t)
        t.start()

    def _evict_over_capacity(self, protect: Optional[int] = None) -> None:
        """``protect`` exempts the engine a resolve JUST spawned: retiring
        it before its own request submits would both waste the warm
        compile and intermittently fail the request (at max_models=1 it
        would be the only candidate).  Capacity may exceed by one until
        the next publish/resolve, when the engine is evictable like any
        other resident."""
        doomed: List[ContinuousBatcher] = []
        with self._lock:
            while len(self._engines) > self.max_models:
                # LRU among the non-latest residents; the latest is pinned,
                # and so are a staged candidate (mid-gate) and a promoted
                # snapshot's incumbent (the sentinel's demote target)
                candidates = [
                    k for k in self._engines
                    if k != self._latest_id and k != protect
                    and k != self._candidate_id and k != self._incumbent_id
                ]
                if not candidates:
                    break
                lru = min(candidates, key=lambda k: self._touched.get(k, 0.0))
                engine = self._engines.pop(lru)
                self._draining.append(engine)  # atomic with the pop
                doomed.append(engine)
                self._touched.pop(lru, None)
        for engine in doomed:
            self._retire(engine)

    # -- routing -------------------------------------------------------------

    def resolve(self, model_id: ModelId, allow_cold: bool = True):
        """(served_key, route) for a request's model id.  served_key is
        what reply frames report — the concrete id actually serving, so a
        client sees the flip the moment it happens.  ``allow_cold=False``
        raises ColdRoute instead of paying disk loads / warm compiles."""
        if isinstance(model_id, (list, tuple)):
            members: List[Tuple[int, ContinuousBatcher]] = []
            for mid in model_id:
                key, engine = self._resolve_single(int(mid), allow_cold)
                if not isinstance(engine, ContinuousBatcher):
                    raise RouteError(
                        f"ensemble member {mid} is not an engine-backed route"
                    )
                members.append((key, engine))
            if not members:
                raise RouteError("empty ensemble")
            return tuple(k for k, _ in members), EnsembleRoute(members)
        return self._resolve_single(int(model_id), allow_cold)

    def _resolve_single(self, mid: int, allow_cold: bool = True):
        with self._lock:
            if self._stopped:
                raise RouteError("router stopped")
        if mid == 0:
            with self._lock:
                unbuilt = self._random is None
            if unbuilt and not allow_cold:
                raise ColdRoute(mid)
            return 0, self._ensure_random()
        with self._lock:
            latest = self._latest_id
            if latest is None:
                raise RouteError("no model published yet")
            # a staged candidate usually carries an id NEWER than latest;
            # it must stay explicitly addressable (the shadow slice and
            # pinned candidate games route by its epoch id) rather than
            # collapsing into the newest-means-latest rule below
            if mid == self._candidate_id:
                engine = self._engines.get(mid)
                if engine is not None:
                    self._touched[mid] = time.monotonic()
                    return mid, engine
            if mid < 0 or mid >= latest:
                self._touched[latest] = time.monotonic()
                return latest, self._engines[latest]
            engine = self._engines.get(mid)
            if engine is not None:
                self._touched[mid] = time.monotonic()
                return mid, engine
        # old snapshot: digest-verified disk load, engine spun on demand —
        # exactly ONE loader per id; a concurrent burst for the same cold
        # epoch waits on the loader's future instead of each paying the
        # load + device_put + warm-up compiles again
        if not allow_cold:
            raise ColdRoute(mid)
        with self._lock:
            pending = self._loading.get(mid)
            if pending is None:
                pending = Future()
                self._loading[mid] = pending
                owner = True
            else:
                owner = False
        if not owner:
            engine = pending.result(timeout=600.0)
            if engine is None:  # the loader substituted: so do we, counted
                return self._substitute_latest()
            with self._lock:
                self._touched[mid] = time.monotonic()
            return mid, engine
        try:
            params = load_verified_params(
                self.model_dir, mid, self._params_template()
            )
            engine = self._spawn(
                build_inference_model(self.module, params, self.weight_dtype)
            )
            engine.warm(self.warm_buckets, self._template_obs)
        except Exception:
            # missing / GC'd / corrupt snapshot (or a failed spawn):
            # substitute latest, COUNTED (the silent-substitution lesson
            # from LocalModelServer.get) — and release the waiters
            with self._lock:
                self._loading.pop(mid, None)
            pending.set_result(None)
            return self._substitute_latest()
        with self._lock:
            if self._stopped:
                registered = None  # shutdown won: nothing may re-register
            else:
                raced = self._engines.get(mid)
                if raced is None:
                    self._engines[mid] = engine
                    registered = engine
                else:
                    # a publish() of this very id won the race: its engine
                    # is the routing truth — ours retires instead of
                    # silently displacing it (which would leak a live serve
                    # thread and its device-resident params)
                    registered = raced
                self._touched[mid] = time.monotonic()
            self._loading.pop(mid, None)
        pending.set_result(registered)
        if registered is None:
            engine.stop()
            raise RouteError("router stopped")
        if registered is not engine:
            engine.stop()  # nothing was ever admitted to it
        else:
            self._evict_over_capacity(protect=mid)
        return mid, registered

    def _substitute_latest(self):
        with self._lock:
            latest = self._latest_id
            engine = None if latest is None else self._engines.get(latest)
            if engine is None:  # stopped (or nothing published) mid-race
                raise RouteError(
                    "router stopped" if self._stopped else "no model published yet"
                )
            self.substituted += 1
            self._touched[latest] = time.monotonic()
            return latest, engine

    def _ensure_random(self) -> _InstantRoute:
        with self._lock:
            if self._random is not None:
                return self._random
            if self._latest_id is None:
                raise RouteError("no model published yet")
            engine = self._engines[self._latest_id]
        # output spec from one engine round-trip (through the engine's own
        # locks, not a bare device call)
        out = engine.submit(self._template_obs).result(timeout=60.0)
        spec = {
            k: (np.shape(v), np.asarray(v).dtype)
            for k, v in out.items()
            if k != "hidden" and v is not None
        }
        with self._lock:
            if self._random is None:
                self._random = _InstantRoute(RandomModel(spec))
            return self._random

    # -- introspection / teardown --------------------------------------------

    def latest_id(self) -> Optional[int]:
        with self._lock:
            return self._latest_id

    def routes(self) -> List[int]:
        with self._lock:
            return sorted(self._engines)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            # ONE consistent cut: draining engines still count (their work
            # must not vanish for the drain window), and the retired totals
            # copy under the SAME acquisition — fold-in moves an engine
            # from _draining to _retired_totals atomically, so splitting
            # these reads across two acquisitions could count a
            # just-folded engine in both
            engines = list(self._engines.values()) + list(self._draining)
            n_models = len(self._engines)
            retired = dict(self._retired_totals)
        per_engine = [e.stats() for e in engines]
        samples: List[float] = []
        for e in engines:
            samples.extend(e.latencies_ms())
        pct = percentiles_ms(samples)
        total = lambda key: sum(s[key] for s in per_engine) + retired.get(key, 0)
        return {
            "models": n_models,
            # instantaneous queue pressure across engines (queued + on the
            # device): the fleet front-end's balancing signal, polled via
            # the stats frame — NOT in _COUNTER_KEYS (it is a gauge, so
            # retired engines contribute nothing by construction)
            "depth": sum(s["depth"] + s["inflight"] for s in per_engine),
            "requests_admitted": total("requests_admitted"),
            "requests_served": total("requests_served"),
            "requests_shed": total("requests_shed"),
            "deadline_misses": total("deadline_misses"),
            "batches_served": total("batches_served"),
            "hot_swaps": self.hot_swaps,
            "substituted": self.substituted,
            "last_warm_ms": self.last_warm_ms,
            "p50_ms": pct[50],
            "p99_ms": pct[99],
        }

    def stop(self, drain: bool = False, timeout: float = 10.0) -> None:
        with self._lock:
            self._stopped = True
            engines = list(self._engines.values())
            self._engines.clear()
            self._touched.clear()
            retiring = list(self._retiring)
        for engine in engines:
            if drain:
                engine.drain_and_stop(timeout)
            else:
                engine.stop()
        for t in retiring:
            t.join(timeout)
