"""Continuous batcher: iteration-level scheduling for the serving plane.

Generalizes ``BatchedInferenceEngine``'s drain loop (Orca-style): instead
of filling a fixed batch and waiting out a batch boundary, the dispatcher
assembles the NEXT device batch from whatever is queued the moment the
previous dispatch is enqueued — a request that expires on the way to the
device frees its bucket slot to the next queued request in the SAME
gather pass, so slots recycle at iteration granularity, not batch
granularity.

Latency discipline (docs/serving.md §SLO semantics):

* every request carries a deadline (caller-supplied, else now +
  ``slo_ms``);
* the admission controller fast-fails (``RequestShed``) when the
  PREDICTED completion — queue depth in batch waves x the EMA batch
  service time — already exceeds the deadline: under overload the queue
  must stay shallow and reject quickly, never collapse into a backlog
  where every admitted request is late (shed-fast beats serve-all-late);
* a request whose deadline passes while queued is failed with
  ``DeadlineExceeded`` at gather time, without spending a device slot.

Device discipline: batches pad to the power-of-two buckets of
``next_bucket`` (a handful of compiled shapes), ``warm()`` compiles them
off the hot path (hot-swap warms the standby engine before the router
flips), and every dispatch runs under ``dispatch_serialized`` with this
engine's explicit device scope — engines of different models placed on
different chips dispatch concurrently; engines sharing a chip serialize
their enqueues (the DL002 invariant).  The host fetch happens OUTSIDE
the device locks (``fetch_outputs``).

Lifecycle is single-owner-drain (the ``BatchedInferenceEngine`` fix):
submit/stop order through one lifecycle gate, and exactly one party —
the serve thread, or ``stop()`` when none exists — fails the stragglers.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..models.inference import fetch_outputs
from ..parallel.mesh import dispatch_serialized
from ..runtime.inference_engine import EngineStopped, next_bucket, stack_padded
from ..utils import tree_map
from ..utils.trace import trace_event

__all__ = [
    "ContinuousBatcher", "ServeError", "RequestShed", "DeadlineExceeded",
    "BadRequest", "obs_spec",
]


class ServeError(RuntimeError):
    """Base class for request-level serving failures (wire kind tag)."""

    kind = "error"


class RequestShed(ServeError):
    """Admission controller fast-fail: the SLO budget is already spent."""

    kind = "shed"


class DeadlineExceeded(ServeError):
    """The request's deadline passed while it sat in the queue."""

    kind = "deadline"


class BadRequest(ServeError):
    """The request's observation does not match the model's input spec."""

    kind = "bad_request"


def obs_spec(tree):
    """Nested shape+dtype fingerprint of an observation pytree — the
    admission gate's input contract.  One malformed obs must fail ITS OWN
    future, never reach ``tree_stack`` where it would poison every
    co-batched request with a stacking error — and dtype is part of the
    contract: a wrong-dtype batch is a fresh jit signature, i.e. a
    hot-path compile a single client could trigger at will."""
    if isinstance(tree, dict):
        return {k: obs_spec(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return tuple(obs_spec(v) for v in tree)
    dtype = getattr(tree, "dtype", None)
    return (np.shape(tree), None if dtype is None else np.dtype(dtype).str)


class _Request:
    __slots__ = ("obs", "hidden", "fut", "deadline", "t0")

    def __init__(self, obs, hidden, fut, deadline, t0):
        self.obs = obs
        self.hidden = hidden
        self.fut = fut
        self.deadline = deadline
        self.t0 = t0


class _LatencyRing:
    """Fixed-size reservoir of recent request latencies (ms).

    A ring, not a full history: the serving percentiles must reflect the
    CURRENT operating point (post-swap, post-load-change), and an
    unbounded list would grow for the life of the server."""

    def __init__(self, size: int = 4096):
        self._buf = [0.0] * size
        self._n = 0
        self._lock = threading.Lock()

    def add(self, ms: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = ms
            self._n += 1

    def snapshot(self) -> List[float]:
        with self._lock:
            if self._n >= len(self._buf):
                return list(self._buf)
            return self._buf[: self._n]


def percentiles_ms(samples: Sequence[float], qs=(50, 99)) -> Dict[int, Optional[float]]:
    """Nearest-rank percentiles of a latency sample (None when empty)."""
    if not samples:
        return {q: None for q in qs}
    ordered = sorted(samples)
    out = {}
    for q in qs:
        idx = min(len(ordered) - 1, max(0, int(round(q / 100.0 * len(ordered))) - 1))
        out[q] = ordered[idx]
    return out


class ContinuousBatcher:
    """One model's serving engine: iteration-level batched inference with
    per-request deadlines and load shedding."""

    def __init__(
        self,
        model,
        devices,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        slo_ms: float = 200.0,
        shed_policy: str = "deadline",
        queue_bound: int = 1024,
        template_obs=None,
    ):
        import jax

        self.model = model
        # variables committed to this engine's device at construction (off
        # the hot path): the jitted apply then runs there, so the router
        # can spread model engines across chips and their dispatches —
        # holding disjoint device locks — overlap
        self._devices = list(devices)
        self.model.variables = jax.device_put(self.model.variables, self._devices[0])
        self.max_batch = max(1, int(max_batch))
        self.max_wait = float(max_wait_ms) / 1000.0
        self.slo_s = float(slo_ms) / 1000.0
        self.shed_policy = shed_policy
        self.queue_bound = max(1, int(queue_bound))
        self._obs_spec = None if template_obs is None else obs_spec(template_obs)
        hidden_template = self.model.init_hidden()
        self._hidden_spec = (
            None if hidden_template is None else obs_spec(hidden_template)
        )
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._gate = threading.Lock()  # lifecycle + admission state
        self._sealed = False           # drain mode: no new admissions
        self._depth = 0                # admitted, not yet gathered
        self._inflight = 0             # gathered, dispatch not yet scattered
        self._ema_batch_s: Optional[float] = None
        # counters: admitted/shed move under the gate; the rest are only
        # touched by the single dispatcher thread
        self.requests_admitted = 0
        self.requests_served = 0
        self.requests_shed = 0
        self.deadline_misses = 0
        self.batches_served = 0
        self.buckets_warmed: List[int] = []
        # bucket sizes whose compile has already been paid (warm() seeds
        # these): a bucket's FIRST execution is compile-dominated and must
        # not feed the service-time EMA — one 300ms compile read as the
        # steady service rate would shed every future request, and with
        # nothing admitted the estimate could never recover
        self._timed_buckets: set = set()
        self._latency = _LatencyRing()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True, name="serve-batcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._gate:
            if self._stop.is_set():
                return
            self._stop.set()
            self._queue.put(None)  # wake the dispatcher
            thread = self._thread
        if thread is None:
            self._fail_pending()

    def join(self, timeout: float = 5.0) -> None:
        """Wait for the serve thread to fully exit (after stop): its last
        counter increments happen after drain waiters can already observe
        an empty queue, so readers of FINAL counters join first."""
        if self._thread is not None:
            self._thread.join(timeout)

    def seal(self) -> None:
        """Refuse new admissions; everything already admitted completes."""
        with self._gate:
            self._sealed = True

    def drain_and_stop(self, timeout: float = 30.0) -> bool:
        """Zero-drop retirement: seal, wait for the queue AND the in-flight
        batch to finish, then stop.  Returns False when the timeout fired
        with work still pending (that work is then failed by stop())."""
        self.seal()
        deadline = time.monotonic() + timeout
        drained = False
        while time.monotonic() < deadline:
            with self._gate:
                if self._depth == 0 and self._inflight == 0:
                    drained = True
                    break
            time.sleep(0.002)
        self.stop()
        return drained

    def _fail_pending(self) -> None:
        """Single-owner final drain (see BatchedInferenceEngine): runs on
        the serve thread after it observes stop, or inside stop() when the
        engine never started."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            with self._gate:
                self._depth -= 1
            if not item.fut.done():
                item.fut.set_exception(EngineStopped("serving engine stopped"))

    # -- client API ---------------------------------------------------------

    def submit(self, obs, hidden=None, deadline: Optional[float] = None) -> Future:
        """Queue one request; the future resolves to the numpy output tree
        or raises RequestShed / DeadlineExceeded / EngineStopped.  A shed
        decision is made HERE, synchronously — fast-fail is the contract."""
        fut: Future = Future()
        now = time.monotonic()
        if deadline is None and self.shed_policy != "none":
            # 'none' is drain semantics — every admitted request completes,
            # so no default budget is imposed; a caller-supplied deadline
            # (explicit slo_ms in the frame) is still honored
            deadline = now + self.slo_s
        if self._obs_spec is not None and obs_spec(obs) != self._obs_spec:
            fut.set_exception(BadRequest(
                "observation does not match the model's input spec"
            ))
            return fut
        if hidden is not None:
            # same isolation contract as obs: a malformed hidden must fail
            # ITS request, never the whole batch at tree_stack
            if self._hidden_spec is None or obs_spec(hidden) != self._hidden_spec:
                fut.set_exception(BadRequest(
                    "hidden state does not match the model's recurrent spec"
                ))
                return fut
        with self._gate:
            if self._sealed or self._stop.is_set():
                fut.set_exception(EngineStopped("serving engine stopped"))
                return fut
            why = self._admission_check(now, deadline)
            if why is not None:
                self.requests_shed += 1
                fut.set_exception(RequestShed(why))
                return fut
            self.requests_admitted += 1
            self._depth += 1
            self._queue.put(_Request(obs, hidden, fut, deadline, now))
        return fut

    def _admission_check(self, now: float, deadline: float) -> Optional[str]:
        """None = admit; else the shed reason.  Caller holds the gate."""
        if self.shed_policy == "none":
            return None
        if self._depth == 0 and not self._inflight:
            # idle engine: the only wait ahead is the request's own service
            # time — serve it.  This is also the estimator's recovery
            # valve: a transient stall (GC pause, noisy neighbor) that
            # inflated the EMA would otherwise shed every request, run no
            # batches, and freeze the bad estimate in place forever
            return None
        if self._depth >= self.queue_bound:
            return f"queue depth {self._depth} at bound {self.queue_bound}"
        if self.shed_policy == "deadline" and self._ema_batch_s is not None:
            # batch waves ahead of this request: the queue in front of it,
            # itself, and the batch currently on the device
            waves = self._depth // self.max_batch + 1 + (1 if self._inflight else 0)
            predicted = now + waves * self._ema_batch_s
            if predicted > deadline:
                budget_ms = (deadline - now) * 1000.0
                return (
                    f"predicted completion {waves} batch wave(s) x "
                    f"{self._ema_batch_s * 1000.0:.1f}ms exceeds the "
                    f"{budget_ms:.1f}ms SLO budget"
                )
        return None

    # -- dispatcher ---------------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            requests = self._gather()
            if not requests:
                continue
            try:
                self._execute(requests)
            except Exception as exc:  # propagate to every waiter
                for r in requests:
                    if not r.fut.done():
                        r.fut.set_exception(exc)
            finally:
                with self._gate:
                    self._inflight = 0
        self._fail_pending()

    def _take(self, req: _Request, live: List[_Request], now: float) -> None:
        """Admit one popped request into the forming batch — or expire it,
        FREEING its slot to whatever the gather pulls next (the
        iteration-level property: an expiry never wastes device work)."""
        expired = req.deadline is not None and now > req.deadline
        with self._gate:
            # depth -> inflight moves atomically per LIVE request, so a
            # drain_and_stop poll can never observe zero/zero while the
            # forming batch holds real work (e.g. during the straggler wait)
            self._depth -= 1
            if not expired:
                self._inflight += 1
        if expired:
            self.deadline_misses += 1
            if not req.fut.done():
                req.fut.set_exception(DeadlineExceeded(
                    f"deadline passed {(now - req.deadline) * 1000.0:.1f}ms "
                    "before dispatch"
                ))
            return
        live.append(req)

    def _gather(self) -> List[_Request]:
        """Form the next device batch: block for the first live request,
        then sweep everything already queued, waiting at most ``max_wait``
        for stragglers once the queue runs dry."""
        item = self._queue.get()
        live: List[_Request] = []
        first_t = time.monotonic()
        while True:
            if item is None:
                break  # stop token; the loop condition handles the rest
            self._take(item, live, time.monotonic())
            if len(live) >= self.max_batch:
                break
            try:
                item = self._queue.get_nowait()
                continue
            except queue.Empty:
                pass
            if not live:
                if self._stop.is_set():
                    break
                item = self._queue.get()  # everything expired: block again
                first_t = time.monotonic()
                continue
            remaining = (first_t + self.max_wait) - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
        return live  # _take already accounted every live request as in flight

    def _execute(self, requests: List[_Request]) -> None:
        model = self.model
        n = len(requests)
        bucket = next_bucket(n, self.max_batch)
        obs_batch, hidden_batch = stack_padded(
            [r.obs for r in requests], [r.hidden for r in requests],
            bucket, model.init_hidden(),
        )
        t0 = time.monotonic()
        device_out = dispatch_serialized(
            lambda: model.inference_batch_async(obs_batch, hidden_batch),
            self._devices,
        )
        outputs = fetch_outputs(device_out)  # host fetch outside the locks
        done = time.monotonic()
        # dispatch -> outputs-on-host for this batch; the per-request
        # "serve.request" span (server.py) brackets admit -> reply around it
        trace_event("serve.batch", done - t0, t0=t0, plane="serving",
                    n=n, bucket=bucket)
        self._note_batch(done - t0, bucket)
        with self._gate:
            # the device work is over: a waiter woken by the scatter below
            # must not see this batch as still in flight (its re-submit
            # would be predicted one wave late; the serve loop's finally
            # remains the backstop on the exception path)
            self._inflight = 0

        for i, r in enumerate(requests):
            if not r.fut.done():
                r.fut.set_result(tree_map(lambda x: x[i], outputs))
            self._latency.add((done - r.t0) * 1000.0)
        self.batches_served += 1
        self.requests_served += n

    def _note_batch(self, seconds: float, bucket: int) -> None:
        if bucket not in self._timed_buckets:
            # first execution at this bucket: compile-dominated, not a
            # service-time sample (see _timed_buckets)
            self._timed_buckets.add(bucket)
            return
        if self._ema_batch_s is None:
            self._ema_batch_s = seconds
        else:
            self._ema_batch_s = 0.8 * self._ema_batch_s + 0.2 * seconds

    # -- warm-up ------------------------------------------------------------

    def warm(self, buckets: Sequence[int], template_obs, template_hidden=None) -> float:
        """Compile each bucket shape off the hot path (dummy batches from
        the template observation); returns wall ms.  The hot-swap router
        runs this on the STANDBY engine before flipping, so the first
        post-swap request never pays an XLA compile."""
        t0 = time.monotonic()
        model = self.model
        template = model.init_hidden() if template_hidden is None else template_hidden
        for b in sorted({max(1, min(int(x), self.max_batch)) for x in buckets}):
            obs_batch, hidden_batch = stack_padded(
                [template_obs] * b, [None] * b, b, template
            )
            device_out = dispatch_serialized(
                lambda: model.inference_batch_async(obs_batch, hidden_batch),
                self._devices,
            )
            fetch_outputs(device_out)  # realized: the compile has finished
            self.buckets_warmed.append(b)
            self._timed_buckets.add(b)  # compile paid: future runs are samples
        return (time.monotonic() - t0) * 1000.0

    # -- introspection ------------------------------------------------------

    @property
    def device(self):
        """The engine's primary device — where its variables are committed
        and where the session cache pins resident hidden state so the next
        batch stacks it without a fresh host upload."""
        return self._devices[0]

    def latencies_ms(self) -> List[float]:
        return self._latency.snapshot()

    def stats(self) -> Dict[str, Any]:
        with self._gate:
            depth = self._depth
            inflight = self._inflight
            ema = self._ema_batch_s
        pct = percentiles_ms(self.latencies_ms())
        return {
            "requests_admitted": self.requests_admitted,
            "requests_served": self.requests_served,
            "requests_shed": self.requests_shed,
            "deadline_misses": self.deadline_misses,
            "batches_served": self.batches_served,
            "depth": depth,
            "inflight": inflight,
            "ema_batch_ms": None if ema is None else ema * 1000.0,
            "p50_ms": pct[50],
            "p99_ms": pct[99],
        }
