"""The standalone inference serving plane (docs/serving.md).

Layered on the pieces the training stack already proved out: jitted
numpy-in/out ``InferenceModel``s, manifest-verified snapshot loading,
the framed-socket transport with per-peer bounded send queues, and the
per-device dispatch-lock registry.

* ``ContinuousBatcher`` — iteration-level batched inference with
  per-request deadlines and SLO-driven load shedding.
* ``ModelRouter`` — N resident snapshot engines + ensemble routes,
  zero-downtime warm-then-flip hot-swap.
* ``ServingServer`` / ``ServingClient`` — the network front and its
  pipelined client.
"""

from .batcher import (
    BadRequest,
    ContinuousBatcher,
    DeadlineExceeded,
    RequestShed,
    ServeError,
)
from .client import ServingClient, ServingError
from .router import EnsembleRoute, ModelRouter, RouteError
from .server import ServingServer, serve_main

__all__ = [
    "BadRequest",
    "ContinuousBatcher",
    "DeadlineExceeded",
    "RequestShed",
    "ServeError",
    "ServingClient",
    "ServingError",
    "EnsembleRoute",
    "ModelRouter",
    "RouteError",
    "ServingServer",
    "serve_main",
]
