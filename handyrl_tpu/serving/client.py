"""Serving-plane client: pipelined request/reply over one framed socket.

One connection, many outstanding requests: every frame carries a ``rid``
and a single receiver thread resolves the matching future, so a caller
can keep a submit window open (the load generator the bench uses) or use
the blocking ``infer`` facade.  Server-side sheds and deadline misses
surface as ``ServingError`` with the wire ``kind`` — fast-fail reaches
the caller as an exception, never as a hang.

Liveness: ``stall_timeout`` arms the framed transport's stall deadline
on the receive side — a peer that keeps the socket open but stops
sending bytes while requests are pending fails every pending future
with ``ServingError(kind="stalled")`` instead of hanging them until
their per-call timeouts.  An idle connection (nothing pending) is never
reaped: request/reply clients are legitimately bursty.

Desync visibility: a reply frame whose ``rid`` is missing or unknown
(a confused or misbehaving server) is COUNTED (``replies_orphaned``)
and warned about once, instead of being silently dropped.

Sessions (docs/serving.md §Fleet tier): ``open_session`` pins recurrent
hidden state server-side; ``submit(..., sid=...)`` then carries only the
observation — the ship-hidden-state-both-ways path stays available as
the stateless fallback.
"""

from __future__ import annotations

import socket
import sys
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..runtime.connection import connect_socket_connection
from ..utils import tree_map

__all__ = ["ServingClient", "ServingError"]


class ServingError(RuntimeError):
    """Server-reported request failure; ``kind`` is the wire tag
    (shed / deadline / stopped / bad_request / swap_failed / stalled /
    replica_lost / ...)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(f"[{kind}] {msg}")
        self.kind = kind


class ServingClient:
    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 retry_seconds: float = 0.0,
                 stall_timeout: Optional[float] = None,
                 on_notice=None):
        self.conn = connect_socket_connection(
            host, int(port), timeout=timeout, retry_seconds=retry_seconds
        )
        self.stall_timeout = (
            None if not stall_timeout else float(stall_timeout)
        )
        # server-pushed notice frames (rid-less by design — e.g. the
        # "draining" broadcast a preempted replica sends every peer):
        # delivered here instead of the orphan counter.  Called on the
        # receiver thread, so handlers must only hand off, never block
        self.on_notice = on_notice
        self._lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._rid = 0
        self._closed = False
        self.replies_orphaned = 0
        self._orphan_warned = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="serve-client-recv"
        )
        self._recv_thread.start()

    # -- plumbing -----------------------------------------------------------

    def _recv_loop(self) -> None:
        while True:
            try:
                kind, data = self.conn.recv(timeout=self.stall_timeout)
            except socket.timeout:
                # the transport's stall deadline fired: no bytes for
                # stall_timeout.  With nothing pending that is just an
                # idle connection — keep listening (the gap deadline
                # consumed no partial frame, so the stream stays synced).
                # With requests pending the peer is wedged: fail them
                # all loudly and close — the stream may now be mid-frame
                with self._lock:
                    n_pending = len(self._pending)
                if n_pending == 0:
                    continue
                self._fail_all(ServingError(
                    "stalled",
                    f"server sent no bytes for {self.stall_timeout:.1f}s "
                    f"with {n_pending} request(s) pending",
                ))
                self.conn.close()
                return
            except Exception:
                self._fail_all(ConnectionResetError("serving connection lost"))
                return
            if kind == "heartbeat" or kind == "__hb__":
                continue
            if kind == "draining":
                # a preempting server announcing its drain window: a
                # notice, not a reply — it must reach the hook (the fleet
                # router's session-handoff trigger) before orphan counting
                hook = self.on_notice
                if hook is not None:
                    try:
                        hook(kind, data if isinstance(data, dict) else {})
                    except Exception:
                        pass  # the receiver thread outlives any bad hook
                continue
            rid = (data or {}).get("rid") if isinstance(data, dict) else None
            with self._lock:
                fut = self._pending.pop(rid, None)
            if fut is None or fut.done():
                # missing/unknown/duplicate rid: a desynced or misbehaving
                # server must be visible, not silently absorbed
                self.replies_orphaned += 1
                if not self._orphan_warned:
                    self._orphan_warned = True
                    print(
                        f"serving client: orphaned reply frame "
                        f"(kind={kind!r}, rid={rid!r}) — counting in "
                        "replies_orphaned; further orphans are silent",
                        file=sys.stderr,
                    )
                continue
            if kind == "error":
                fut.set_exception(
                    ServingError(data.get("kind", "error"), data.get("msg", ""))
                )
            elif kind == "stats":
                fut.set_result(data.get("stats"))
            else:  # result / swapped / session / session_closed
                fut.set_result(data)

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    def _send(self, req: str, data: Dict[str, Any]) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed:
                fut.set_exception(ConnectionResetError("client closed"))
                return fut
            self._rid += 1
            rid = self._rid
            self._pending[rid] = fut
        try:
            self.conn.send((req, dict(data, rid=rid)))
        except Exception as exc:
            with self._lock:
                self._pending.pop(rid, None)
            if not fut.done():
                fut.set_exception(exc)
        return fut

    # -- API ----------------------------------------------------------------

    def submit(self, obs, model=-1, hidden=None,
               slo_ms: Optional[float] = None,
               sid: Optional[str] = None) -> Future:
        """Async inference; resolves to {"model": served_id, "out": tree}.
        With ``sid`` the server reads/writes the session's hidden state —
        the wire carries neither direction of it."""
        data: Dict[str, Any] = {"model": model, "obs": obs}
        if hidden is not None:
            data["hidden"] = hidden
        if slo_ms is not None:
            data["slo_ms"] = float(slo_ms)
        if sid is not None:
            data["sid"] = sid
        return self._send("infer", data)

    def infer(self, obs, model=-1, hidden=None, slo_ms: Optional[float] = None,
              sid: Optional[str] = None,
              timeout: float = 60.0) -> Dict[str, Any]:
        return self.submit(obs, model, hidden, slo_ms, sid).result(timeout=timeout)

    def open_session(self, model=-1, timeout: float = 30.0) -> str:
        """Open a server-resident recurrent session; returns its sid."""
        reply = self._send("open_session", {"model": model}).result(timeout=timeout)
        return reply["sid"]

    def close_session(self, sid: str, timeout: float = 30.0) -> Dict[str, Any]:
        return self._send("close_session", {"sid": sid}).result(timeout=timeout)

    def stats(self, timeout: float = 30.0) -> Dict[str, Any]:
        return self._send("stats", {}).result(timeout=timeout)

    def swap(self, model_id: int, params=None, timeout: float = 300.0) -> Dict[str, Any]:
        """Hot-swap the served latest to ``model_id`` (params inline, or
        loaded digest-verified from the server's model dir when None).
        Blocks until the standby engine is warm and the flip happened."""
        data: Dict[str, Any] = {"id": int(model_id)}
        if params is not None:
            # the wire codec speaks numpy pytrees; a device-resident params
            # tree (fresh from a train step) converts here, once
            data["params"] = tree_map(np.asarray, params)
        return self._send("swap", data).result(timeout=timeout)

    def export_sessions(self, timeout: float = 60.0) -> Dict[str, Any]:
        """Pull the server's whole session cache (migration source side):
        {"sessions": {sid: numpy hidden tree}, "fresh": [...], "count"}.
        The server CLEARS its cache — ownership transfers to the caller."""
        return self._send("export_sessions", {}).result(timeout=timeout)

    def import_sessions(self, sessions: Dict[str, Any], fresh=(),
                        timeout: float = 60.0) -> Dict[str, Any]:
        """Hand migrated sessions to the successor replica (adopt —
        they land in its spill tier and restore bit-identically)."""
        return self._send("import_sessions", {
            "sessions": sessions or {}, "fresh": list(fresh),
        }).result(timeout=timeout)

    # -- data flywheel (docs/serving.md §Data flywheel) ----------------------

    def harvest_open(self, players, sids, timeout: float = 30.0) -> str:
        """Bind one game's per-player sessions into a harvest episode on
        the server; returns the harvest id.  ``players``/``sids`` are
        parallel lists — the server captures each sid's obs/policy/value
        at its own infer seams from here on."""
        reply = self._send("harvest_open", {
            "players": list(players), "sids": list(sids),
        }).result(timeout=timeout)
        return reply["hid"]

    def harvest_step(self, hid: str, actions, legal, rewards, turn,
                     timeout: float = 30.0) -> int:
        """Close one step with the client-side half: per-player sampled
        actions (None for non-movers), legal-action lists, rewards, and
        the turn player.  Call AFTER every acting player's infer reply
        arrived — the reply is the capture receipt.  Returns the step
        count so far."""
        reply = self._send("harvest_step", {
            "hid": hid, "actions": list(actions), "legal": list(legal),
            "rewards": list(rewards), "turn": turn,
        }).result(timeout=timeout)
        return reply["steps"]

    def harvest_close(self, hid: str, outcome, timeout: float = 60.0) -> bool:
        """Finalize the episode with per-player outcomes (None = the game
        was abandoned: the server counts a truncated drop).  Returns
        whether the episode was kept."""
        reply = self._send("harvest_close", {
            "hid": hid,
            "outcome": None if outcome is None else list(outcome),
        }).result(timeout=timeout)
        return reply["kept"]

    def harvest_pull(self, max_episodes: int = 64,
                     timeout: float = 60.0) -> Tuple[list, Dict[str, Any]]:
        """Drain up to ``max_episodes`` completed harvest episodes
        (ownership transfers) plus the server's harvest counters — the
        learner ingest loop's poll."""
        reply = self._send("harvest_pull", {
            "max": int(max_episodes),
        }).result(timeout=timeout)
        return reply.get("episodes") or [], reply.get("counts") or {}

    def report_outcome(self, model: int, outcome: float,
                       timeout: float = 30.0) -> None:
        """Book one finished game's outcome ([-1, 1]) against the epoch
        that served it — the promotion gate / quality sentinel's feed.
        Pin the game to one epoch (the first reply's served id) so the
        attribution is honest."""
        self._send("report_outcome", {
            "model": int(model), "outcome": float(outcome),
        }).result(timeout=timeout)

    def pending_count(self) -> int:
        """Requests in flight on this connection — the migration drain
        barrier (a retire exports only once this reaches zero)."""
        with self._lock:
            return len(self._pending)

    def wire_bytes(self) -> Tuple[int, int]:
        """(sent, received) frame bytes on this connection so far."""
        return self.conn.bytes_sent, self.conn.bytes_received

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self.conn.close()
        self._fail_all(ConnectionResetError("client closed"))
