"""Serving-plane client: pipelined request/reply over one framed socket.

One connection, many outstanding requests: every frame carries a ``rid``
and a single receiver thread resolves the matching future, so a caller
can keep a submit window open (the load generator the bench uses) or use
the blocking ``infer`` facade.  Server-side sheds and deadline misses
surface as ``ServingError`` with the wire ``kind`` — fast-fail reaches
the caller as an exception, never as a hang.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from ..runtime.connection import connect_socket_connection
from ..utils import tree_map

__all__ = ["ServingClient", "ServingError"]


class ServingError(RuntimeError):
    """Server-reported request failure; ``kind`` is the wire tag
    (shed / deadline / stopped / bad_request / swap_failed / ...)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(f"[{kind}] {msg}")
        self.kind = kind


class ServingClient:
    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 retry_seconds: float = 0.0):
        self.conn = connect_socket_connection(
            host, int(port), timeout=timeout, retry_seconds=retry_seconds
        )
        self._lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._rid = 0
        self._closed = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="serve-client-recv"
        )
        self._recv_thread.start()

    # -- plumbing -----------------------------------------------------------

    def _recv_loop(self) -> None:
        while True:
            try:
                kind, data = self.conn.recv(timeout=None)
            except Exception:
                self._fail_all(ConnectionResetError("serving connection lost"))
                return
            if kind == "heartbeat" or kind == "__hb__":
                continue
            rid = (data or {}).get("rid") if isinstance(data, dict) else None
            with self._lock:
                fut = self._pending.pop(rid, None)
            if fut is None or fut.done():
                continue
            if kind == "error":
                fut.set_exception(
                    ServingError(data.get("kind", "error"), data.get("msg", ""))
                )
            elif kind == "stats":
                fut.set_result(data.get("stats"))
            else:  # result / swapped
                fut.set_result(data)

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    def _send(self, req: str, data: Dict[str, Any]) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed:
                fut.set_exception(ConnectionResetError("client closed"))
                return fut
            self._rid += 1
            rid = self._rid
            self._pending[rid] = fut
        try:
            self.conn.send((req, dict(data, rid=rid)))
        except Exception as exc:
            with self._lock:
                self._pending.pop(rid, None)
            if not fut.done():
                fut.set_exception(exc)
        return fut

    # -- API ----------------------------------------------------------------

    def submit(self, obs, model=-1, hidden=None,
               slo_ms: Optional[float] = None) -> Future:
        """Async inference; resolves to {"model": served_id, "out": tree}."""
        data: Dict[str, Any] = {"model": model, "obs": obs}
        if hidden is not None:
            data["hidden"] = hidden
        if slo_ms is not None:
            data["slo_ms"] = float(slo_ms)
        return self._send("infer", data)

    def infer(self, obs, model=-1, hidden=None, slo_ms: Optional[float] = None,
              timeout: float = 60.0) -> Dict[str, Any]:
        return self.submit(obs, model, hidden, slo_ms).result(timeout=timeout)

    def stats(self, timeout: float = 30.0) -> Dict[str, Any]:
        return self._send("stats", {}).result(timeout=timeout)

    def swap(self, model_id: int, params=None, timeout: float = 300.0) -> Dict[str, Any]:
        """Hot-swap the served latest to ``model_id`` (params inline, or
        loaded digest-verified from the server's model dir when None).
        Blocks until the standby engine is warm and the flip happened."""
        data: Dict[str, Any] = {"id": int(model_id)}
        if params is not None:
            # the wire codec speaks numpy pytrees; a device-resident params
            # tree (fresh from a train step) converts here, once
            data["params"] = tree_map(np.asarray, params)
        return self._send("swap", data).result(timeout=timeout)

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self.conn.close()
        self._fail_all(ConnectionResetError("client closed"))
