"""Serving-plane network front: framed-socket request/reply over the
actor-plane transport.

Reuses the fault-tolerant pieces of ``runtime/connection.py`` unchanged:
length-prefixed codec frames, per-peer bounded send queues with sender
threads (one stalled client can never wedge replies to the rest), and
optional silent-peer reaping.  On top of that, one dispatch thread pulls
request frames off the hub and hands them to the router — inference
itself is asynchronous (the reply is sent from a future callback on the
owning engine's dispatcher thread), so a slow batch never blocks frame
intake, which is what lets thousands of connections share one server.

Wire protocol (codec frames, all request/reply pairs carry ``rid``):

    -> ("infer", {"rid", "model", "obs", "hidden"?, "slo_ms"?, "sid"?})
    <- ("result", {"rid", "model": served_id, "out": numpy tree, "sid"?})
    <- ("error",  {"rid", "kind": shed|deadline|stopped|bad_request|..., "msg"})
    -> ("stats", {"rid"})               <- ("stats", {"rid", "stats": {...}})
    -> ("swap",  {"rid", "id", "params"?})  <- ("swapped", {"rid", "id", "warm_ms"})
    -> ("open_session",  {"rid", "model"?})  <- ("session", {"rid", "sid"})
    -> ("close_session", {"rid", "sid"})     <- ("session_closed", {"rid", "sid", "existed"})
    -> ("export_sessions", {"rid"})     <- ("sessions_export", {"rid", "sessions", "fresh", "count"})
    -> ("import_sessions", {"rid", "sessions", "fresh"?})
                                        <- ("sessions_imported", {"rid", "count"})
    -> ("harvest_open",  {"rid", "players", "sids"})
                                        <- ("harvest_opened", {"rid", "hid"})
    -> ("harvest_step",  {"rid", "hid", "actions", "legal", "rewards", "turn"})
                                        <- ("harvest_stepped", {"rid", "hid", "steps"})
    -> ("harvest_close", {"rid", "hid", "outcome"})
                                        <- ("harvest_closed", {"rid", "hid", "kept"})
    -> ("harvest_pull",  {"rid", "max"})
                                        <- ("harvest", {"rid", "episodes", "counts"})
    -> ("report_outcome", {"rid", "model", "outcome"})
                                        <- ("outcome_recorded", {"rid"})
    -> ("heartbeat", None)              (liveness only, never replied)
    <- ("draining", {"deadline_s"})     (rid-less notice, pushed to every peer)

``export_sessions``/``import_sessions`` are the migration frames
(docs/serving.md §Elastic fleet): a planned retire drains the source
replica, pulls its whole session cache (both tiers, realized to numpy —
codec-safe), and lands it in the successor's spill ring, where the next
infer restores it bit-identically through the ``session_restored`` path.
A SIGTERM'd replica pushes the ``draining`` notice so the fleet router
runs that same handoff inside ``drain_deadline_seconds`` before the
process exits 75 (EX_TEMPFAIL — the training plane's preemption code).

An ``infer`` carrying a ``sid`` reads/writes the session's recurrent
hidden state server-side (fleet/sessions.py) — the wire carries neither
direction of it, and the reply's ``out`` has its ``hidden`` stripped.

``swap`` with no params loads ``{id}.ckpt`` digest-verified from the
checkpoint manifest; the warm-then-flip sequence lives in the router.
A ``watch_interval`` > 0 arms a manifest watcher that hot-swaps
automatically when training publishes a newer verified snapshot.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import init_variables
from ..runtime.checkpoint import latest_verified_epoch, load_verified_params
from ..runtime.connection import (
    FramedConnection,
    QueueCommunicator,
    open_socket_connection,
    accept_socket_connections,
)
from ..fleet.sessions import SessionCache
from ..runtime.inference_engine import EngineStopped
from ..utils.metrics import append_metrics_record
from ..utils.trace import trace_event
from .router import ColdRoute, ModelRouter

__all__ = ["ServingServer", "serve_main"]


class ServingServer(QueueCommunicator):
    """Continuous-batching inference server over the framed transport."""

    def __init__(
        self,
        router: ModelRouter,
        serving_cfg: Dict[str, Any],
        metrics_path: Optional[str] = None,
        flywheel=None,
    ):
        cfg = dict(serving_cfg or {})
        recv_timeout = float(cfg.get("recv_timeout", 0.0)) or None
        # reply bursts ARE the product here: a pipelining client draining a
        # whole batch's replies momentarily outruns its socket, and the
        # hub's default 64-deep send queue would reap it as wedged.  Size
        # the fault boundary to the engine queue bound instead — a peer
        # that stops reading for THAT long really is gone
        super().__init__(
            recv_timeout=recv_timeout,
            send_queue_size=max(256, int(cfg.get("queue_bound", 1024))),
        )
        self.router = router
        # data flywheel (flywheel/__init__.py): harvest capture at the
        # infer/reply seams, harvest_* wire frames, and the promotion
        # gate replacing the bare manifest refresh in the watch loop.
        # None = every flywheel seam compiles out to the old behavior.
        self.flywheel = flywheel
        self.port = int(cfg.get("port", 9997))
        self.bound_port: Optional[int] = None
        self.watch_interval = float(cfg.get("watch_interval", 0.0))
        if flywheel is not None and self.watch_interval <= 0:
            # the gate/sentinel live in the watch loop — a flywheel server
            # without a watcher would stage candidates never and judge
            # nothing, so default the beat on rather than silently stall
            self.watch_interval = 1.0
        self.stats_interval = float(cfg.get("stats_interval", 30.0))
        self._default_slo_s = float(cfg.get("slo_ms", 200.0)) / 1000.0
        self._sheds = cfg.get("shed_policy", "deadline") != "none"
        self._metrics_path = metrics_path
        self._sock = None
        self._threads: List[threading.Thread] = []
        # cold resolves (disk load + warm compiles, or waiting on another
        # loader) run here: bounded workers, so a burst of requests for a
        # non-resident model queues instead of spawning a thread apiece
        from concurrent.futures import ThreadPoolExecutor

        self._cold_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="serve-cold"
        )
        # server-resident recurrent sessions (docs/serving.md §Fleet tier):
        # open_session/infer(sid)/close_session pin hidden state here so
        # the wire carries only observations.  session_capacity: 0 turns
        # the tier off — ship-state-both-ways stays the stateless fallback
        # either way.  The cache adopts the serving engine's device on
        # first use (engine placement is the router's call)
        session_capacity = int(cfg.get("session_capacity", 1024))
        self.sessions: Optional[SessionCache] = (
            SessionCache(session_capacity, int(cfg.get("session_spill", 4096)))
            if session_capacity > 0
            else None
        )
        self._stats_lock = threading.Lock()
        self.requests_in = 0
        self.replies = 0
        self.errors: Dict[str, int] = {}
        self._stats_t0 = time.monotonic()
        self._stats_served0 = 0
        # preemption drain plumbing: set by begin_drain (SIGTERM path),
        # released by the router pulling the session cache via
        # export_sessions — or by the deadline, whichever comes first
        self._sessions_exported = threading.Event()
        # HANDYRL_FAULT_SIGTERM_REPLICA="N": self-SIGTERM after N served
        # replies (runtime/faults.py — parsed here so a spawned replica
        # inherits the injection through its environment)
        from ..runtime import faults

        self._fault_sigterm_after = faults.sigterm_replica()

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> "ServingServer":
        # bind AND listen synchronously: port 0 (tests/bench) resolves
        # before return, and a client connecting the instant run() returns
        # must never see a refused connect because the accept thread
        # hasn't reached its own listen() yet
        self._sock = open_socket_connection(self.port)
        self._sock.listen(1024)
        self.bound_port = self._sock.getsockname()[1]
        targets = [self._accept_loop, self._dispatch]
        if self.watch_interval > 0:
            targets.append(self._watch_loop)
        if self._metrics_path and self.stats_interval > 0:
            targets.append(self._metrics_loop)
        for target in targets:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        super().shutdown()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._cold_pool.shutdown(wait=False)
        self.router.stop()

    def _accept_loop(self) -> None:
        for conn in accept_socket_connections(timeout=0.5, sock=self._sock):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)

    # -- request dispatch ----------------------------------------------------

    def _dispatch(self) -> None:
        while not self.shutdown_flag:
            try:
                conn, frame = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            try:
                req, data = frame
            except (TypeError, ValueError):
                continue  # malformed frame; the codec already vetted types
            if req == "heartbeat" or req == "__hb__":
                continue
            if not isinstance(data, dict):
                data = {}
            rid = data.get("rid")
            try:
                if req == "infer":
                    self._handle_infer(conn, data)
                elif req == "stats":
                    # stats_record copies + sorts every engine's latency
                    # reservoir — O(n log n) a polling dashboard must not
                    # inject into frame intake; the cold pool is idle
                    # whenever no snapshot is loading
                    self._cold_pool.submit(self._handle_stats, conn, rid)
                elif req == "swap":
                    # warm-up compiles take seconds: never on this thread —
                    # and through the BOUNDED pool, so a client looping swap
                    # frames queues instead of spawning a warming thread
                    # (and a racing publish) apiece
                    self._cold_pool.submit(self._handle_swap, conn, data)
                elif req == "open_session":
                    self._handle_open_session(conn, rid)
                elif req == "close_session":
                    self._handle_close_session(conn, rid, data.get("sid"))
                elif req == "export_sessions":
                    # realizes every resident hidden to host numpy — a
                    # device sync by design, so off the dispatch thread
                    self._cold_pool.submit(self._handle_export_sessions,
                                           conn, rid)
                elif req == "import_sessions":
                    self._cold_pool.submit(self._handle_import_sessions,
                                           conn, rid, data)
                elif req in ("harvest_open", "harvest_step",
                             "harvest_close", "harvest_pull",
                             "report_outcome"):
                    if self.flywheel is None:
                        self._error(conn, rid, "bad_request",
                                    "flywheel disabled (flywheel.enabled: false)")
                    elif req in ("harvest_close", "harvest_pull"):
                        # close finalizes + zlib-compresses a whole
                        # trajectory; pull serializes a batch of blobs —
                        # both off the dispatch thread
                        self._cold_pool.submit(self._handle_harvest,
                                               conn, rid, req, data)
                    else:
                        self._handle_harvest(conn, rid, req, data)
                else:
                    self._error(conn, rid, "bad_request",
                                f"unknown request {req!r}")
            except Exception as exc:
                # this is THE dispatch thread: no frame — however malformed
                # or unlucky — may kill it, or every client hangs forever
                # while the accept loop keeps admitting new ones
                self._error(conn, rid, "error",
                            f"{type(exc).__name__}: {exc}")

    def _handle_infer(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        with self._stats_lock:
            self.requests_in += 1
        # the SLO clock starts at frame arrival: a cold-routed request that
        # waits behind a snapshot load must not have its budget re-based
        # when the pool task finally runs it.  Assigned UNCONDITIONALLY —
        # a wire-supplied "_arrival" would let a client mint itself an
        # unshedable (or instantly-expired) deadline
        data["_arrival"] = time.monotonic()
        try:
            # hot path: resident routes resolve + submit inline.  ColdRoute
            # (disk load + warm compiles ahead) re-dispatches to the bounded
            # cold pool — the resolve call ITSELF makes the decision, so no
            # check-then-resolve race can sneak cold work onto this thread
            self._do_infer(conn, data, allow_cold=False)
        except ColdRoute:
            self._cold_pool.submit(self._infer_cold, conn, data)

    def _handle_stats(self, conn: FramedConnection, rid) -> None:
        try:
            self.send(conn, ("stats", {"rid": rid, "stats": self.stats_record()}))
        except Exception as exc:  # a pool task must never die silently
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _infer_cold(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        try:
            self._do_infer(conn, data)
        except Exception as exc:  # a pool task must never die silently
            self._error(conn, data.get("rid"), "error",
                        f"{type(exc).__name__}: {exc}")

    def _handle_open_session(self, conn: FramedConnection, rid) -> None:
        if self.sessions is None:
            self._error(conn, rid, "bad_request",
                        "session cache disabled (serving.session_capacity: 0)")
            return
        self.send(conn, ("session", {"rid": rid, "sid": self.sessions.open()}))

    def _handle_close_session(self, conn: FramedConnection, rid, sid) -> None:
        if self.sessions is None or not isinstance(sid, str):
            self._error(conn, rid, "bad_request", f"bad session id {sid!r}")
            return
        existed = self.sessions.close(sid)
        self.send(conn, ("session_closed",
                         {"rid": rid, "sid": sid, "existed": existed}))

    def _handle_export_sessions(self, conn: FramedConnection, rid) -> None:
        """Migration source side: hand the whole session cache (both
        tiers + fresh sids) to the caller and clear it — ownership
        transfer.  A session-less server exports empty rather than
        erroring: retiring a stateless replica is still a legal retire."""
        try:
            if self.sessions is None:
                exported: Dict[str, Any] = {"sessions": {}, "fresh": []}
            else:
                exported = self.sessions.export_all()
            self.send(conn, ("sessions_export", {
                "rid": rid,
                "sessions": exported["sessions"],
                "fresh": exported["fresh"],
                "count": len(exported["sessions"]),
            }))
            # signalled only AFTER the reply frame is on the wire: a
            # draining serve_main shuts the socket down the moment this
            # event fires, and the export must not be cut mid-flight
            self._sessions_exported.set()
        except Exception as exc:  # a pool task must never die silently
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_import_sessions(self, conn: FramedConnection, rid,
                                data: Dict[str, Any]) -> None:
        """Migration successor side: adopt the retiring replica's
        sessions into the spill tier (restored bit-identically on their
        next infer through the counted ``session_restored`` path)."""
        try:
            if self.sessions is None:
                self._error(conn, rid, "bad_request",
                            "session cache disabled "
                            "(serving.session_capacity: 0)")
                return
            n = self.sessions.adopt(
                data.get("sessions") or {}, data.get("fresh") or ()
            )
            self.send(conn, ("sessions_imported", {"rid": rid, "count": n}))
        except Exception as exc:  # a pool task must never die silently
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def _handle_harvest(self, conn: FramedConnection, rid, req: str,
                        data: Dict[str, Any]) -> None:
        """Data-flywheel wire frames (docs/serving.md §Data flywheel).
        The client reports the half of each step only it knows (sampled
        action, legal set, rewards, turn, final outcome); the recorder
        already captured the server half at the infer/reply seams."""
        from ..flywheel import HarvestError

        recorder = self.flywheel.recorder
        try:
            if req == "harvest_open":
                hid = recorder.open_episode(
                    data.get("players") or (), data.get("sids") or ()
                )
                self.send(conn, ("harvest_opened", {"rid": rid, "hid": hid}))
            elif req == "harvest_step":
                steps = recorder.step(
                    data.get("hid"), data.get("actions") or (),
                    data.get("legal") or (), data.get("rewards") or (),
                    data.get("turn"),
                )
                self.send(conn, ("harvest_stepped",
                                 {"rid": rid, "hid": data.get("hid"),
                                  "steps": steps}))
            elif req == "harvest_close":
                episode = recorder.close(data.get("hid"), data.get("outcome"))
                self.send(conn, ("harvest_closed",
                                 {"rid": rid, "hid": data.get("hid"),
                                  "kept": episode is not None}))
            elif req == "harvest_pull":
                episodes, counts = recorder.pull(int(data.get("max", 64)))
                self.send(conn, ("harvest", {"rid": rid, "episodes": episodes,
                                             "counts": counts}))
            else:  # report_outcome
                self.flywheel.quality.record_outcome(
                    data.get("model"), data.get("outcome")
                )
                self.send(conn, ("outcome_recorded", {"rid": rid}))
        except (HarvestError, ValueError) as exc:
            self._error(conn, rid, "bad_request", str(exc))
        except Exception as exc:  # a pool task must never die silently
            self._error(conn, rid, "error", f"{type(exc).__name__}: {exc}")

    def begin_drain(self, deadline_s: float = 60.0) -> bool:
        """Preemption handoff (SIGTERM path, docs/fault_tolerance.md):
        push a rid-less ``draining`` notice to every peer, then wait for
        a router to pull the session cache via ``export_sessions`` — or
        for the deadline.  Returns True if the handoff happened.  A
        server with no peers or no sessions returns immediately: there
        is nothing to hand off, and the drain must never outwait its
        own deadline doing nothing."""
        for conn in self.connections():
            self.send(conn, ("draining", {"deadline_s": float(deadline_s)}))
        if self.sessions is None or self.connection_count() == 0:
            return False
        stats = self.sessions.stats()
        if stats["session_resident"] + stats["session_spilled"] == 0:
            return False
        deadline = time.monotonic() + max(0.0, float(deadline_s))
        while time.monotonic() < deadline:
            if self._sessions_exported.wait(timeout=0.1):
                return True
        return self._sessions_exported.is_set()

    def _do_infer(self, conn: FramedConnection, data: Dict[str, Any],
                  allow_cold: bool = True) -> None:
        rid = data.get("rid")
        model_id = data.get("model", -1)
        if self.flywheel is not None:
            # shadow slice: a latest-addressed request may be rewritten to
            # the staged candidate (explicit/pinned ids pass untouched —
            # the reply's served id tells the client which epoch answered,
            # and harvest clients pin their whole game to that id)
            model_id = self.flywheel.shadow_model(model_id)
        # the deadline is based at frame ARRIVAL for the default budget
        # too, not just explicit slo_ms — otherwise a cold-routed request's
        # wait behind a snapshot load would never count against it (the
        # engine would stamp a fresh budget at submit time)
        arrival = data.get("_arrival", time.monotonic())
        deadline = arrival + self._default_slo_s if self._sheds else None
        slo_ms = data.get("slo_ms")
        if slo_ms is not None:
            try:
                deadline = arrival + float(slo_ms) / 1000.0
            except (TypeError, ValueError):
                self._error(conn, rid, "bad_request",
                            f"slo_ms={slo_ms!r} is not a number")
                return
        sid = data.get("sid")
        hidden = data.get("hidden")
        if sid is not None and self.sessions is None:
            self._error(conn, rid, "bad_request",
                        "session cache disabled (serving.session_capacity: 0)")
            return
        if sid is not None and hidden is None:
            # session path: the hidden state lives HERE, next to the model
            # (an explicit wire hidden still wins — the stateless override).
            # A miss (spill overflow, or a session re-routed off a dead
            # replica) falls back to the model's initial state and is
            # counted — the client keeps playing, degraded loudly in stats
            hidden, _status = self.sessions.lookup(sid)
        if self.flywheel is not None and sid is not None:
            # harvest capture, request half: the observation for this
            # session's player (no-op unless the sid is bound to an open
            # harvest episode)
            self.flywheel.capture_request(sid, data.get("obs"))
        for attempt in (0, 1):
            try:
                served, route = self.router.resolve(model_id, allow_cold=allow_cold)
            except ColdRoute:
                raise
            except Exception as exc:
                self._error(conn, rid, getattr(exc, "kind", "bad_request"), str(exc))
                return
            fut = route.submit(data.get("obs"), hidden, deadline)
            if (
                attempt == 0
                and fut.done()
                and isinstance(fut.exception(), EngineStopped)
            ):
                # raced an eviction's drain between resolve and submit:
                # re-resolve once — the request must not be dropped by a
                # retirement it never chose
                continue
            break
        if sid is not None and self.sessions.device is None:
            # adopt the engine's device once so resident state stacks into
            # future batches without a per-request host upload
            self.sessions.device = getattr(route, "device", None)
        fut.add_done_callback(
            lambda f, c=conn, r=rid, s=served, a=arrival, i=sid:
                self._reply(c, r, s, f, a, i)
        )

    def _reply(self, conn: FramedConnection, rid, served, fut,
               arrival: Optional[float] = None, sid=None) -> None:
        exc = fut.exception()
        if arrival is not None:
            # the request lifecycle as one span: frame arrival (admission)
            # -> queue -> batch dispatch -> this reply callback.  The
            # nested "serve.batch" span (batcher.py) shows how much of it
            # was device work vs queueing
            trace_event(
                "serve.request", time.monotonic() - arrival, t0=arrival,
                plane="serving", ok=exc is None,
            )
        if exc is None:
            with self._stats_lock:
                self.replies += 1
                replies = self.replies
            if self._fault_sigterm_after is not None \
                    and replies == self._fault_sigterm_after:
                # fault injection: a spot-instance preemption lands mid-
                # storm — SIGTERM to our own process; serve_main's handler
                # drives the draining broadcast -> session handoff -> 75
                import os
                import signal

                print(f"serving: FAULT sigterm_replica after {replies} "
                      "replies — raising SIGTERM")
                os.kill(os.getpid(), signal.SIGTERM)
            out = fut.result()
            if self.flywheel is not None and sid is not None:
                # harvest capture, reply half: the policy/value this epoch
                # produced — BEFORE the reply frame leaves, so a client
                # that waits for its reply can close the step knowing the
                # capture is already on the books
                self.flywheel.capture_reply(sid, served, out)
            if sid is not None and isinstance(out, dict) and "hidden" in out:
                # the session's whole point: the next-step state stays
                # here (store() re-pins it device-side) and the reply
                # frame sheds its largest tensor.  out is this request's
                # own scatter slice, so popping mutates nothing shared
                self.sessions.store(sid, out.pop("hidden"))
            reply = {"rid": rid, "model": served, "out": out}
            if sid is not None:
                reply["sid"] = sid
            self.send(conn, ("result", reply))
        else:
            kind = getattr(exc, "kind", None) or (
                "stopped" if isinstance(exc, EngineStopped) else "error"
            )
            self._error(conn, rid, kind, str(exc))

    def _error(self, conn: FramedConnection, rid, kind: str, msg: str) -> None:
        with self._stats_lock:
            self.errors[kind] = self.errors.get(kind, 0) + 1
        self.send(conn, ("error", {"rid": rid, "kind": kind, "msg": msg}))

    def _handle_swap(self, conn: FramedConnection, data: Dict[str, Any]) -> None:
        rid = (data or {}).get("rid")
        try:
            sid = int(data["id"])
            params = data.get("params")
            if params is None:
                params = load_verified_params(
                    self.router.model_dir, sid, self.router._params_template()
                )
            warm_ms = self.router.publish(sid, params)
            self.send(conn, ("swapped", {"rid": rid, "id": sid, "warm_ms": warm_ms}))
        except Exception as exc:
            self._error(conn, rid, "swap_failed", f"{type(exc).__name__}: {exc}")

    # -- checkpoint watcher --------------------------------------------------

    def _watch_loop(self) -> None:
        while not self.shutdown_flag:
            time.sleep(self.watch_interval)
            if self.shutdown_flag:
                return
            try:
                if self.flywheel is not None:
                    # the flywheel beat subsumes the bare refresh: with
                    # gating off it IS maybe_refresh, with gating on it
                    # stages/judges candidates and runs the sentinel
                    event = self.flywheel.tick()
                    if event is not None:
                        print(f"serving: flywheel: {event}")
                    continue
                published = self.router.maybe_refresh()
                if published is not None:
                    print(f"serving: hot-swapped to verified snapshot {published}")
            except Exception as exc:
                # a corrupt manifest mid-write etc. must not kill the watcher
                print(f"serving: refresh failed: {type(exc).__name__}: {exc}")

    # -- stats / metrics -----------------------------------------------------

    def stats_record(self, advance_window: bool = False) -> Dict[str, Any]:
        """One metrics.jsonl-shaped record of the serving plane's health.
        Every key here is registered in utils.metrics.METRIC_KEYS (MET006).
        qps is over the window since it was last ADVANCED — only the
        periodic metrics loop advances it, so a dashboard polling wire
        stats cannot shrink (and thereby noise up) the recorded windows."""
        rstats = self.router.stats()
        now = time.monotonic()
        with self._stats_lock:
            requests_in = self.requests_in
            # self.replies is the wire truth: it counts every successful
            # reply including instant (model 0) and ensemble routes, which
            # no single engine's requests_served sees
            replies = self.replies
            errors = dict(self.errors)
            dt = max(now - self._stats_t0, 1e-6)
            served_delta = replies - self._stats_served0
            if advance_window:
                self._stats_t0 = now
                self._stats_served0 = replies
        record: Dict[str, Any] = {
            "serve_requests": requests_in,
            "serve_replies": replies,
            "serve_shed": rstats["requests_shed"],
            "serve_deadline_miss": rstats["deadline_misses"],
            "serve_batches": rstats["batches_served"],
            "serve_depth": rstats["depth"],
            "serve_qps": round(served_delta / dt, 2),
            "serve_p50_ms": rstats["p50_ms"],
            "serve_p99_ms": rstats["p99_ms"],
            "serve_hot_swaps": rstats["hot_swaps"],
            "serve_models": rstats["models"],
            "serve_snapshot_substituted": rstats["substituted"],
            "serve_connections": self.connection_count(),
            "serve_errors": sum(errors.values()),
        }
        if self.sessions is not None:
            record.update(self.sessions.stats())
        if self.flywheel is not None:
            # flywheel_* harvest counters + quality_* gate/sentinel books
            # (quality_wp{epoch} rides the registered prefix family)
            record.update(self.flywheel.stats_record())
        if getattr(self.router, "weight_dtype", "float32") != "float32":
            # low-precision rung: dtype pin + the publish-time MEASURED
            # calibration record (None until a calibration_source is wired
            # and a publish has run) — keys registered in METRIC_KEYS
            record["lowprec_weight_dtype"] = self.router.weight_dtype
            calib = getattr(self.router, "last_calibration", None)
            if calib:
                record["lowprec_calib_batches"] = calib["calib_batches"]
                record["lowprec_calib_max_dev"] = calib["calib_max_dev"]
                record["lowprec_calib_mean_dev"] = calib["calib_mean_dev"]
        return record

    def _metrics_loop(self) -> None:
        while not self.shutdown_flag:
            time.sleep(self.stats_interval)
            if self.shutdown_flag:
                return
            try:
                self._write_metrics(self.stats_record(advance_window=True))
            except Exception as exc:
                print(f"serving: metrics write failed: {type(exc).__name__}: {exc}")

    def _write_metrics(self, record: Dict[str, Any]) -> None:
        """Learner._write_metrics discipline: one flushed+fsynced append
        per record (timestamp seam included), so readers tolerate at most
        a truncated tail line — shared with the fleet router's records."""
        append_metrics_record(self._metrics_path, record)


def serve_main(args: Dict[str, Any]) -> None:
    """`main.py --serve`: standalone serving plane for the configured env.

    Publishes the newest manifest-verified snapshot (fresh-init params
    when the model dir is empty — a cold dev server still answers), then
    serves until interrupted.  With ``serving.watch_interval`` > 0 the
    server follows the training run's checkpoints: every new verified
    snapshot hot-swaps in with zero dropped requests.
    """
    from ..envs import make_env, prepare_env
    from ..utils import trace

    train = args["train_args"]
    env_args = args["env_args"]
    if trace.configure(train.get("trace")):
        print(f"serving: trace spans -> {trace.current_path()}")
    prepare_env(env_args)
    env = make_env(env_args)
    module = env.net()
    env.reset()
    template_obs = env.observation(env.players()[0])
    model_dir = train.get("model_dir", "models")

    router = ModelRouter(
        module, template_obs, train.get("serving", {}), model_dir=model_dir
    )
    newest = 0
    try:
        newest = latest_verified_epoch(model_dir)
    except Exception as exc:
        print(f"serving: checkpoint scan failed ({exc}); starting fresh")
    if newest > 0:
        template = init_variables(module, env)["params"]
        params = load_verified_params(model_dir, newest, template, pre_verified=True)
        router.publish(newest, params)
    else:
        # cold dev server: fresh-init weights under id 0 — the untrained/
        # random id, which also keeps the manifest watcher's newer-than-
        # current check able to pick up training's very first epoch
        router.publish(0, init_variables(module, env)["params"])

    flywheel = None
    fly_cfg = train.get("flywheel", {}) or {}
    if fly_cfg.get("enabled"):
        from ..flywheel import FlywheelPlane

        obs_spec_fn = None
        if train.get("obs_int8"):
            # harvested episodes must quantize under the SAME env spec the
            # self-play Generator uses, or ring ingest would mix scales
            from ..models.quantize import obs_quant_spec

            obs_spec_fn = lambda obs: obs_quant_spec(env, obs=obs)
        gen_args = {
            "gamma": train.get("gamma", 0.8),
            "compress_steps": train.get("compress_steps", 8),
            "observation": train.get("observation", True),
            "obs_int8": bool(train.get("obs_int8", False)),
        }
        flywheel = FlywheelPlane(
            router, model_dir, fly_cfg, gen_args, obs_spec_fn=obs_spec_fn
        )
        print(f"serving: data flywheel on (gate_promotions="
              f"{bool(fly_cfg.get('gate_promotions', True))})")

    server = ServingServer(
        router, train.get("serving", {}),
        metrics_path=train.get("metrics_path"), flywheel=flywheel,
    ).run()
    print(f"serving: listening on port {server.bound_port} "
          f"(model {router.latest_id()}, dir {model_dir!r})", flush=True)

    # preemption-aware replica (docs/fault_tolerance.md): SIGTERM — the
    # spot-instance eviction signal — triggers a bounded drain: broadcast
    # the draining notice, wait for a fleet router to pull the session
    # cache (export_sessions) inside drain_deadline_seconds, then exit 75
    # (EX_TEMPFAIL) so a launcher replaces the replica.  SIGINT (an
    # operator's Ctrl-C) keeps the immediate shutdown.
    import signal
    import sys as _sys

    preempted = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: preempted.set())
    except ValueError:
        pass  # not the main thread (embedded use): no preemption handler
    try:
        while not preempted.wait(timeout=1.0):
            pass
        deadline_s = float(train.get("drain_deadline_seconds", 60.0))
        print(f"serving: SIGTERM — draining sessions "
              f"(deadline {deadline_s:.0f}s)", flush=True)
        handed_off = server.begin_drain(deadline_s)
        if handed_off:
            # the export reply frame is written but the router still has
            # to READ it — closing with unread inbound frames queued (a
            # racing stats poll) would RST the socket and cut it off
            time.sleep(0.25)
        print(f"serving: drain complete (sessions handed off: {handed_off}); "
              "exiting 75 for relaunch", flush=True)
        server.shutdown()
        _sys.exit(75)
    except KeyboardInterrupt:
        print("serving: shutting down")
        server.shutdown()
