"""Multi-host (multi-process) initialization + epoch cadence for the
gradient plane.

The reference scales out with its pickle/TCP worker tree only — its learner
is single-host (``nn.DataParallel``, reference train.py:340-341).  Here the
learner itself can span hosts: ``jax.distributed.initialize`` connects the
processes, ``jax.devices()`` then returns the GLOBAL device list, and the
same ``make_mesh``/``NamedSharding`` train step runs SPMD across hosts with
XLA routing collectives over ICI within a slice and DCN across slices
(SURVEY.md §2.5 gradient-plane prescription).

Config (``train_args.distributed``)::

    distributed:
      coordinator_address: "10.0.0.1:1234"   # host:port of process 0
      num_processes: 4
      process_id: 0                          # or set via PROCESS_ID env
      initialization_timeout: 300.0          # loud failure, never a hang
      heartbeat_interval: 5.0                # cross-host health plane
      heartbeat_timeout: 30.0                # (parallel/health.py)
      collective_timeout: 300.0
      health_port: 0                         # 0 = coordinator port + 1

Division of labor when initialized:

* every process executes the jitted train step (SPMD requires all
  processes to join every collective), feeding its local batch shard via
  ``jax.make_array_from_process_local_data``;
* only process 0 (``is_coordinator()``) writes checkpoints/metrics and
  serves models to the actor plane — the guards live in
  ``runtime/learner.py``;
* the EPOCH CADENCE is coordinator-driven (``DistributedCadence``): every
  process must run the exact same sequence of collectives, so "is this
  epoch over" / "does the run stop" / "are we draining" are themselves
  tiny broadcast collectives from process 0, never local decisions.
"""

from __future__ import annotations

import os
import socket
import sys
import time
from typing import Any, Dict, Optional

import jax
import numpy as np


def _enable_cpu_collectives() -> None:
    """CPU-platform runs need a cross-process collectives backend: without
    it XLA:CPU rejects every multi-process computation outright
    ("Multiprocess computations aren't implemented on the CPU backend").
    Select gloo when the platform is pinned to CPU — it must happen BEFORE
    the backend initializes, which is why it lives here, at the one
    chokepoint every multi-process entry path already goes through.  Best
    effort: jax versions where gloo is absent (or already the default)
    simply proceed."""
    platforms = (
        os.environ.get("JAX_PLATFORMS", "") or getattr(jax.config, "jax_platforms", "") or ""
    )
    if "cpu" not in str(platforms).lower().split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def _timeout_error(process_id: int, num_processes: int, address: str,
                   timeout: float, last_exc: Optional[BaseException]) -> RuntimeError:
    return RuntimeError(
        f"jax.distributed.initialize could not connect process "
        f"{process_id}/{num_processes} to the coordinator at {address} "
        f"within initialization_timeout={timeout:.0f}s "
        f"(last error: {type(last_exc).__name__}: {last_exc}). "
        "Check that distributed.coordinator_address names a reachable "
        "host:port, that process 0 is up, and that every process agrees "
        "on num_processes."
    )


def _await_coordinator(address: str, deadline: float, process_id: int,
                       num_processes: int, timeout: float) -> None:
    """TCP pre-flight for non-coordinator ranks: wait (backoff-retry,
    bounded by the same deadline) until the coordinator port ACCEPTS a
    connection before handing off to ``jax.distributed.initialize``.

    This probe is what makes the dead-coordinator case a catchable loud
    error at all: on this jax, a follower whose RegisterTask RPC times
    out doesn't raise — the C++ coordination client LOG(FATAL)s and
    SIGABRTs the process, so a Python-side retry around ``initialize``
    never regains control.  The not-yet-up race (process 0 boots a beat
    later than the fleet) is absorbed by the same loop."""
    from .health import _split_address

    host, port = _split_address(address)
    backoff = 0.25
    last_exc: Optional[BaseException] = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise _timeout_error(
                process_id, num_processes, address, timeout, last_exc
            ) from last_exc
        try:
            sock = socket.create_connection(
                (host, port), timeout=min(remaining, 5.0)
            )
            sock.close()
            return
        except OSError as exc:
            last_exc = exc
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
            backoff = min(backoff * 2.0, 5.0)


def _reset_half_initialized_state() -> None:
    """Make a retry of ``jax.distributed.initialize`` REAL: jax assigns
    ``global_state.client`` (and the rank-0 service) *before*
    ``client.connect()``, so a failed connect leaves initialize poisoned —
    every later call raises ``'distributed.initialize should only be
    called once'`` instantly, the retry loop absorbs nothing, and that
    misleading message would be reported as the final cause.  shutdown()
    resets exactly those fields; if the never-connected client refuses a
    clean shutdown, clear them by hand."""
    try:
        jax.distributed.shutdown()
    except Exception:
        try:
            from jax._src.distributed import global_state

            global_state.client = None
            global_state.service = None
        except Exception:
            pass


def init_distributed(dist_args: Optional[Dict[str, Any]]) -> int:
    """Initialize ``jax.distributed`` from config; returns the process index.

    A missing/empty ``coordinator_address`` means single-process — no-op,
    returns 0.  ``process_id`` may come from the config or the
    ``PROCESS_ID`` environment variable (per-host launchers usually inject
    the rank via env).

    A dead or mis-addressed coordinator must surface as a LOUD bounded
    error, never an indefinite startup hang: ``initialization_timeout``
    caps the whole attempt (passed through to ``jax.distributed
    .initialize``, which itself retries the connect internally), and a
    short backoff-retry loop absorbs the coordinator-not-yet-up race a
    fleet launcher hits when process 0 boots a beat later than the rest.
    """
    if not dist_args or not dist_args.get("coordinator_address"):
        return 0
    _enable_cpu_collectives()
    address = dist_args["coordinator_address"]
    num_processes = int(dist_args["num_processes"])
    process_id = dist_args.get("process_id")
    if process_id is None:
        process_id = int(os.environ.get("PROCESS_ID", "0"))
    process_id = int(process_id)
    timeout = float(dist_args.get("initialization_timeout") or 300.0)
    deadline = time.monotonic() + timeout
    if process_id != 0:
        # a dead coordinator inside initialize is a C++ SIGABRT, not an
        # exception — prove the port is up first, under the same budget
        _await_coordinator(address, deadline, process_id, num_processes, timeout)
    backoff = 1.0
    last_exc: Optional[BaseException] = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            jax.distributed.initialize(
                coordinator_address=address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=dist_args.get("local_device_ids"),
                initialization_timeout=max(1, int(remaining)),
            )
            return jax.process_index()
        except Exception as exc:  # grpc surfaces several concrete types
            last_exc = exc
            _reset_half_initialized_state()
            if time.monotonic() + backoff >= deadline:
                break
            time.sleep(backoff)
            backoff = min(backoff * 2.0, 15.0)
    raise _timeout_error(
        process_id, num_processes, address, timeout, last_exc
    ) from last_exc


def shutdown_distributed() -> None:
    """Synchronized ``jax.distributed.shutdown`` after a clean run.

    The coordination service runs a shutdown BARRIER: a process that
    simply exits (atexit) while its peers are still draining trips the
    service's own heartbeat timeout and every survivor gets a fatal abort
    (SIGABRT) — a clean multi-process run must therefore shut the service
    down explicitly, at a point every process reaches within seconds of
    the others (train_main does, right after Learner.run()).  Best
    effort: a failed disconnect must not turn a finished run into a
    nonzero exit."""
    if jax.process_count() <= 1:
        return
    try:
        jax.distributed.shutdown()
    except Exception as exc:
        print(
            f"[handyrl_tpu] jax.distributed.shutdown failed "
            f"({type(exc).__name__}: {exc}); continuing exit",
            file=sys.stderr,
        )


def is_coordinator() -> bool:
    """True on the process that owns checkpoints, metrics, model serving."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def local_batch_size(global_batch_size: int) -> int:
    """Per-process share of a global batch (SPMD data feeding)."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"batch_size {global_batch_size} not divisible by {n} processes"
        )
    return global_batch_size // n


def broadcast_from_coordinator(value: int) -> int:
    """Broadcast one int32 from process 0 to every process (a tiny
    collective; all processes must call).  The primitive under both the
    auto-resume epoch agreement and the epoch cadence."""
    from jax.experimental import multihost_utils

    return int(multihost_utils.broadcast_one_to_all(np.int32(value)))


def broadcast_resume_epoch(local_epoch: int) -> int:
    """Every SPMD process must resume the SAME epoch, and only the
    coordinator's manifest scan is authoritative (it owns the checkpoint
    files): process 0 passes its ``latest_verified_epoch`` verdict, the
    rest pass anything — all return the coordinator's value.  Pinned by
    tests/test_multihost.py::test_resume_epoch_broadcast_two_process."""
    if jax.process_count() <= 1:
        return int(local_epoch)
    return broadcast_from_coordinator(int(local_epoch))


def broadcast_params(tree, mesh):
    """Broadcast a param pytree from process 0 to every process (all
    processes must call; followers pass a LIKE-SHAPED tree whose values
    are discarded).  The primitive under the cross-process sentinel
    rollback: only the coordinator owns checkpoint files, so the rolled-
    back params themselves ride a collective — every rank installs the
    SAME bytes without needing the snapshot on its filesystem."""
    from jax.experimental import multihost_utils

    from .mesh import dispatch_serialized

    # the broadcast ends in a host fetch on purpose (the received params
    # are installed host-side), so it lives inside the dispatch scope
    # like the cadence broadcasts
    return dispatch_serialized(
        lambda: jax.tree.map(
            np.asarray, multihost_utils.broadcast_one_to_all(tree)
        ),
        mesh,
    )


# -- coordinator-driven epoch cadence ----------------------------------------

# agree_step() command bits, broadcast from the coordinator: CONTINUE (0)
# keeps stepping; END closes the epoch on every process after the same
# step count; DRAIN (always with END) additionally ends the RUN at this
# boundary for a preemption-safe drain, skipping the stop agreement.
CMD_CONTINUE = 0
CMD_END = 1
CMD_DRAIN = 2


class DistributedCadence:
    """Lockstep epoch cadence for the multi-process ``Learner``.

    Under ``jax.distributed`` every train step is a cross-process
    collective, so all processes must execute the SAME number of steps per
    epoch and agree on shutdown — a process deciding locally (its own
    episode counts, its own ``update_flag``) would leave the others wedged
    in a collective forever.  The coordinator's decisions are therefore
    broadcast as one tiny int32 collective per step (``agree_step``) and
    one per epoch boundary (``agree_stop``); followers pass 0 and obey.

    All calls happen on the trainer thread, in identical program order on
    every process: per epoch ``[agree_step (train_step agree_step)* ,
    agree_stop?]`` — ``agree_stop`` is skipped by every process alike when
    the epoch ended with the DRAIN bit set.  Dispatches hold the mesh's
    device locks (``dispatch_serialized``) like every other program.
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self.is_coordinator = is_coordinator()
        self.num_processes = process_count()

    def _agree(self, value: int, tag: str = "agree") -> int:
        from ..utils.trace import trace_span
        from .mesh import dispatch_serialized

        # broadcast_one_to_all returns a host value: the device_get is the
        # point of the call (the cadence decision must reach the host), so
        # it lives inside the dispatch scope like the CPU backend's other
        # blocking dispatches.  The span times the whole rendezvous: under
        # rank skew it IS the wait for the slowest process, which is the
        # cross-host stall the observability plane exists to attribute
        with trace_span("cadence." + tag, plane="cadence"):
            return dispatch_serialized(
                lambda: broadcast_from_coordinator(value), self.mesh
            )

    def agree_step(self, end: bool, drain: bool) -> int:
        """One per trainer-loop iteration: the coordinator passes its local
        epoch-end / drain verdicts, everyone receives the agreed command."""
        cmd = CMD_CONTINUE
        if self.is_coordinator and (end or drain):
            cmd = CMD_END | (CMD_DRAIN if drain else 0)
        return self._agree(cmd, "agree_step")

    def agree_stop(self, stop: bool) -> bool:
        """One per epoch boundary (unless the epoch drained): the
        coordinator passes its learner's continue/shutdown decision."""
        return bool(
            self._agree(1 if (self.is_coordinator and stop) else 0, "agree_stop")
        )

    def agree_rollback_epoch(self, epoch: int) -> int:
        """Sentinel-rollback agreement: the coordinator passes its
        manifest verdict (the newest verified epoch, 0 = none), followers
        pass anything — all receive the same target.  Every process
        reaches this call together because the streak that triggers it is
        computed from the COLLECTIVE step metrics (identical on all
        ranks)."""
        return self._agree(
            int(epoch) if self.is_coordinator else 0, "agree_rollback"
        )
