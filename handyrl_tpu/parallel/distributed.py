"""Multi-host (multi-process) initialization for the gradient plane.

The reference scales out with its pickle/TCP worker tree only — its learner
is single-host (``nn.DataParallel``, reference train.py:340-341).  Here the
learner itself can span hosts: ``jax.distributed.initialize`` connects the
processes, ``jax.devices()`` then returns the GLOBAL device list, and the
same ``make_mesh``/``NamedSharding`` train step runs SPMD across hosts with
XLA routing collectives over ICI within a slice and DCN across slices
(SURVEY.md §2.5 gradient-plane prescription).

Config (``train_args.distributed``)::

    distributed:
      coordinator_address: "10.0.0.1:1234"   # host:port of process 0
      num_processes: 4
      process_id: 0                          # or set via PROCESS_ID env

Division of labor when initialized:

* every process executes the jitted train step (SPMD requires all
  processes to join every collective), feeding its local batch shard via
  ``jax.make_array_from_process_local_data``;
* only process 0 (``is_coordinator()``) writes checkpoints/metrics and
  serves models to the actor plane — the guards live in
  ``runtime/learner.py``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax


def init_distributed(dist_args: Optional[Dict[str, Any]]) -> int:
    """Initialize ``jax.distributed`` from config; returns the process index.

    A missing/empty ``coordinator_address`` means single-process — no-op,
    returns 0.  ``process_id`` may come from the config or the
    ``PROCESS_ID`` environment variable (per-host launchers usually inject
    the rank via env).
    """
    if not dist_args or not dist_args.get("coordinator_address"):
        return 0
    process_id = dist_args.get("process_id")
    if process_id is None:
        process_id = int(os.environ.get("PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=dist_args["coordinator_address"],
        num_processes=int(dist_args["num_processes"]),
        process_id=int(process_id),
        local_device_ids=dist_args.get("local_device_ids"),
    )
    return jax.process_index()


def is_coordinator() -> bool:
    """True on the process that owns checkpoints, metrics, model serving."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()


def local_batch_size(global_batch_size: int) -> int:
    """Per-process share of a global batch (SPMD data feeding)."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"batch_size {global_batch_size} not divisible by {n} processes"
        )
    return global_batch_size // n
