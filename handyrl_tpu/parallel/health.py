"""Cross-host health plane: peer heartbeats + a collective-timeout watchdog.

Under ``jax.distributed`` a lost or frozen peer process is, by default, an
INDEFINITE hang: every surviving process blocks inside the next collective
waiting for a participant that will never arrive.  This module bounds that
failure.  Two independent detectors run beside the training threads:

* **Heartbeats** (``HostHealthPlane``): the coordinator (process 0) serves
  a tiny TCP health port (default: coordinator port + 1); every other
  process sends a one-line JSON heartbeat each ``heartbeat_interval``
  seconds.  A peer silent past ``heartbeat_timeout`` is declared LOST on
  the coordinator; the loss is echoed to the surviving peers in the
  heartbeat acks so they stop too.  A follower whose heartbeats go
  unanswered past the timeout declares the COORDINATOR lost.  Heartbeat
  threads never touch a device, so they keep beating while the trainer is
  wedged inside a dead collective — which is exactly when they matter.

* **Collective watchdog** (``CollectiveWatchdog``): the trainer arms it
  around every cross-process dispatch; a dispatch still in flight after
  ``collective_timeout`` seconds means a peer stopped participating (a
  wedged-but-not-dead host keeps heartbeating), and the watchdog fires.

Either detector ends in the learner's ``_host_fault``: the coordinator
drain-saves a manifest-verified checkpoint from the last consistent host
snapshot and every survivor exits ``EXIT_RESUMABLE`` (75) — a wedged
collective cannot be cancelled from Python, so a loud bounded exit with a
verified resume point is the strongest recovery a host-side supervisor can
offer (the PaLM skip-and-rollback discipline extended from bad steps to
dead hosts; docs/fault_tolerance.md §Multi-host failure matrix).

Everything here is stdlib sockets + threads: no jax imports, so the
monitor logic is unit-testable socket-free (tests/test_health.py).
"""

from __future__ import annotations

import json
import select
import socket
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional


def _split_address(address: str) -> tuple:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


def resolve_health_port(dist_args: Dict[str, Any]) -> int:
    """The health plane's TCP port: ``distributed.health_port`` when set,
    else coordinator port + 1 (one launcher knob covers both planes)."""
    port = int(dist_args.get("health_port") or 0)
    if port:
        return port
    return _split_address(dist_args["coordinator_address"])[1] + 1


class CollectiveWatchdog:
    """Bounds the time any armed section may stay in flight.

    The trainer arms it immediately before a cross-process dispatch and
    disarms it when the dispatch returns; a monitor thread fires
    ``on_timeout(tag)`` once if an armed section outlives ``timeout``
    seconds.  First-dispatch jit compilation is excluded by the CALLER
    (arm only after the first completed step — the plane-watchdog
    compile-grace pattern); pre-first-step peer deaths are the heartbeat
    plane's job.  ``timeout <= 0`` disables the watchdog entirely.
    """

    def __init__(self, timeout: float, on_timeout: Callable[[str], None],
                 clock: Callable[[], float] = time.monotonic):
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._tag = ""
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self.timeout <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="collective-watchdog"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    @property
    def fired(self) -> bool:
        return self._fired

    def arm(self, tag: str) -> None:
        with self._lock:
            self._armed_at = self._clock()
            self._tag = tag

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None

    def check(self, now: Optional[float] = None) -> bool:
        """One monitor tick (public for socket-free unit tests); True once
        the watchdog has fired."""
        if self.timeout <= 0:
            return False
        with self._lock:
            armed_at, tag, fired = self._armed_at, self._tag, self._fired
            if fired or armed_at is None:
                return fired
            age = (self._clock() if now is None else now) - armed_at
            if age <= self.timeout:
                return False
            self._fired = True
        self.on_timeout(
            f"collective '{tag}' still in flight after {age:.1f}s "
            f"(> collective_timeout {self.timeout:.0f}s) — a peer process "
            "stopped participating"
        )
        return True

    def _monitor(self) -> None:
        tick = max(0.05, min(1.0, self.timeout / 8.0))
        while not self._stop.is_set():
            time.sleep(tick)
            if self.check():
                return


class HostHealthPlane:
    """Peer liveness over a dedicated TCP port, beside jax.distributed.

    Role follows the process index: process 0 runs the server/monitor
    half, everyone else the heartbeat-client half.  ``on_fault(reason,
    kind)`` is invoked AT MOST ONCE (kinds: ``"peer_loss"`` /
    ``"coordinator_loss"``); cumulative counters live in ``events`` and
    feed the learner's ``dist_*`` metrics keys.
    """

    def __init__(self, dist_args: Dict[str, Any], process_id: int,
                 num_processes: int,
                 on_fault: Callable[[str, str], None],
                 clock: Callable[[], float] = time.monotonic):
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.on_fault = on_fault
        self.interval = float(dist_args.get("heartbeat_interval") or 0.0)
        self.timeout = float(dist_args.get("heartbeat_timeout") or 30.0)
        self.enabled = self.interval > 0 and self.num_processes > 1
        self._host = _split_address(dist_args["coordinator_address"])[0] \
            if dist_args.get("coordinator_address") else "127.0.0.1"
        self._port = resolve_health_port(dist_args) if self.enabled else 0
        self._clock = clock
        self._stop = threading.Event()
        self._beat = threading.Event()   # cleared by the wedge fault
        self._beat.set()
        self._faulted = False
        self._fault_lock = threading.Lock()
        self._threads: list = []
        self._server: Optional[socket.socket] = None
        # coordinator books: rank -> last heartbeat arrival (monotonic)
        self.last_seen: Dict[int, float] = {}
        self._conn_by_rank: Dict[int, socket.socket] = {}
        self.lost: set = set()
        self._last_miss_bump: Dict[int, float] = {}
        self._started_at: Optional[float] = None
        self.events: Dict[str, int] = {
            "heartbeat_misses": 0,
            "peer_losses": 0,
            "coordinator_losses": 0,
        }
        # -- cross-host metric relay (observability.rank_metrics) ---------
        # follower side: the next heartbeat carries this snapshot once;
        # coordinator side: rank -> (snapshot, arrival monotonic).  PR 12
        # made metrics.jsonl coordinator-only — this is how follower ranks
        # get back INTO it, as rank_* aggregates, without a second
        # transport (the beats are already flowing)
        self._pending_metrics: Optional[Dict[str, Any]] = None
        self._metrics_lock = threading.Lock()
        # rank -> (snapshot, arrival) — written by per-connection serve
        # threads, read at epoch boundaries: every access holds
        # _metrics_lock (a first-beat insert racing the learner's fold
        # would otherwise die on dict-changed-size)
        self.peer_metrics: Dict[int, tuple] = {}
        # report-cadence EMA for the staleness verdict: snapshots arrive
        # once per EPOCH, not per beat, so "stale" must key off the
        # observed aggregation period (the beat interval only floors it)
        self._agg_period: Optional[float] = None
        self._last_agg_at: Optional[float] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if not self.enabled:
            return
        self._started_at = self._clock()
        if self.process_id == 0:
            self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._server.bind(("", self._port))
            self._server.listen(self.num_processes + 2)
            self._server.settimeout(0.5)
            self._spawn(self._accept_loop, "health-accept")
            self._spawn(self._monitor_loop, "health-monitor")
        else:
            self._spawn(self._client_loop, "health-heartbeat")

    def stop(self) -> None:
        self._stop.set()
        server, self._server = self._server, None
        if server is not None:
            try:
                server.close()
            except OSError:
                pass

    def stop_heartbeats(self) -> None:
        """Freeze this process's health-plane traffic WITHOUT tearing the
        plane down — the wedge fault's hook (a frozen host goes silent;
        it does not close its sockets).  On a follower the outgoing beats
        stop; on the COORDINATOR the server half stops acking (and its
        monitor stops declaring losses — a frozen host declares nothing),
        so the documented follower-side detector (beats unanswered past
        heartbeat_timeout -> coordinator_loss) really is reachable under
        HANDYRL_FAULT_WEDGE_PROCESS on rank 0."""
        self._beat.clear()

    def disarm(self) -> None:
        """The run concluded coherently on EVERY process (the cadence's
        agreed stop/drain boundary reached all ranks): from here peer
        silence is expected teardown, not a host fault.  Teardown is not
        lockstep — worker joins, final fetches and checkpoint writes skew
        the ranks by arbitrary seconds, so a still-armed plane would
        misread the first rank to stop answering (or beating) as a lost
        host and os._exit(75) out of a CLEAN run.  Threads keep running
        until stop(); they just can no longer declare a loss."""
        with self._fault_lock:
            self._faulted = True

    # -- cross-host metric relay ---------------------------------------------

    def offer_metrics(self, snapshot: Dict[str, Any]) -> None:
        """Follower side: queue one per-epoch metric snapshot to ride the
        next heartbeat (newest wins — the relay is a health signal, not a
        lossless stream).  A no-op on a disabled plane."""
        with self._metrics_lock:
            self._pending_metrics = dict(snapshot)

    def _take_pending_metrics(self) -> Optional[Dict[str, Any]]:
        with self._metrics_lock:
            snap, self._pending_metrics = self._pending_metrics, None
            return snap

    def _restore_pending_metrics(self, snap: Optional[Dict[str, Any]]) -> None:
        """A failed send must not lose the epoch's snapshot — restore it
        unless a newer one was offered meanwhile."""
        if snap is None:
            return
        with self._metrics_lock:
            if self._pending_metrics is None:
                self._pending_metrics = snap

    def note_peer_metrics(self, rank: int, snapshot: Dict[str, Any],
                          now: Optional[float] = None) -> None:
        """Coordinator side: file a follower's metric snapshot (public for
        socket-free unit tests; ``_serve_peer`` is the wire caller)."""
        at = self._clock() if now is None else now
        with self._metrics_lock:
            self.peer_metrics[int(rank)] = (dict(snapshot), at)

    def rank_aggregates(self, own: Dict[str, Any],
                        now: Optional[float] = None) -> Dict[str, Any]:
        """Coordinator side: fold the per-rank snapshots (self = rank 0,
        fresh; followers = last relayed) into the ``rank_*`` metrics keys.

        The staleness fields are the point: a WEDGED-but-heartbeating
        follower keeps acking but its trainer stops, so its relayed epoch/
        steps freeze and ``rank_report_age_s_max`` grows past the epoch
        cadence — visible in metrics.jsonl long before the collective
        watchdog's bound fires (docs/observability.md §Rank aggregates).

        Snapshots arrive once per EPOCH (a follower one boundary behind is
        the healthy steady state), so the stale verdict keys off the
        OBSERVED aggregation cadence: a report older than 2.5x the period
        EMA — floored at 3 heartbeat intervals for second-scale epochs —
        is stale.  The bound uses the EMA from BEFORE this call's gap, so
        a host-fault fold minutes after the last boundary judges against
        the healthy cadence, not the wedge-stretched gap.
        """
        now = self._clock() if now is None else now
        reports = [(0, dict(own), now)]
        with self._metrics_lock:
            peers = sorted(self.peer_metrics.items())
        for rank, (snap, at) in peers:
            reports.append((rank, snap, at))
        # pre-update EMA -> stale bound; then fold this call's gap in
        stale_bound = (
            max(3.0 * max(self.interval, 1e-6), 2.5 * self._agg_period)
            if self._agg_period is not None
            else None  # first fold: no cadence observed, no stale verdict
        )
        if self._last_agg_at is not None and now > self._last_agg_at:
            gap = now - self._last_agg_at
            self._agg_period = (
                gap if self._agg_period is None
                else 0.5 * self._agg_period + 0.5 * gap
            )
        self._last_agg_at = now
        out: Dict[str, Any] = {"rank_reports": len(reports)}

        def fold(key: str, values, digits: int = 4) -> None:
            vals = [float(v) for v in values if v is not None]
            if not vals:
                return
            out[f"rank_{key}_min"] = round(min(vals), digits)
            out[f"rank_{key}_max"] = round(max(vals), digits)
            out[f"rank_{key}_mean"] = round(sum(vals) / len(vals), digits)

        fold("epoch", [s.get("epoch") for _, s, _ in reports], 0)
        fold("steps", [s.get("steps") for _, s, _ in reports], 0)
        fold("train_steps_per_sec",
             [s.get("train_steps_per_sec") for _, s, _ in reports])
        fold("input_wait_frac",
             [s.get("input_wait_frac") for _, s, _ in reports])
        ages = [max(0.0, now - at) for _, _, at in reports]
        out["rank_report_age_s_max"] = round(max(ages), 2)
        # ranks (self included via its 0 age) whose report outlived the
        # cadence-derived bound: the wedged-follower flag.  The raw max
        # age above is always reported, so operators can judge even on
        # the first fold (where no bound exists yet)
        out["rank_stale_reports"] = (
            sum(1 for a in ages if a > stale_bound)
            if stale_bound is not None else 0
        )
        out["rank_missing_reports"] = self.num_processes - len(reports)
        return out

    def _spawn(self, target, name: str) -> None:
        t = threading.Thread(target=target, daemon=True, name=name)
        # per-connection _serve_peer threads arrive once per follower
        # RECONNECT — unpruned, a flapping peer grows this list forever
        self._threads = [x for x in self._threads if x.is_alive()]
        self._threads.append(t)
        t.start()

    def _fault(self, reason: str, kind: str) -> None:
        with self._fault_lock:
            if self._faulted:
                return
            self._faulted = True
        self.on_fault(reason, kind)

    # -- coordinator half ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except (OSError, socket.timeout, TypeError, AttributeError):
                if self._stop.is_set():
                    return
                continue
            conn.settimeout(self.timeout)
            self._spawn(lambda c=conn: self._serve_peer(c), "health-peer")

    def _serve_peer(self, conn: socket.socket) -> None:
        buf = b""
        try:
            while not self._stop.is_set():
                chunk = conn.recv(4096)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        msg = json.loads(line)
                        rank = int(msg["rank"])
                    except (ValueError, KeyError, TypeError):
                        continue  # a garbled line is not a liveness signal
                    if not self._beat.is_set():  # wedged: receive, never ack
                        continue
                    self._conn_by_rank[rank] = conn
                    self.last_seen[rank] = self._clock()
                    snap = msg.get("metrics")
                    if isinstance(snap, dict):
                        # per-epoch metric snapshot riding the beat: file
                        # it for the learner's rank_* aggregates
                        self.note_peer_metrics(rank, snap)
                    ack = json.dumps({"ok": 1, "lost": sorted(self.lost)})
                    conn.sendall(ack.encode() + b"\n")
        except OSError:
            return  # a dropped connection surfaces as heartbeat silence
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _peer_has_pending_data(self, rank: int) -> bool:
        """True when rank's connection holds UNPROCESSED bytes: its beats
        arrived but the serve thread hasn't run yet (LOCAL scheduling
        starvation — GIL convoy under CPU oversubscription — not a dead
        peer).  Declaring a loss on top of that would exit 75 out of a
        healthy run; skip the tick and let the serve thread catch up."""
        conn = self._conn_by_rank.get(rank)
        if conn is None:
            return False
        try:
            readable, _, _ = select.select([conn], [], [], 0)
            return bool(readable)
        except (OSError, ValueError):
            return False

    def check_peers(self, now: Optional[float] = None) -> Optional[int]:
        """One monitor tick (public for socket-free unit tests): returns
        the first newly-LOST rank, or None.  A peer is lost once silent
        past ``timeout`` — including one that never sent a first beat
        within the join grace (it died between jax init and plane start)."""
        now = self._clock() if now is None else now
        grace_origin = self._started_at if self._started_at is not None else now
        for rank in range(1, self.num_processes):
            if rank in self.lost:
                continue
            last = self.last_seen.get(rank, grace_origin)
            age = now - last
            if age > 1.5 * self.interval and (
                now - self._last_miss_bump.get(rank, 0.0) > self.interval
            ):
                # one miss per silent interval, not per monitor tick
                self._last_miss_bump[rank] = now
                self.events["heartbeat_misses"] += 1
            if age > self.timeout:
                if self._peer_has_pending_data(rank):
                    continue  # beats are HERE, just not processed yet
                self.lost.add(rank)
                self.events["peer_losses"] += 1
                return rank
        return None

    def _rebase_after_stall(self, gap: float) -> None:
        """The monitor thread itself just lost ``gap`` seconds to
        scheduling starvation: that window observed nothing, so shifting
        every liveness origin forward by it keeps the staleness that was
        measured BEFORE the stall without counting the blackout as peer
        silence (a starved process must not declare its healthy peers
        dead the instant it wakes up)."""
        for rank in list(self.last_seen):
            self.last_seen[rank] += gap
        if self._started_at is not None:
            self._started_at += gap

    def _monitor_loop(self) -> None:
        tick = max(0.05, self.interval / 2.0)
        prev = self._clock()
        while not self._stop.is_set():
            time.sleep(tick)
            now = self._clock()
            if now - prev > 3.0 * tick + 1.0:
                self._rebase_after_stall(now - prev)
            prev = now
            if not self._beat.is_set():  # wedged: a frozen host declares nothing
                continue
            rank = self.check_peers()
            if rank is not None:
                self._fault(
                    f"peer process {rank} lost: no heartbeat for "
                    f"{self.timeout:.0f}s (heartbeat_timeout)",
                    "peer_loss",
                )
                return

    # -- follower half -------------------------------------------------------

    def _client_loop(self) -> None:
        # lazy: trace is stdlib-only, but the utils package init pulls jax
        # — keep health.py's module import jax-free for socket-free units
        from ..utils.trace import trace_span

        last_ok = self._clock()
        conn: Optional[socket.socket] = None
        buf = b""
        seq = 0
        attempts_since_ok = 0
        # one recv cycle waits at most ~2 beat intervals, not the whole
        # timeout: a single delayed ack must not silently swallow the
        # entire budget with zero further probes in flight
        ack_wait = min(self.timeout, max(2.0 * self.interval, 1.0))
        while not self._stop.is_set():
            if not self._beat.is_set():   # wedged: go silent, stay up
                time.sleep(self.interval)
                continue
            pending = None
            try:
                if conn is None:
                    conn = socket.create_connection(
                        (self._host, self._port), timeout=ack_wait
                    )
                    conn.settimeout(ack_wait)
                    buf = b""
                seq += 1
                attempts_since_ok += 1
                msg: Dict[str, Any] = {"rank": self.process_id, "seq": seq}
                pending = self._take_pending_metrics()
                if pending is not None:
                    # the per-epoch metric snapshot piggybacks on the beat
                    # (one send covers liveness AND observability)
                    msg["metrics"] = pending
                with trace_span("health.heartbeat", plane="health", seq=seq):
                    conn.sendall(json.dumps(msg).encode() + b"\n")
                    while b"\n" not in buf:
                        chunk = conn.recv(4096)
                        if not chunk:
                            raise OSError("health connection closed")
                        buf += chunk
                line, buf = buf.split(b"\n", 1)
                ack = json.loads(line)
                pending = None  # acked: the snapshot reached the books
                last_ok = self._clock()
                attempts_since_ok = 0
                lost = [r for r in ack.get("lost", []) if r != self.process_id]
                if lost:
                    self._fault(
                        f"coordinator reports peer process(es) {lost} lost; "
                        "the run cannot keep its collectives coherent",
                        "peer_loss",
                    )
                    return
            except (OSError, ValueError, socket.timeout):
                self.events["heartbeat_misses"] += 1
                self._restore_pending_metrics(pending)
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    conn = None
            if (
                self._clock() - last_ok > self.timeout
                and attempts_since_ok >= 3
            ):
                # the probe-count floor keeps a locally-STARVED client
                # honest: a thread that just lost the whole window to a
                # GIL convoy has sent nothing, so it earns no verdict
                # until a few real probes go unanswered too
                self.events["coordinator_losses"] += 1
                self._fault(
                    f"coordinator at {self._host}:{self._port} unreachable "
                    f"for {self.timeout:.0f}s (heartbeat_timeout, "
                    f"{attempts_since_ok} unanswered probes) — it likely "
                    "died; exiting instead of hanging in its collectives",
                    "coordinator_loss",
                )
                return
            self._stop.wait(self.interval)


def announce_fault(reason: str, kind: str, exit_code: int) -> None:
    """One loud, grep-stable stderr line for every host-fault exit."""
    print(
        f"[handyrl_tpu] host fault ({kind}): {reason} — exiting "
        f"{exit_code} (EX_TEMPFAIL; relaunch with restart_epoch: -1)",
        file=sys.stderr,
        flush=True,
    )
