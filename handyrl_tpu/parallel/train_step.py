"""The jitted, sharded training step — forward, targets, loss, optimizer.

This is the TPU replacement for the reference's host-side training loop
(train.py:128-268, 348-372): ONE compiled function per batch shape doing

    forward (FF flatten or lax.scan RNN with burn-in)
    -> loss core (ops/losses.py, targets as reverse scans)
    -> global-norm clip + L2 decay + Adam
    -> parameter update

under ``jax.jit`` with NamedShardings: the batch is sharded over the 'dp'
mesh axis, params/optimizer state replicated; XLA inserts the gradient
all-reduce over ICI.  The learning rate is a scalar argument (the
reference's data-count-EMA schedule, train.py:328-332/383-385, is computed
on host per epoch).

Forward-prediction semantics parity (train.py:128-187):
* feed-forward nets flatten (B, T, P) into one device batch;
* recurrent nets scan over T carrying hidden state, zeroing the carry into
  steps a player did not observe and only committing new hidden where
  observed; burn-in steps run under stop_gradient;
* policy logits are turn-masked (summed over the player axis for
  turn-alternating batches) and get the action mask subtracted;
* value-ish outputs are observation-masked (broadcasting the turn player's
  prediction against the full-player mask in turn-based mode).
"""

from __future__ import annotations


from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

from ..ops import compute_loss_from_outputs
from ..utils import tree_map
from .mesh import batch_sharding, dispatch_serialized, param_shardings, replicated_sharding


def _flat_apply(module, params, obs, lead_shape):
    """Apply module to observations flattened over ``lead_shape`` dims."""
    n = len(lead_shape)
    flat = tree_map(lambda x: x.reshape((-1,) + x.shape[n:]), obs)
    out = module.apply({"params": params}, flat, None)
    return {
        k: v.reshape(lead_shape + v.shape[1:])
        for k, v in out.items()
        if k != "hidden" and v is not None
    }


def _compute_dtype(args: Dict[str, Any]):
    return jnp.bfloat16 if args.get("compute_dtype") == "bfloat16" else None


def _auto_flag(args: Dict[str, Any], key: str, default: bool) -> bool:
    """Tri-state config flag: absent / None / 'auto' -> backend-chosen
    default; anything else is coerced to bool.  Without this, a literal
    ``remat: auto`` in config.yaml would be truthy and force the exact
    pathological mode the auto default exists to avoid."""
    v = args.get(key, "auto")
    if v is None or v == "auto":
        return default
    return bool(v)


def _cast_floats(tree, dtype):
    return tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree
    )


def resolve_seq_attention(args: Dict[str, Any], T: int) -> str:
    """THE seq-mode attention auto-pick policy, as one shared resolver
    ('einsum' | 'flash' | 'ring' for a window of length ``T``) used by the
    compiled forward, the bench's transformer stages, and the CI smoke —
    so "which path did the program take" is decided (and reportable) in
    exactly one place.

    ``auto`` picks the Pallas masked flash kernel for windows >=
    ``flash_min_t`` and the exact einsum below it.  The crossover is a
    property of the PROGRAM (the O(T^2) score tensor vs the kernel's fixed
    launch/block overhead, measured on-chip: einsum wins at T64, flash
    1.54x at T1024 — BENCH_r05 flash_attention.speedup).  The policy is
    shared by TPU (compiled kernel) and CPU (exact interpret-mode kernel —
    CPU long-T runs are tests/smokes on this TPU framework, and sharing
    the pick is what lets CI exercise the very program the chip compiles);
    any OTHER backend (e.g. GPU) falls back to einsum under auto, because
    the interpreter there would be a silent orders-of-magnitude slowdown
    on what may be a real training run — spell ``flash`` explicitly to
    override."""
    mode = args.get("seq_attention", "auto")
    if mode == "auto":
        if jax.default_backend() not in ("tpu", "cpu"):
            return "einsum"
        return "flash" if T >= int(args.get("flash_min_t", 128)) else "einsum"
    return mode


def resolve_seq_remat(args: Dict[str, Any], T: int) -> str:
    """The seq-path rung of the remat ladder ('none' | 'attn' | 'block').

    Explicit ladder values pass through; booleans collapse to the nearest
    rung (True -> 'block', False -> 'none'); ``auto`` turns 'block' on for
    long windows (T >= 512) on TPU — the d2048 width sweep died to HBM
    pressure with remat named as the missing lever (bench.py) — and stays
    'none' elsewhere (short windows fit, and the CPU path prefers speed).

    Ring attention is always 'none': each device already holds only its
    T/n shard's activations (the ring IS the memory partitioning), and
    jax.checkpoint around the shard_map ring loop trips shard_map's
    scan-carry replication typing at trace time (reproduced on jax
    0.4.37) — the combination is rejected at config time and neutralized
    here for direct-API callers."""
    if args.get("seq_attention") == "ring":
        return "none"
    v = args.get("remat", "auto")
    if v in ("none", "attn", "block"):
        return v
    # isinstance, not identity/equality: config validation rejects bare
    # ints, and 1 == True must not silently alias a rung
    if isinstance(v, bool):
        return "block" if v else "none"
    return "block" if jax.default_backend() == "tpu" and T >= 512 else "none"


def forward_prediction(module, params, batch: Dict[str, Any], args: Dict[str, Any]) -> Dict[str, Any]:
    """Run the net over a (B, T, P, ...) batch; returns post-burn-in outputs
    of length forward_steps, already turn/action/observation masked.

    With ``compute_dtype: bfloat16`` the forward runs in bf16 (params are
    cast by the caller; observations/hidden here) — MXU-rate compute with
    fp32 master weights.  Outputs are restored to fp32 before the masking
    arithmetic (the 1e32 action mask is not bf16-representable)."""
    cdt = _compute_dtype(args)
    obs = batch["observation"]
    if any(x.dtype == jnp.int8 for x in jax.tree.leaves(obs)):
        # obs_int8: host-fed batches carry int8 planes end-to-end (wire ->
        # shm -> device upload); dequantize here, inside the jitted update,
        # under the spec the generator quantized with (threaded by the
        # learner as args['_obs_quant']; absent = identity scale)
        from ..models.quantize import dequantize_obs_tree

        obs = dequantize_obs_tree(obs, args.get("_obs_quant"))
    if cdt is not None:
        # observations (and params, cast by the caller) carry bf16 through
        # the net; recurrent hidden stays fp32 — the carry must keep one
        # dtype across scan steps, and e.g. the transformer's step counter
        # is not exactly representable in bf16 past 256
        obs = _cast_floats(obs, cdt)
    B, T, P1 = batch["action"].shape[:3]
    burn_in = args["burn_in_steps"]
    hidden0 = module.initial_state((B, P1))

    if hidden0 is None:
        # Feed-forward compaction: put_batch may have sliced the observation
        # to the live prefix [0, T_obs) — every later step is end-of-episode
        # padding whose outputs the masks below zero exactly (make_batch
        # keeps the valid region a prefix when burn_in is 0).  Compute the
        # net only on the live steps and zero-pad the outputs back to T:
        # numerically identical, ~40% fewer forward/backward FLOPs on
        # short-episode envs like TicTacToe (reference train.py pads the
        # same windows but always pays full-T compute).
        T_obs = jax.tree.leaves(obs)[0].shape[1]
        outputs = _flat_apply(module, params, obs, (B, T_obs, P1))
        if T_obs < T:
            outputs = {
                k: jnp.pad(v, ((0, 0), (0, T - T_obs)) + ((0, 0),) * (v.ndim - 2))
                for k, v in outputs.items()
            }
        outputs = {k: v[:, burn_in:] for k, v in outputs.items()}
    elif getattr(module, "supports_seq", False) and args.get("seq_forward", True):
        # whole-window attention path: one batched call instead of a T-step
        # scan — the masks reproduce the KV-cache semantics exactly (see
        # CachedSelfAttention seq mode), so values match the scan path.
        omask = batch["observation_mask"]
        assert omask.shape[2] == P1, (
            "recurrent training requires full-player batches "
            "(set observation: true for RNN models)"
        )
        to_bp = lambda x: jnp.moveaxis(x, 2, 1).reshape((B * P1, T) + x.shape[3:])
        obs_bp = tree_map(to_bp, obs)                       # (B*P, T, ...)
        km = to_bp(omask)[..., 0]                           # (B*P, T)
        # seq_attention: 'einsum' (exact O(T^2) path), 'flash' (Pallas
        # masked flash-attention kernel, blk_q/blk_k block-size knobs),
        # 'ring' (sequence-parallel masked ring attention over the mesh's
        # 'sp' axis — args['_mesh'], set by TrainContext), or 'auto'
        # (flash at T >= flash_min_t, einsum below — see
        # resolve_seq_attention, the single shared policy).  The remat
        # ladder (resolve_seq_remat: 'none'/'attn'/'block') rides the same
        # call: checkpointed blocks trade ~1 extra forward for ~n_layers x
        # less live activation HBM at long T.
        mode = resolve_seq_attention(args, T)
        ring_mesh = None
        if mode == "ring":
            # mesh shape + T divisibility are validated up front by
            # TrainContext.__init__ (fail-fast); args['_mesh'] is set there
            ring_mesh = args.get("_mesh")
        outs = module.apply(
            {"params": params}, obs_bp, None, seq=True, key_mask=km,
            burn_in=burn_in, use_flash=mode == "flash", ring_mesh=ring_mesh,
            remat=resolve_seq_remat(args, T),
            blk_q=int(args.get("blk_q", 128)), blk_k=int(args.get("blk_k", 128)),
        )
        outputs = {
            k: jnp.moveaxis(v.reshape((B, P1, T) + v.shape[2:]), 1, 2)[:, burn_in:]
            for k, v in outs.items()
            if k != "hidden" and v is not None
        }
    else:
        omask = batch["observation_mask"]
        assert omask.shape[2] == P1, (
            "recurrent training requires full-player batches "
            "(set observation: true for RNN models)"
        )
        obs_tl = tree_map(lambda x: jnp.moveaxis(x, 1, 0), obs)      # (T, B, P, ...)
        omask_tl = jnp.moveaxis(omask, 1, 0)                          # (T, B, P, 1)

        def step(hidden, x):
            obs_t, omask_t = x

            def mask_like(h):
                m = omask_t.reshape(omask_t.shape[:2] + (1,) * (h.ndim - 2))
                return m

            h_in = tree_map(lambda h: h * mask_like(h), hidden)
            h_flat = tree_map(lambda h: h.reshape((-1,) + h.shape[2:]), h_in)
            obs_flat = tree_map(lambda o: o.reshape((-1,) + o.shape[2:]), obs_t)
            out = module.apply({"params": params}, obs_flat, h_flat)
            new_hidden = tree_map(
                lambda h: h.reshape((B, P1) + h.shape[1:]), out.pop("hidden")
            )
            # commit new hidden only where observed (train.py:174)
            hidden = jax.tree.map(
                lambda h, nh: h * (1 - mask_like(h)) + nh * mask_like(nh), hidden, new_hidden
            )
            outs = {
                k: v.reshape((B, P1) + v.shape[1:]) for k, v in out.items() if v is not None
            }
            return hidden, outs

        # Backend-aware scan strategy:
        # * remat (default on TPU): recompute the body's activations in the
        #   backward pass instead of storing T steps of DRC gate tensors —
        #   ~T x less live HBM at ~1.3x forward recompute (config: remat).
        # * unroll (default on single-device CPU, i.e. the CPU-fallback
        #   bench/train case): XLA:CPU executes ops inside while-loop
        #   bodies without its fast kernel runtime — measured 17-40x slower
        #   than the identical ops unrolled (DRC step: 9.3s looped vs 0.56s
        #   unrolled at batch 16).  Full unroll restores the fast kernels;
        #   on TPU the loop is fine and compiles T x faster, and on a
        #   multi-device mesh the unrolled body makes the SPMD partitioner's
        #   compile time explode (config: unroll).
        on_cpu = jax.default_backend() == "cpu"
        mesh = args.get("_mesh")
        one_dev = mesh is None or mesh.size == 1
        # the seq-path remat LADDER strings collapse to on/off here: the
        # scan body has no attention/FFN split to checkpoint selectively
        rv = args.get("remat", "auto")
        rv = {"none": False, "attn": True, "block": True}.get(rv, rv)
        if _auto_flag({"remat": rv}, "remat", not on_cpu):
            step = jax.checkpoint(step)
        unroll = _auto_flag(args, "unroll", on_cpu and one_dev)

        def burn_step(hidden, x):
            hidden, _ = step(hidden, x)
            return jax.lax.stop_gradient(hidden), None

        slice_t = lambda tree, lo, hi: tree_map(lambda x: x[lo:hi], tree)
        hidden = hidden0
        if burn_in > 0:
            hidden, _ = jax.lax.scan(
                burn_step, hidden,
                (slice_t(obs_tl, 0, burn_in), omask_tl[:burn_in]),
                unroll=unroll,
            )
        _, outs_tl = jax.lax.scan(
            step, hidden,
            (slice_t(obs_tl, burn_in, T), omask_tl[burn_in:]),
            unroll=unroll,
        )
        outputs = {k: jnp.moveaxis(v, 0, 1) for k, v in outs_tl.items()}  # (B, T', P, ...)

    # -- output masking (train.py:177-187), on post-burn-in arrays ---------
    tmask = batch["turn_mask"][:, burn_in:]
    omask = batch["observation_mask"][:, burn_in:]
    amask = batch["action_mask"][:, burn_in:]

    masked = {}
    for k, v in outputs.items():
        v = v.astype(jnp.float32)  # loss/target math stays fp32
        if k == "policy":
            v = v * tmask
            if v.shape[2] > 1 and P1 == 1:
                v = v.sum(axis=2, keepdims=True)  # gather the turn player's logits
            masked[k] = v - amask
        else:
            masked[k] = v * omask
    return masked


def trim_burn_in(batch: Dict[str, Any], burn_in: int) -> Dict[str, Any]:
    """Drop burn-in steps from every time-majored batch array (train.py:222)."""
    if burn_in == 0:
        return batch
    return {k: (v[:, burn_in:] if v.shape[1] > 1 else v) for k, v in batch.items() if k != "observation"} | {
        "observation": tree_map(lambda x: x[:, burn_in:], batch["observation"])
    }


def make_optimizer() -> optax.GradientTransformation:
    """clip(4.0) -> L2 weight decay 1e-5 -> Adam, matching reference
    train.py:328-332 + 371 (decay applied to gradients, torch-Adam style).
    The learning rate is applied separately in the train step."""
    return optax.chain(
        optax.clip_by_global_norm(4.0),
        optax.add_decayed_weights(1e-5),
        optax.scale_by_adam(),
    )


class TrainContext:
    """Owns the mesh, the optimizer, and the compiled train step."""

    def __init__(self, module, args: Dict[str, Any], mesh):
        self.module = module
        # '_mesh' rides in the (untraced) args dict so forward_prediction
        # can hand the mesh to sequence-parallel attention paths
        self.args = dict(args, _mesh=mesh)
        if args.get("seq_attention") == "ring":
            sp = mesh.shape.get("sp", 1)
            if sp < 2:
                raise ValueError(
                    "seq_attention='ring' needs a mesh with an 'sp' axis of "
                    f"size >= 2 (got {dict(mesh.shape)}); set train_args.mesh "
                    "accordingly, e.g. {'dp': 2, 'sp': 4}"
                )
            T = args["burn_in_steps"] + args["forward_steps"]
            if T % sp:
                raise ValueError(
                    f"seq_attention='ring': window length {T} (burn_in_steps "
                    f"+ forward_steps) must be divisible by the 'sp' axis "
                    f"size {sp}"
                )
        # fail-fast geometry checks for the seq attention paths (same
        # construction-time-loudness contract as the ring checks above)
        if getattr(module, "supports_seq", False) and args.get("seq_forward", True):
            # same rule as config.validate_args, re-checked here for
            # direct-API callers that never pass through normalize_args —
            # the two layers must not drift into different constraints.
            # Power-of-two blocks make the padded-window divisibility of
            # ops.flash_attention.effective_blocks hold by construction
            # (the smaller power of two divides the larger).
            for name in ("blk_q", "blk_k"):
                b = int(args.get(name, 128))
                if b < 8 or (b & (b - 1)):
                    raise ValueError(
                        f"{name} must be a power of two >= 8, got {b}"
                    )
            if args.get("seq_attention") == "ring" and args.get("remat") in (
                "attn", "block", True,
            ):
                raise ValueError(
                    "remat ladder is unsupported with seq_attention='ring': "
                    "the ring already partitions activation memory over "
                    "'sp', and jax.checkpoint around the shard_map ring "
                    "loop fails shard_map's scan-carry replication typing "
                    "— set remat: none/auto"
                )
        # fail fast at construction, not mid-training in a learner thread:
        # under turn-based training, stateful models (RNN hidden or
        # KV-cache) train on all-player windows, which only exist when
        # every player's observation is recorded (the forward asserts the
        # same on batch shapes).  Simultaneous-move configs
        # (turn_based_training: false) are exempt: their single-player
        # windows observe the target player every step, so the hidden
        # carry is well-defined without the flag.
        if (
            module.initial_state((1, 1)) is not None
            and args.get("turn_based_training", True)
            and not args.get("observation")
        ):
            raise ValueError(
                "recurrent/memory models (RNN hidden or KV-cache transformer) "
                "under turn-based training require train_args.observation: "
                "true — per-step observations for every player are needed to "
                "build their all-player training windows.  (For a "
                "SINGLE-player custom env the turn player is the target "
                "player every step, so the carry is well-defined either "
                "way — set observation: true, or turn_based_training: "
                "false, to proceed.)"
            )
        self.mesh = mesh
        self.tx = make_optimizer()
        self._replicated = replicated_sharding(mesh)
        self._batch_shard = batch_sharding(mesh)
        # Feed-forward batches with burn_in 0 keep their live steps in a
        # prefix of the T axis (batch.py padding layout); put_batch then
        # slices the observation to that prefix so the train step skips
        # compute on end-of-episode padding (see forward_prediction).
        # Multi-process is excluded: every process must agree on the
        # global array shape and t_eff is computed from local rows only.
        self._ff_compact = (
            module.initial_state((1, 1)) is None
            and args.get("burn_in_steps", 0) == 0
            and args.get("compact_padding", True)
        )

        loss_keys = ("p", "v", "r", "ent", "total")

        cdt = _compute_dtype(args)
        if cdt is not None and not getattr(module, "supports_seq", False):
            # bf16 on the small-conv game nets, settled by the round-4
            # dispatch-amortized on-chip profile (tools/profile_bf16.py,
            # K=32 fused, v5e, 2026-08-01): device math is PARITY — fp32
            # 3.05 ms/update vs bf16 2.93 (1.04x) at geese shapes; the
            # round-2 "2.9x slower" was a dispatch-bound measurement, not
            # kernel time.  bf16 additionally wins whenever transfers
            # dominate (smaller copies).  XLA:CPU is the real regression
            # (~0.46x: convert ops don't fuse there) — warn only there,
            # judged by the mesh that will actually run the step (a CPU
            # mesh on a TPU host still hits the CPU regression).
            if mesh.devices.flat[0].platform == "cpu":
                import sys

                print(
                    "[handyrl_tpu] compute_dtype=bfloat16 on a conv game "
                    "net under XLA:CPU: measured ~2x SLOWER than float32 "
                    "(unfused convert ops); on TPU it is parity-or-better "
                    "(see BASELINE.md bf16 row)",
                    file=sys.stderr,
                )

        def _loss_fn(params, batch):
            # mixed precision: bf16 copies feed the forward, fp32 master
            # params stay in the optimizer; grads come back fp32 through
            # the cast's vjp
            fwd_params = params if cdt is None else _cast_floats(params, cdt)
            outputs = forward_prediction(self.module, fwd_params, batch, self.args)
            trimmed = trim_burn_in(batch, self.args["burn_in_steps"])
            losses, dcnt = compute_loss_from_outputs(outputs, trimmed, self.args)
            full = {k: losses.get(k, jnp.zeros(())) for k in loss_keys}
            return losses["total"], (full, dcnt)

        # Divergence sentinel (config: sentinel, default on): finite-checks
        # of the loss, the gradient global-norm, and the lr are FUSED into
        # the compiled step — the verdict rides back with the existing
        # metrics (no extra host sync on the happy path), and a bad step's
        # update is suppressed under lax.cond so a single NaN/inf can never
        # poison the params or the Adam moments.  The host (runtime/
        # trainer.py) counts the flags at epoch end (sentinel_skipped_steps)
        # and escalates a long bad streak to a verified-checkpoint rollback.
        sentinel = bool(args.get("sentinel", True))

        def _step(state, batch, lr):
            (loss, (losses, dcnt)), grads = jax.value_and_grad(_loss_fn, has_aux=True)(
                state["params"], batch
            )

            def _apply(_):
                updates, opt_state = self.tx.update(
                    grads, state["opt_state"], state["params"]
                )
                updates = jax.tree.map(lambda u: -lr * u, updates)
                return optax.apply_updates(state["params"], updates), opt_state

            metrics = dict(losses)
            metrics["dcnt"] = dcnt
            if sentinel:
                gnorm = optax.global_norm(grads)
                bad = jnp.logical_not(
                    jnp.isfinite(loss) & jnp.isfinite(gnorm) & jnp.isfinite(lr)
                )
                params, opt_state = jax.lax.cond(
                    bad,
                    lambda _: (state["params"], state["opt_state"]),
                    _apply,
                    operand=None,
                )
                # a skipped step contributes nothing to the epoch's loss
                # averages (a NaN loss summed once would poison them); its
                # count rides in its own key instead
                metrics = jax.tree.map(
                    lambda m: jnp.where(bad, jnp.zeros_like(m), m), metrics
                )
                metrics["sentinel_bad"] = bad.astype(jnp.float32)
            else:
                params, opt_state = _apply(None)
            new_state = {"params": params, "opt_state": opt_state, "steps": state["steps"] + 1}
            return new_state, metrics

        # sharding follows the data: params/opt_state enter laid out by
        # init_state (replicated, or 'mp'-sharded kernels when the mesh has
        # a tensor-parallel axis), the batch enters 'dp'-sharded, and GSPMD
        # propagates — the gradient all-reduce over ICI falls out of the
        # layout rather than being spelled as explicit collectives.  The
        # state shardings are pinned on BOTH sides of the jit (bound lazily
        # on the first state, _bind): without out_shardings the first call
        # compiles against init_state's layout, returns compiler-chosen
        # output shardings, and the second call silently recompiles — a
        # hidden ~30s stall on TPU that round 2's bench exposed.
        self._step_fn = _step

        def _steps(state, batches, lr):
            """k SGD updates under one lax.scan — one dispatch, one
            executable; metrics come back summed over the k steps (the
            trainer accumulates sums anyway).  Semantically identical to k
            separate calls with the same (held-per-epoch) lr; numerically
            equivalent only up to float reassociation, since XLA fuses the
            scan body differently than the unrolled step (pinned at
            rtol 1e-5 by tests/test_training.py)."""
            def body(s, b):
                return _step(s, b, lr)

            state, metrics = jax.lax.scan(
                body, state, batches,
                # same XLA:CPU while-loop pathology as the RNN scan above
                unroll=jax.default_backend() == "cpu" and mesh.size == 1,
            )
            return state, jax.tree.map(lambda m: m.sum(axis=0), metrics)

        self._steps_fn = _steps
        self._train_step = None
        self._train_steps = None

    def _fresh_put(self, tree):
        """Lay ``tree`` out on the mesh in NEW buffers.

        ``jax.device_put`` may alias the source buffer as one shard of the
        produced array; because the train step donates its state
        (``donate_argnums=(0,)``), an aliased layout would delete the
        caller's arrays on the first update.  A jitted identity always
        materializes fresh outputs, so the caller keeps ownership.

        The layout put is a multi-device program like any other, and this
        path also runs MID-RUN (sentinel rollback re-lays params while the
        rollout thread keeps dispatching) — so it takes the mesh's
        dispatch locks itself.  Callers must NOT wrap it again: the
        per-device locks are not reentrant."""
        shardings = param_shardings(self.mesh, tree)
        put = jax.jit(lambda t: t, out_shardings=shardings)
        return dispatch_serialized(lambda: put(tree), self.mesh)

    def _bind(self, state):
        """Compile the train step with the state layout pinned on both sides
        (in_shardings == out_shardings), so every call — including the first
        — hits one executable."""
        if self._train_step is None:
            ss = param_shardings(self.mesh, state)
            self._train_step = jax.jit(
                self._step_fn,
                donate_argnums=(0,),
                in_shardings=(ss, self._batch_shard, self._replicated),
                out_shardings=(ss, self._replicated),
            )
        return self._train_step

    def init_state(self, params) -> Dict[str, Any]:
        params = self._fresh_put(params)
        # optimizer moments inherit the params' layout (same shape-based
        # 'mp' rule, pinned so the state enters _bind's layout exactly);
        # dispatched under the mesh's locks like _fresh_put — init_state
        # runs mid-run on a sentinel rollback
        init = jax.jit(
            self.tx.init,
            out_shardings=param_shardings(
                self.mesh, jax.eval_shape(self.tx.init, params)
            ),
        )
        opt_state = dispatch_serialized(lambda: init(params), self.mesh)
        return {
            "params": params,
            "opt_state": opt_state,
            "steps": jax.device_put(jnp.zeros((), jnp.int32), self._replicated),
        }

    def put_state(self, state_host: Dict[str, Any]) -> Dict[str, Any]:
        """Lay a host-side (resumed) train state out on the mesh: every leaf
        gets the same shape-based 'mp' rule as fresh params, so a checkpoint
        written on any mesh restores onto this one."""
        return self._fresh_put(state_host)

    def _live_steps(self, batch) -> int:
        """Last T index with any turn/observation activity (+1).  Exact —
        the distinct-shape set (and so the jit cache) stays tiny in
        practice because an env's max episode length pins the batch max."""
        act = np.asarray(batch["turn_mask"]) + np.asarray(batch["observation_mask"])
        live = act.any(axis=(0, 2, 3))
        return int(live.nonzero()[0][-1]) + 1 if live.any() else 1

    def _compact_ff(self, batch, t_eff: Optional[int] = None):
        """Slice the observation to the live prefix (see _ff_compact)."""
        if not self._ff_compact or jax.process_count() > 1:
            return batch
        if t_eff is None:
            t_eff = self._live_steps(batch)
        if t_eff >= np.asarray(batch["turn_mask"]).shape[1]:
            return batch
        return dict(
            batch,
            observation=tree_map(lambda x: x[:, :t_eff], batch["observation"]),
        )

    def put_batch(self, batch: Dict[str, Any]):
        """Lay a host batch out dp-sharded.

        Single-process: one device_put.  Multi-process (jax.distributed):
        ``batch`` is this process's LOCAL shard (global_batch /
        process_count rows); every process assembles its own shard and the
        global array is built with make_array_from_process_local_data —
        no cross-host batch traffic."""
        batch = self._compact_ff(batch)
        return self._put_sharded(batch, self._batch_shard, batch["action"].shape[0])

    def train_step(self, state, device_batch, lr: float):
        # concurrent multi-device programs (e.g. the sharded device
        # rollout) must reach every device in one order — see
        # mesh.dispatch_serialized
        fn = self._bind(state)
        return dispatch_serialized(
            lambda: fn(state, device_batch, jnp.float32(lr)), self.mesh
        )

    def put_batches(self, host_batches):
        """Stack k host batches -> one (k, B, ...) device tree, B sharded
        over 'dp' (axis 1), for the fused train_steps path."""
        if self._ff_compact and jax.process_count() == 1:
            t_eff = max(self._live_steps(b) for b in host_batches)
            host_batches = [self._compact_ff(b, t_eff) for b in host_batches]
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *host_batches)
        shard = NamedSharding(self.mesh, PartitionSpec(None, "dp"))
        return self._put_sharded(stacked, shard, host_batches[0]["action"].shape[0])

    def _put_sharded(self, tree, shard, B: int):
        """Lay a host tree out under ``shard``.  Single-process: one
        device_put (with a clear dp-divisibility error).  Multi-process
        (jax.distributed): ``tree`` is this process's LOCAL shard
        (global_batch / process_count rows) and the global array is built
        with make_array_from_process_local_data — no cross-host traffic."""
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(shard, np.asarray(x)),
                tree,
            )
        dp = self.mesh.shape.get("dp", 1)
        if B % dp != 0:
            raise ValueError(f"batch size {B} not divisible by dp axis {dp}")
        return jax.device_put(tree, shard)

    def train_steps(self, state, stacked_device_batch, lr: float):
        """k fused updates (see _steps); input from put_batches."""
        if self._train_steps is None:
            ss = param_shardings(self.mesh, state)
            stacked_shard = NamedSharding(self.mesh, PartitionSpec(None, "dp"))
            self._train_steps = jax.jit(
                self._steps_fn,
                donate_argnums=(0,),
                in_shardings=(ss, stacked_shard, self._replicated),
                out_shardings=(ss, self._replicated),
            )
        return dispatch_serialized(
            lambda: self._train_steps(state, stacked_device_batch, jnp.float32(lr)),
            self.mesh,
        )

    def flops_per_step(self, state, device_batch):
        """Flops of one update (for MFU accounting), best source first:

        1. HLO cost analysis of the bound executable's lowering (shares
           the signature, so no second jit-cache entry);
        2. a CPU-backend lowering of the same program (same arithmetic) —
           unavailable when the platform list is pinned to a single
           plugin (e.g. the axon sitecustomize sets jax_platforms=axon,
           so no in-process CPU backend exists: the exact configuration
           where fallback 1 also has no cost model);
        3. backend-free analytic counting over the jaxpr
           (``jaxpr_flops``) — dot/conv terms only, which is also what
           dominates the HLO count."""
        def _cpu_lowering():
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                return jax.jit(self._step_fn).lower(
                    jax.tree.map(jax.typeof, state),
                    jax.tree.map(jax.typeof, device_batch),
                    jax.ShapeDtypeStruct((), jnp.float32),
                )

        for lower in (
            lambda: self._bind(state).lower(state, device_batch, jnp.float32(1e-5)),
            _cpu_lowering,
        ):
            try:
                ca = lower().cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                flops = float(ca.get("flops", 0.0))
                if flops > 0:
                    return flops
            except Exception:
                continue
        try:
            jaxpr = jax.make_jaxpr(self._step_fn)(
                state, device_batch, jnp.float32(1e-5)
            )
            flops = jaxpr_flops(jaxpr.jaxpr)
            return flops if flops > 0 else None
        except Exception:
            return None


# peak dense bf16 FLOP/s per chip (public figures) — the denominator for
# MFU accounting everywhere (bench.py headline stages, Trainer per-epoch
# stats -> metrics.jsonl)
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5", 197e12),   # v5e / v5 litepod
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _by_device_kind(table, device) -> Optional[float]:
    """First-match substring lookup over a (tag, value) table; tag order
    matters (longer tags like 'v5p' before 'v5')."""
    kind = getattr(device, "device_kind", "").lower()
    for tag, value in table:
        if tag in kind:
            return value
    return None


def peak_flops_per_chip(device) -> Optional[float]:
    """Peak dense FLOP/s for ``device`` (None when the kind is unknown —
    callers report MFU as null-with-reason rather than guessing)."""
    return _by_device_kind(PEAK_FLOPS_BY_KIND, device)


# peak HBM bandwidth per chip, bytes/s (public figures) — the other
# roofline axis: a step whose arithmetic intensity (flops / bytes
# accessed) sits below the ridge point peak_flops/bw is bandwidth-bound
# and its MFU ceiling is intensity * bw / peak_flops (tools/roofline.py)
HBM_BW_BY_KIND = [
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5", 819e9),    # v5e / v5 litepod
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]


def hbm_bandwidth_per_chip(device) -> Optional[float]:
    return _by_device_kind(HBM_BW_BY_KIND, device)


def jaxpr_flops(jaxpr) -> float:
    """Backend-free analytic flop count of a jaxpr: 2*MACs for every
    ``dot_general`` and ``conv_general_dilated``, recursing through
    higher-order primitives (scan multiplied by trip count, cond counted
    at its widest branch, while bodies once).  Elementwise/reduction ops
    are ignored — matmul/conv dominate the HLO count this substitutes for
    (flops_per_step fallback 3, used when no backend offers a cost
    model).  Tends to overestimate slightly (XLA simplifies some convs
    away): measured 1.15x XLA:CPU's HLO 'flops' on the GeeseNet train
    step, 1.58x on TicTacToe; factor-2 agreement is asserted by
    tests/test_training.py::test_jaxpr_flops_close_to_hlo."""
    import numpy as _np

    total = 0.0
    for eqn in jaxpr.eqns:
        p = eqn.primitive.name
        if p == "dot_general":
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            lhs, rhs = (v.aval.shape for v in eqn.invars[:2])
            batch = _np.prod([lhs[i] for i in lb], dtype=float) if lb else 1.0
            contract = _np.prod([lhs[i] for i in lc], dtype=float) if lc else 1.0
            lfree = _np.prod(
                [d for i, d in enumerate(lhs) if i not in set(lc) | set(lb)],
                dtype=float,
            ) if lhs else 1.0
            rfree = _np.prod(
                [d for i, d in enumerate(rhs) if i not in set(rc) | set(rb)],
                dtype=float,
            ) if rhs else 1.0
            total += 2.0 * batch * contract * lfree * rfree
        elif p == "conv_general_dilated":
            dn = eqn.params["dimension_numbers"]
            rhs_shape = eqn.invars[1].aval.shape
            out_numel = float(_np.prod(eqn.outvars[0].aval.shape, dtype=float))
            in_feats = rhs_shape[dn.rhs_spec[1]]  # already / feature_groups
            k_spatial = _np.prod([rhs_shape[i] for i in dn.rhs_spec[2:]], dtype=float)
            total += 2.0 * out_numel * in_feats * k_spatial
        else:
            subs = []
            for val in eqn.params.values():
                vals = val if isinstance(val, (tuple, list)) else (val,)
                for v in vals:
                    inner = getattr(v, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        subs.append(inner)
                    elif hasattr(v, "eqns"):
                        subs.append(v)
            if not subs:
                continue
            if p == "scan":
                mult = float(eqn.params.get("length", 1))
                total += mult * sum(jaxpr_flops(s) for s in subs)
            elif p == "cond":
                total += max(jaxpr_flops(s) for s in subs)
            else:  # pjit, while, remat, custom_* — count bodies once
                total += sum(jaxpr_flops(s) for s in subs)
    return total
