"""Device mesh construction and sharding helpers.

The gradient/parameter plane of the framework: where the reference used
``nn.DataParallel`` over local GPUs (train.py:340-341), we lay devices out
in a named ``jax.sharding.Mesh`` and let XLA insert the collectives (psum
over ICI for gradients).  Axes:

* ``dp`` — data parallel: batches shard along axis 0, params replicated.
* further axes (e.g. ``mp``) can be added through the config
  ``train_args.mesh`` dict without touching the train step: params/batch
  shardings are derived from the mesh axis names.

Multi-host: under ``jax.distributed`` initialization the same code spans
hosts — ``jax.devices()`` returns the global device list and XLA routes
collectives over ICI/DCN.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.trace import trace_span

# Serializes the DISPATCH of multi-device (collective-bearing) programs
# PER DEVICE.  Two SPMD programs enqueued concurrently from different host
# threads — e.g. the sharded train step and the sharded device rollout —
# can reach the devices in a different order on different devices; XLA's
# collective rendezvous then waits for a participant that is queued behind
# the other program and aborts the process ("Expected N threads to join
# ... only N-1 arrived", reproduced on the 8-device CPU mesh).  Holding
# every participating device's lock across the enqueue (the jitted call
# returns right after dispatch; execution stays async) gives every device
# the same program order, which is the documented requirement for
# concurrent collective programs.
#
# The locks are PER DEVICE (not one global lock) so programs on DISJOINT
# device sets — the split actor/learner planes — dispatch concurrently:
# they share no device, hence no queue whose order could diverge and no
# rendezvous either could join.  Overlapping sets share at least one
# device lock and therefore serialize exactly as before; acquiring in
# global sorted id order makes the multi-lock acquisition deadlock-free.
_DEVICE_LOCKS: dict = {}
_REGISTRY_LOCK = threading.Lock()


def _locks_for(devices):
    """The per-device locks covering ``devices``, in canonical order."""
    keys = sorted({(d.process_index, d.id) for d in devices})
    with _REGISTRY_LOCK:
        return [_DEVICE_LOCKS.setdefault(k, threading.Lock()) for k in keys]


def dispatch_serialized(call, devices=None):
    """Run ``call`` (which enqueues one multi-device program and returns
    its async outputs) holding the dispatch lock of every participating
    device.

    ``devices`` names the devices the program touches: a ``Mesh``, an
    iterable of jax devices, or None for ALL local devices (the
    conservative legacy behavior — serializes with everything).  Disjoint
    device sets proceed concurrently; any overlap serializes.

    On TPU the locks cover only the enqueue — hardware per-device queues
    then preserve the program order and execution stays async.  On the
    CPU backend the locks additionally hold until the outputs are READY:
    virtual devices share one thunk pool, so a collective's rendezvous
    waiters can pin every pool thread while another in-flight program on
    an OVERLAPPING device set holds the slot the last participant needs —
    a liveness failure (XLA aborts after its 40 s rendezvous timeout)
    reproduced on the 8-device CPU mesh whenever the sharded train step
    and the sharded device rollout ran concurrently.  Disjoint-set
    programs never share a rendezvous, so holding only their own locks
    keeps them overlapping on CPU too (pinned by
    tests/test_plane.py::test_disjoint_dispatches_overlap)."""
    if devices is None:
        devices = jax.devices()
    elif isinstance(devices, Mesh):
        devices = devices.devices.flat
    locks = _locks_for(devices)
    held = []
    try:
        # acquisition inside the try: an async exception (Ctrl-C) landing
        # mid-loop must release the locks already held, or every later
        # dispatch touching those devices deadlocks.  The spans (trace:
        # enabled only — disabled is one attribute check and a shared
        # no-op context) split lock contention from program time: on CPU
        # "dispatch.run" includes execution (the lock covers readiness),
        # on TPU it is enqueue time only
        with trace_span("dispatch.wait", devices=len(locks)):
            for lock in locks:
                lock.acquire()
                held.append(lock)
        with trace_span("dispatch.run", devices=len(locks)):
            out = call()
            if jax.default_backend() == "cpu":
                jax.block_until_ready(out)
        return out
    finally:
        for lock in reversed(held):
            lock.release()


def make_mesh(spec: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from an axis-name -> size dict; -1 fills remaining devices.

    make_mesh({'dp': -1})            # all devices data-parallel
    make_mesh({'dp': 4, 'mp': 2})    # 4x2 two-axis mesh
    make_mesh({'dp': 2})             # sub-mesh on the first 2 devices

    All-positive axis sizes may cover a prefix of the devices (sub-mesh,
    e.g. to pin the learner to some chips); -1 axes fill what remains.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = dict(spec or {"dp": -1})
    n = len(devices)
    fixed = math.prod(s for s in spec.values() if s > 0)
    if any(s <= 0 for s in spec.values()):
        if n % max(fixed, 1) != 0:
            raise ValueError(f"{n} devices not divisible by fixed mesh axes {spec}")
        fill = n // fixed
        sizes = tuple(s if s > 0 else fill for s in spec.values())
    else:
        sizes = tuple(spec.values())
    if math.prod(sizes) > n:
        raise ValueError(f"mesh {dict(zip(spec, sizes))} needs more than {n} devices")
    import numpy as np

    return Mesh(np.asarray(devices[: math.prod(sizes)]).reshape(sizes), tuple(spec.keys()))


def split_mesh(spec: Optional[Dict[str, int]] = None, actor_chips: int = 1,
               devices: Optional[Sequence] = None):
    """Partition the device list into disjoint (learner_mesh, actor_mesh).

    The learner plane keeps the PREFIX of the device list (so device 0 —
    the coordinator / checkpoint owner — stays a learner chip) laid out by
    ``spec`` exactly as ``make_mesh`` would over that many devices; the
    actor plane takes the trailing ``actor_chips`` devices as a flat
    ``{'dp': actor_chips}`` mesh.  With per-device dispatch locks the two
    planes enqueue programs concurrently — self-play and training at full
    duty on their own chips (config: ``plane: split`` + ``actor_chips``).

    Under a multi-process ``jax.distributed`` run (``devices`` left None
    and ``jax.process_count() > 1``) the carve is per HOST, not per list
    position: every process contributes its leading ``local - actor_chips``
    devices to one GLOBAL learner mesh (the collective train step spans
    hosts over DCN) and keeps its trailing ``actor_chips`` devices as a
    process-LOCAL actor mesh — the actor plane's rollout/ingest programs
    are per-process by design (each host generates its own shard of
    episodes), so they must never be collective across hosts.  ``actor_
    chips`` therefore means "per host" in a pod-slice run.
    """
    actor_chips = int(actor_chips)
    if actor_chips < 1:
        raise ValueError(f"actor_chips must be >= 1, got {actor_chips}")
    if devices is None and jax.process_count() > 1:
        local = list(jax.local_devices())
        if actor_chips >= len(local):
            raise ValueError(
                f"plane: split needs at least one learner device PER HOST: "
                f"actor_chips {actor_chips} of {len(local)} local devices "
                "leaves none (actor_chips is per host in a multi-process run)"
            )
        # group the global list by owning process, preserving jax's order
        # within each group, so the learner mesh keeps the canonical
        # device order XLA expects for cross-host collectives
        by_proc: Dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        counts = {len(ds) for ds in by_proc.values()}
        if len(counts) != 1:
            raise ValueError(
                f"plane: split needs the same local device count on every "
                f"host, got {sorted(counts)}"
            )
        learner_devs = [
            d for p in sorted(by_proc) for d in by_proc[p][: len(by_proc[p]) - actor_chips]
        ]
        learner = make_mesh(spec, learner_devs)
        actor = make_mesh({"dp": actor_chips}, local[len(local) - actor_chips:])
        return learner, actor
    devices = list(devices if devices is not None else jax.devices())
    if actor_chips >= len(devices):
        raise ValueError(
            f"plane: split needs at least one learner device: actor_chips "
            f"{actor_chips} of {len(devices)} devices leaves none"
        )
    learner = make_mesh(spec, devices[: len(devices) - actor_chips])
    actor = make_mesh({"dp": actor_chips}, devices[len(devices) - actor_chips:])
    return learner, actor


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a (B, ...) pytree's leading axis over the 'dp' mesh axis."""
    return NamedSharding(mesh, PartitionSpec("dp"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def param_shardings(mesh: Mesh, params):
    """Tensor-parallel parameter layout over the 'mp' mesh axis.

    Heuristic matching how dense/conv kernels want to split on TPU: a leaf
    with >=2 dims whose output-channel (last) axis divides the 'mp' size is
    sharded on that axis; everything else (biases, scales, small heads) is
    replicated.  Without an 'mp' axis this degenerates to full replication
    — the v1 data-parallel layout.  XLA/GSPMD inserts the collectives
    implied by the layout (all-gather on column-parallel matmuls etc.).
    """
    mp = mesh.shape.get("mp", 1)

    def shard(x):
        if mp > 1 and getattr(x, "ndim", 0) >= 2 and x.shape[-1] % mp == 0:
            return NamedSharding(mesh, PartitionSpec(*([None] * (x.ndim - 1)), "mp"))
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree.map(shard, params)
