from .mesh import (
    make_mesh,
    split_mesh,
    batch_sharding,
    param_shardings,
    replicated_sharding,
)
from .train_step import (
    TrainContext,
    forward_prediction,
    resolve_seq_attention,
    resolve_seq_remat,
)
from .distributed import (
    DistributedCadence,
    broadcast_resume_epoch,
    init_distributed,
    is_coordinator,
    local_batch_size,
    process_count,
    process_index,
)
from .health import CollectiveWatchdog, HostHealthPlane

__all__ = [
    "make_mesh",
    "split_mesh",
    "batch_sharding",
    "replicated_sharding",
    "param_shardings",
    "TrainContext",
    "forward_prediction",
    "resolve_seq_attention",
    "resolve_seq_remat",
    "init_distributed",
    "is_coordinator",
    "local_batch_size",
    "process_count",
    "process_index",
    "DistributedCadence",
    "broadcast_resume_epoch",
    "CollectiveWatchdog",
    "HostHealthPlane",
]
