from .mesh import make_mesh, batch_sharding, replicated_sharding
from .train_step import TrainContext, forward_prediction

__all__ = ["make_mesh", "batch_sharding", "replicated_sharding", "TrainContext", "forward_prediction"]
