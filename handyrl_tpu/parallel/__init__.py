from .mesh import make_mesh, batch_sharding, param_shardings, replicated_sharding
from .train_step import TrainContext, forward_prediction

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "param_shardings",
    "TrainContext",
    "forward_prediction",
]
