"""Headline benchmark: sharded train-step throughput on the default config.

Measures trained env-steps/sec (batch_size x forward_steps per update)
through the REAL pipeline — self-play episodes -> replay windows ->
make_batch -> jitted sharded train step — on whatever devices are present
(one real TPU chip under the driver, virtual CPU devices in tests).

Baseline: the reference (kuto5046/HandyRL) measured on this machine,
same config (TicTacToe, batch 128 x forward_steps 16, torch CPU):
    19.39 updates/s = 39,707 trained env-steps/s
(see BASELINE.md "measured" table; the reference publishes no numbers).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_TRAINED_STEPS_PER_SEC = 39707.0  # measured, BASELINE.md


def main() -> None:
    import jax

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, RandomModel, init_variables
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": {}})
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    n_dev = len(jax.devices())
    if args["batch_size"] % n_dev:
        args["batch_size"] = max(n_dev, args["batch_size"] // n_dev * n_dev)

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    # self-play data through the real generator (host-side, no device calls)
    store = EpisodeStore(1024)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 256:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])

    def sample_batch():
        windows = []
        while len(windows) < args["batch_size"]:
            w = store.sample_window(
                args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
            )
            if w is not None:
                windows.append(w)
        return make_batch(windows, args)

    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(variables["params"])
    device_batches = [ctx.put_batch(sample_batch()) for _ in range(4)]

    # warmup (compile)
    state, metrics = ctx.train_step(state, device_batches[0], 1e-5)
    jax.block_until_ready(metrics["total"])

    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 15.0:
        state, metrics = ctx.train_step(state, device_batches[n % len(device_batches)], 1e-5)
        n += 1
    jax.block_until_ready(metrics["total"])
    dt = time.perf_counter() - t0

    trained_steps_per_sec = n * args["batch_size"] * args["forward_steps"] / dt
    print(
        json.dumps(
            {
                "metric": "tictactoe_trained_env_steps_per_sec",
                "value": round(trained_steps_per_sec, 1),
                "unit": "env-steps/s",
                "vs_baseline": round(trained_steps_per_sec / REFERENCE_TRAINED_STEPS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
