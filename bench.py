"""Headline benchmark suite: real-pipeline throughput on whatever chip is present.

Three measurements, all through the REAL framework paths (no synthetic
kernels):

1. TicTacToe trained env-steps/s — self-play episodes -> replay windows ->
   make_batch -> jitted sharded train step (headline; the reference measured
   39,707 trained env-steps/s on this machine, BASELINE.md).
2. HungryGeese (north-star env) generation throughput — thread actors
   driving the batched cross-env inference engine (the actor-plane TPU
   path); reference single-process generation measured 1,557 env-steps/s.
3. HungryGeese training throughput + input_wait_frac through the threaded
   BatchPipeline, plus MFU from XLA compiled cost analysis (always
   reported — as a number or as null with the reason).
4. The north-star loop itself: streaming on-device HungryGeese self-play
   feeding the store while the learner trains from it concurrently, with
   both planes' rates, learner input starvation, and the per-chip
   fraction of the 100k/v4-32 target.

Every timed window stretches until at least one unit (update / episode)
completes — a slow backend yields a small measured rate or an explicit
null+note, never a silent 0.0.

Prints json lines of the shape {"metric", "value", "unit", "vs_baseline"}
plus "extra": one snapshot after the probe and after every stage (marked
"partial") and a final unmarked line, each also atomically replacing the
side file ``bench_snapshot.json`` — so a SIGKILL at any moment leaves the
newest parseable state on stdout's last line AND on disk.  Never exits
non-zero for backend trouble: a wedged chip lease is waited out (re-probe
loop, BENCH_TPU_WAIT budget) but only up to the outer deadline
(BENCH_DEADLINE_S, default 1700 s) minus a reserve for the headline stage
(BENCH_RESERVE_S, default 300 s) — a 29-minute wedge can no longer eat
the measuring window (the r04 rc=124 failure).  Each stage retries once
on a transient failure; stages that would start with < BENCH_STAGE_MIN_S
of deadline left are skipped with an honest note so the run finishes
clean (rc=0) before the driver's kill.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Optional



REFERENCE_TRAINED_STEPS_PER_SEC = 39707.0  # measured, BASELINE.md (torch CPU)
REFERENCE_GEN_STEPS_PER_SEC = 1557.0       # measured, BASELINE.md (torch CPU, TicTacToe)
# HungryGeese like-for-like: the reference's own loop shape (batch-1 torch
# inference per active player, single process) with the reference's own
# GeeseNet on this host — tools/reference_geese_gen.py.  Rounds 1-3 divided
# the geese stages by the TICTACTOE row above, understating them 17x.
REFERENCE_GEESE_GEN_STEPS_PER_SEC = 89.0   # measured 2026-08-01, BASELINE.md

QUICK = bool(os.environ.get("BENCH_QUICK"))
T_TRAIN = 4.0 if QUICK else 12.0
T_GEN = 4.0 if QUICK else 10.0


def _note(msg: str) -> None:
    """Progress marker on stderr (stdout stays one JSON line)."""
    import sys

    global _LAST_NOTE
    _LAST_NOTE = msg
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()
_LAST_NOTE = "startup"


def _env_float(name: str, default: float) -> float:
    """Env override parsed as float; malformed or SET-BUT-EMPTY values
    fall back to the default rather than costing the capture (an empty
    string from CI interpolation must not read as 0 and silently disable
    the lease wait / watchdog — explicit \"0\" is the disable switch)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _tpu_wait_budget() -> float:
    """Seconds the init-time probe may spend waiting out a wedged chip
    lease before the CPU fallback (BENCH_TPU_WAIT, default 30 min)."""
    return _env_float("BENCH_TPU_WAIT", 1800.0)


def _deadline_s() -> float:
    """Outer wall-clock deadline for the WHOLE run (BENCH_DEADLINE_S,
    default 1700 s, 0 disables).  The driver kills the bench at roughly
    1,800 s; r04 spent 1,741 s of that waiting out a wedged lease and was
    killed ~60 s into the first stage having printed nothing parseable.
    Everything that can spend time — the lease wait, stage starts, the
    measuring watchdog — budgets against this deadline so the process
    always finishes (or snapshots) BEFORE the driver's kill."""
    return _env_float("BENCH_DEADLINE_S", 1700.0)


def _effective_tpu_wait() -> float:
    """Lease-wait budget capped against the outer deadline: the wait may
    never eat the measuring window.  BENCH_RESERVE_S (default 300 s) is
    held back for the headline TicTacToe stage — the r04 lesson: a
    29-minute wedge left ~1 minute to measure, which is none."""
    wait = _tpu_wait_budget()
    deadline = _deadline_s()
    if deadline <= 0:
        return wait
    reserve = _env_float("BENCH_RESERVE_S", 300.0)
    return min(wait, max(0.0, deadline - (time.perf_counter() - _T0) - reserve))


def _snapshot_path() -> str:
    return os.environ.get("BENCH_SNAPSHOT") or "bench_snapshot.json"


def _emit_snapshot(result: dict, final: bool = False,
                   lock_timeout: Optional[float] = None) -> None:
    """Write the accumulated result as a complete JSON line to stdout AND
    atomically replace the side file — after the probe and after every
    stage — so a SIGKILL at ANY moment leaves the newest parseable
    snapshot behind (r04 printed exactly once, at the very end, and was
    killed first).  Every line is the full result-so-far; a consumer
    taking the last parseable stdout line always gets the newest state.
    Non-final lines carry a "partial" marker naming where the run was.
    Serialized under a lock: the watchdog thread emits concurrently with
    the main thread, and two writers on one tmp path could install a
    truncated side file (or interleave the stdout lines).  The watchdog
    passes ``lock_timeout`` so a main thread wedged INSIDE the lock (a
    stuck fsync) cannot block the emergency emission forever — after the
    timeout it emits anyway (interleaving risk only in that already-
    pathological case) and proceeds to os._exit."""
    if lock_timeout is None:
        got = _EMIT_LOCK.acquire()
    else:
        got = _EMIT_LOCK.acquire(timeout=lock_timeout)
    try:
        snap = dict(result)
        snap["extra"] = dict(result.get("extra") or {})
        if final:
            snap.pop("partial", None)
        else:
            snap["partial"] = {
                "at": _LAST_NOTE,
                "elapsed_s": round(time.perf_counter() - _T0),
            }
        line = json.dumps(snap, default=str)
        print(line, flush=True)
        try:  # side file is best-effort; stdout is the contract
            path = _snapshot_path()
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            pass
    finally:
        if got:
            _EMIT_LOCK.release()


_EMIT_LOCK = threading.Lock()


def _start_watchdog(result: dict, done: "threading.Event",
                    budget: Optional[float] = None) -> None:
    """A single wedged device dispatch must not cost the whole capture: a
    tunneled TPU call can block forever (observed mid-run, 2026-07-31 —
    the same failure mode the init-time probe sentinel already guards).
    If the run exceeds BENCH_WATCHDOG_S (default 45 min; 0 disables), the
    watchdog prints the result JSON accumulated SO FAR with an explicit
    error naming the wedged stage, then hard-exits.  os._exit aborts the
    in-flight XLA call, which can wedge the chip lease — acceptable only
    because a lease stuck under a hung dispatch is already lost to this
    process, and a partial capture beats none.  The measuring-phase
    instance starts AFTER the device probe (the probe's lease wait has
    its own budget and must not eat the measuring budget); a separate
    probe-phase instance with ``budget`` = lease wait + slack covers the
    probe loop AND the unbounded in-process ``jax.devices()`` init, which
    can hang exactly like the subprocess probe it follows."""
    if budget is None:
        budget = _env_float("BENCH_WATCHDOG_S", 2700.0)
    if budget <= 0:
        return

    def fire():
        if done.wait(budget) or done.is_set():
            return  # normal completion (re-checked: main prints exactly once)
        import sys

        msg = f" watchdog: run exceeded {budget:.0f}s; wedged at stage: {_LAST_NOTE}"
        try:
            try:
                # snapshot: main may still be mutating result on a slow run
                snap = dict(result)
                snap["extra"] = dict(result.get("extra") or {})
                snap["error"] = (snap.get("error") or "") + msg
                _emit_snapshot(snap, final=True, lock_timeout=10.0)
            except Exception:  # racing mutation: still honor the JSON contract
                print(json.dumps({"metric": result.get("metric"), "value": None,
                                  "unit": "env-steps/s", "vs_baseline": None,
                                  "error": msg}))
            sys.stdout.flush()
        finally:
            os._exit(0)

    threading.Thread(target=fire, daemon=True).start()


def _probe_accelerator(timeout: float = 120.0) -> Optional[tuple]:
    """Try accelerator backend init in a SUBPROCESS (it can hang, not just
    raise — e.g. a stale chip lease after a killed process); returns None
    if healthy, else a ("hung" | "failed", message) tuple."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return ("hung", f"accelerator backend init hung >{timeout:.0f}s")
    if proc.returncode != 0:
        return ("failed", "accelerator backend init failed: " + (proc.stderr or "")[-300:])
    return None


def _devices_with_retry(retries: int = 3, delay: float = 20.0):
    """Probe the accelerator out-of-process until it answers, then fall
    back to CPU so the bench always produces a measured number (round-1
    failure mode: one transient axon UNAVAILABLE crashed the whole
    bench).  A HUNG probe means a wedged chip lease — observed recoveries
    (ROUND3.md) land on the tens-of-minutes scale, and the driver-run
    capture is the only number that counts — so the lease is WAITED OUT:
    re-probe on a backoff loop up to BENCH_TPU_WAIT seconds (default
    30 min; 0 disables the wait) before surrendering to CPU.  Quick
    FAILURES (probe raises rather than hangs) keep the old short-retry
    behavior: ``retries`` tries ``delay`` apart."""
    import jax

    if os.environ.get("HANDYRL_PLATFORM") == "cpu":
        # explicit CPU request (validation runs): skip the probe entirely
        from handyrl_tpu.utils import apply_platform_override

        apply_platform_override()
        return jax.devices(), None

    # the wait budget is capped against the outer deadline (minus the
    # headline-stage reserve): a 29-minute wedge must never eat the
    # measuring window (the r04 rc=124 failure)
    wait_budget = _effective_tpu_wait()
    reprobe_wait = min(150.0, max(wait_budget, 1.0))

    err = None
    tried = 0
    fail_tries = 0
    t_wait0 = time.perf_counter()
    while True:
        tried += 1
        probe = _probe_accelerator()
        if probe is None:
            try:
                return jax.devices(), None
            except Exception as exc:  # probe ok but in-process init failed
                probe = ("failed", str(exc))
        kind, err = probe
        waited = time.perf_counter() - t_wait0
        if kind == "hung":
            # each probe itself holds 120 s, so probe+sleep cycles every
            # ~4.5 min: ~7 chances for the lease to clear inside 30 min
            if waited + reprobe_wait < wait_budget:
                _note(
                    f"accelerator probe hung (wedged lease?); waited "
                    f"{waited:.0f}s of {wait_budget:.0f}s budget; "
                    f"re-probing in {reprobe_wait:.0f}s"
                )
                time.sleep(reprobe_wait)
                continue
        else:
            fail_tries += 1
            if fail_tries < retries:
                _note(f"accelerator probe failed ({err}); retrying")
                time.sleep(delay)
                continue
        break
    try:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices(), (
            f"accelerator unavailable after {tried} tries over "
            f"{time.perf_counter() - t_wait0:.0f}s ({err}); CPU fallback"
        )
    except Exception as exc2:
        return None, f"no backend at all: {err} / {exc2}"


def _peak_flops(device) -> float | None:
    # lazy: bench.py must not import jax (via handyrl_tpu) before the
    # out-of-process accelerator probe has run
    from handyrl_tpu.parallel.train_step import peak_flops_per_chip

    return peak_flops_per_chip(device)


def _make_args(env_name: str, overrides=None, env_overrides=None):
    from handyrl_tpu.config import normalize_args

    cfg = normalize_args(
        {
            "env_args": {"env": env_name, **(env_overrides or {})},
            "train_args": dict(overrides or {}),
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    return args


def _fill_store(args, n_episodes: int):
    """Self-play episodes through the real generator with the zero-output
    RandomModel (host-side, no device calls) — data for the train benches."""
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, RandomModel, init_variables
    from handyrl_tpu.runtime import EpisodeStore, Generator

    env = make_env(args["env"])
    module = env.net()
    model = InferenceModel(module, init_variables(module, env))
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(max(n_episodes * 4, 1024))
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < n_episodes:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    return env, module, model, store


def _sample_batch(store, args):
    from handyrl_tpu.runtime import make_batch

    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(
            args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
        )
        if w is not None:
            windows.append(w)
    return make_batch(windows, args)


def _timed_loop(step, duration: float) -> float:
    """Warm-compile then time: ``step()`` dispatches (possibly async)
    device work and returns a value to block on; the trailing
    block_until_ready is inside the measured window so enqueued work is
    fully accounted.  Returns calls/sec (always from >= 1 completed call:
    the window stretches rather than reporting a zero)."""
    import jax

    jax.block_until_ready(step())  # compile + warm
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < duration or n == 0:
        out = step()
        n += 1
        if n == 1:
            jax.block_until_ready(out)  # slow-backend case: 1 call > window
    jax.block_until_ready(out)
    return n / (time.perf_counter() - t0)


def _sig(x, digits: int = 3):
    """Round a rate to ``digits`` significant figures — never collapses a
    small-but-measured value to 0.0 the way fixed-decimal rounding did
    (round 2 reported geister_rnn_updates_per_sec: 0.0 for a measured
    0.0021/s)."""
    if x is None or x == 0:
        return x
    from math import floor, log10

    return round(x, max(digits - 1 - floor(log10(abs(x))), 0))


def _train_bench(env_name: str, overrides, duration: float, n_devices: int,
                 fill_episodes: int = 48, fused: bool = False, reuse=None,
                 env_overrides=None):
    """Timed jitted-train-step loop on pre-staged device batches.

    Returns updates/s, trained env-steps/s, flops/step (XLA cost analysis).
    ``reuse`` recycles a prior result's (module, model, store) so config
    variants (e.g. bf16) skip episode generation."""
    import jax

    from handyrl_tpu.parallel import TrainContext, make_mesh

    args = _make_args(env_name, overrides, env_overrides)
    if args["batch_size"] % n_devices:
        args["batch_size"] = max(n_devices, args["batch_size"] // n_devices * n_devices)

    if reuse is not None:
        module, model, store = reuse["module"], reuse["model"], reuse["store"]
        _note(f"{env_name}: reusing filled store; compiling + timing the train step")
    else:
        _note(f"{env_name}: generating episodes for the replay store")
        _, module, model, store = _fill_store(args, 12 if QUICK else fill_episodes)
        _note(f"{env_name}: store filled; compiling + timing the train step")

    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(model.variables["params"])
    device_batches = [ctx.put_batch(_sample_batch(store, args)) for _ in range(4)]

    flops = ctx.flops_per_step(state, device_batches[0])

    holder = {"state": state, "i": 0}

    # FF compaction can give the staged batches distinct live-prefix
    # shapes; warm-compile every DISTINCT shape outside the timed window
    # (one cold compile inside the loop skews a 12 s window badly).  Only
    # distinct ones: an extra no-op warm costs a full update, which on a
    # slow backend (DRC on 1-core CPU: minutes) is far from free.
    def _shape_key(b):
        return tuple(
            (x.shape, str(x.dtype)) for x in jax.tree.leaves(b["observation"])
        )

    seen = {_shape_key(device_batches[0])}
    for b in device_batches[1:]:
        k = _shape_key(b)
        if k in seen:
            continue
        seen.add(k)
        holder["state"], m = ctx.train_step(holder["state"], b, 1e-5)
        jax.block_until_ready(m["total"])

    def seq_step():
        holder["state"], metrics = ctx.train_step(
            holder["state"], device_batches[holder["i"] % 4], 1e-5
        )
        holder["i"] += 1
        return metrics["total"]

    ups = _timed_loop(seq_step, duration)

    # fused_steps variant (k below): same updates through the lax.scan path — the
    # dispatch-amortization headroom for small models (config: fused_steps).
    # Opt-in per stage: big recurrent models pay a second long compile for
    # little dispatch-amortization benefit.  TPU-only: XLA:CPU executes
    # scan bodies single-threaded (measured 10-20x slower than unrolled).
    fused_ups = None
    fused_err = None
    if fused and jax.default_backend() == "tpu":
        try:
            # k=16 (was 8, round 3): on tunnel-RTT-bound hours the fused
            # rate is ~(k x updates)/round-trip, so doubling the scan
            # depth roughly doubles the headline at negligible memory
            # (16 stacked TicTacToe batches) and one-off compile cost
            k = 16
            stacked = ctx.put_batches([_sample_batch(store, args) for _ in range(k)])

            def fused_step():
                holder["state"], metrics = ctx.train_steps(holder["state"], stacked, 1e-5)
                return metrics["total"]

            fused_ups = _timed_loop(fused_step, duration / 2) * k
        except Exception:
            fused_err = traceback.format_exc(limit=3)

    return {
        "updates_per_sec": ups,
        "fused_updates_per_sec": fused_ups,
        "fused_error": fused_err,
        "trained_env_steps_per_sec": ups * args["batch_size"] * args["forward_steps"],
        "flops_per_step": flops,
        "store": store,
        "args": args,
        "ctx": ctx,
        "module": module,
        "model": model,
    }


def _generation_bench(env_name: str, overrides, duration: float, num_actors: int = 16):
    """Actor-plane throughput: thread actors sharing one device model via
    the BatchedInferenceEngine (runtime/inference_engine.py), counting
    env-steps completed in the timed window."""
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.runtime import Generator
    from handyrl_tpu.runtime.inference_engine import BatchedInferenceEngine, EngineStopped

    args = _make_args(env_name, overrides)
    env0 = make_env(args["env"])
    module = env0.net()
    model = InferenceModel(module, init_variables(module, env0))

    # pre-compile every power-of-two inference bucket OUTSIDE the timed
    # window (each distinct batch shape is one XLA compile)
    max_batch = min(args["inference_batch_size"], 4 * num_actors)
    _note(f"{env_name}: warming inference buckets up to {max_batch}")
    from handyrl_tpu.utils import tree_stack

    env0.reset()
    obs0 = env0.observation(env0.players()[0])
    b = 1
    while b <= max_batch:
        model.inference_batch(tree_stack([obs0] * b), None)
        b *= 2
    engine = BatchedInferenceEngine(model, max_batch=max_batch).start()
    _note(f"{env_name}: timing generation for {duration:.0f}s")

    steps = [0] * num_actors
    stop = threading.Event()

    def actor(i):
        env = make_env(args["env"])

        def count():
            steps[i] += 1  # incremental: long episodes still register

        gen = Generator(env, args, on_step=count)
        players = env.players()
        models = {p: engine.client() for p in players}
        gen_args = {"player": players, "model_id": {p: -1 for p in players}}
        while not stop.is_set():
            try:
                gen.generate(models, gen_args)
            except EngineStopped:
                return

    threads = [threading.Thread(target=actor, args=(i,), daemon=True) for i in range(num_actors)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    dt = time.perf_counter() - t0  # counting window ends here, before teardown
    engine.stop()
    for t in threads:
        t.join(timeout=5.0)
    total = sum(steps)
    return {
        "env_steps_per_sec": total / dt,
        "episodes_completed": None,
        "batches_served": engine.batches_served,
        "mean_infer_batch": (engine.requests_served / max(engine.batches_served, 1)),
    }


def _timed_pipeline_train(pipe, ctx, state, duration: float, on_timed_start=None,
                          on_timed_end=None):
    """Warm the train path on one pipeline batch, then time updates fed by
    the pipeline, accounting time spent waiting on input separately.
    Stretches past ``duration`` until >= 1 update completes (never a
    silent zero).  ``on_timed_start`` fires after the warm-up, right
    before the clock starts, and ``on_timed_end`` the moment the window
    closes — e.g. to launch a concurrent producer and snapshot its
    counters in sync with the window (work the producer retires after the
    window must not land in the numerator).  Returns
    (n_updates, wait_s, dt)."""
    import jax

    batch = pipe.batch()
    state, metrics = ctx.train_step(state, batch, 1e-5)  # compile path warm
    jax.block_until_ready(metrics["total"])

    if on_timed_start is not None:
        on_timed_start()
    wait_s = 0.0
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < duration or n == 0:
        tw = time.perf_counter()
        batch = pipe.batch()
        wait_s += time.perf_counter() - tw
        if batch is None:
            break
        state, metrics = ctx.train_step(state, batch, 1e-5)
        n += 1
    jax.block_until_ready(metrics["total"])
    dt = time.perf_counter() - t0
    if on_timed_end is not None:
        on_timed_end()
    return n, wait_s, dt


def _pipeline_bench(train_res, duration: float):
    """Train through the configured batch pipeline (default: shared-memory
    batcher PROCESSES, runtime/shm_batch.py; replay -> make_batch ->
    device_put -> step) and measure input starvation (north-star: learner
    never input-starved) plus the per-stage time breakdown that says
    WHICH stage any starvation comes from."""
    from handyrl_tpu.runtime.trainer import make_pipeline

    args, ctx, store = train_res["args"], train_res["ctx"], train_res["store"]
    stop = threading.Event()
    pipe = make_pipeline(args, store, ctx, stop)
    pipe.start()
    state = ctx.init_state(train_res["model"].variables["params"])
    window = {}

    # snapshot the cumulative stage counters exactly at the timed window's
    # edges, so warm-up assembly never lands in the breakdown
    n, wait_s, dt = _timed_pipeline_train(
        pipe, ctx, state, duration,
        on_timed_start=lambda: window.update(t0=pipe.stats()),
        on_timed_end=lambda: window.update(t1=pipe.stats()),
    )
    stop.set()
    pipe.stop()
    s0, s1 = window.get("t0", {}), window.get("t1", {})
    from handyrl_tpu.runtime.trainer import PIPE_STAT_KEYS

    stages = {
        key: round(s1.get(key, 0.0) - s0.get(key, 0.0), 4)
        for key in PIPE_STAT_KEYS
    }
    gets = s1.get("gets", 0.0) - s0.get("gets", 0.0)
    stages["device_queue_depth"] = round(
        (s1.get("device_queue_depth_sum", 0.0)
         - s0.get("device_queue_depth_sum", 0.0)) / gets, 3
    ) if gets else None
    stages["mode"] = s1.get("mode")
    return {
        "updates_per_sec": n / dt,
        "trained_env_steps_per_sec": n * args["batch_size"] * args["forward_steps"] / dt,
        "input_wait_frac": wait_s / dt,
        "stages": stages,
    }


def _pipeline_scaling_bench(train_res, duration: float):
    """northstar4: the host-pipeline scaling curve + the host-bypass path,
    side by side over ONE episode store (ROADMAP item 3).

    BENCH_r05 measured the chip eating 376 direct updates/s while the
    host-fed pipeline delivered 3.0 — and the shm plane had never been
    shown to scale past one child.  This stage measures exactly that:
    the shm plane at num_batchers 1/2/4 (updates/s, input_wait_frac,
    per-stage breakdown each), then ``batch_pipeline: device`` — episodes
    uploaded once into device rings, windows assembled on device
    (runtime/device_batch.py) — and evaluates every point against the
    direct updates/s from geese-train (target: host-fed >= 50% of direct
    with input_wait_frac < 0.05).
    """
    from handyrl_tpu.runtime.trainer import PIPE_STAT_KEYS, make_pipeline

    args, ctx, store = train_res["args"], train_res["ctx"], train_res["store"]
    params = train_res["model"].variables["params"]
    per_point = max(2.0, duration / 2)

    def timed_point(cfg_over):
        cfg = dict(args, **cfg_over)
        stop = threading.Event()
        pipe = make_pipeline(cfg, store, ctx, stop)
        pipe.start()
        state = ctx.init_state(params)
        window = {}
        n, wait_s, dt = _timed_pipeline_train(
            pipe, ctx, state, per_point,
            on_timed_start=lambda: window.update(t0=pipe.stats()),
            on_timed_end=lambda: window.update(t1=pipe.stats()),
        )
        stop.set()
        pipe.stop()
        s0, s1 = window.get("t0", {}), window.get("t1", {})
        return {
            "updates_per_sec": n / dt,
            "input_wait_frac": wait_s / dt,
            "mode": s1.get("mode"),
            "stages": {
                key: round(s1.get(key, 0.0) - s0.get(key, 0.0), 4)
                for key in PIPE_STAT_KEYS
            },
        }

    points = {}
    for nb in (1, 2, 4):
        _note(f"northstar4: shm plane, num_batchers={nb}")
        points[f"host_b{nb}"] = timed_point(
            {"batch_pipeline": "shm", "num_batchers": nb}
        )
    # stage geometry sized to the STORE: a chunk flushes only when every
    # lane has chunk steps queued, so on a small static store the default
    # lanes x chunk would never become sampleable and batch() would wait
    # forever (host-generated geese episodes run ~5 steps, not hundreds)
    total_steps = sum(int(ep["steps"]) for ep in store.snapshot())
    dp = ctx.mesh.shape.get("dp", 1)
    # a chunk is INGEST granularity, not window length — windows span
    # chunks, so it only needs to leave half the store flushable
    chunk = max(1, min(64, total_steps // (2 * dp)))
    _note(f"northstar4: host-bypass device stage ({dp} lanes x chunk {chunk})")
    points["device"] = timed_point({
        "batch_pipeline": "device",
        "device_stage_lanes": dp,
        "device_stage_chunk": chunk,
        "device_stage_slots": max(
            int(args.get("device_stage_slots", 1024)), 2 * chunk
        ),
    })

    direct = train_res["updates_per_sec"]
    best_host = max(
        (k for k in points if k.startswith("host_")),
        key=lambda k: points[k]["updates_per_sec"],
    )

    def target_met(p):
        return bool(
            direct
            and p["updates_per_sec"] >= 0.5 * direct
            and p["input_wait_frac"] < 0.05
        )

    return {
        "points": points,
        "direct_updates_per_sec": direct,
        "best_host": best_host,
        "best_host_vs_direct": points[best_host]["updates_per_sec"] / direct
        if direct else None,
        "device_vs_direct": points["device"]["updates_per_sec"] / direct
        if direct else None,
        "host_target_met": target_met(points[best_host]),
        "device_target_met": target_met(points["device"]),
    }


def _device_selfplay_bench(duration: float):
    """Fully on-device self-play (runtime/device_rollout.py): env stepping
    + inference + sampling in ONE jit call over thousands of parallel
    games (2048 on TPU, 512 on CPU) — the actor plane with zero host
    round-trips."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.envs.vector_tictactoe import VectorTicTacToe
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.device_rollout import build_selfplay_fn

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    params = init_variables(module, env)["params"]
    # 2048 parallel games on TPU (512 on CPU): per-dispatch work is what
    # amortizes the tunnel RTT, and the whole vectorized board state is
    # tiny next to HBM
    n_games = 2048 if jax.default_backend() == "tpu" else 512
    fn = build_selfplay_fn(VectorTicTacToe, module, n_games)

    holder = {"key": jax.random.PRNGKey(0)}

    def call():
        holder["key"], sub = jax.random.split(holder["key"])
        cols = fn(params, sub)
        holder["last"] = cols
        return cols["alive"]

    calls_per_sec = _timed_loop(call, duration)
    alive_per_call = float(jax.device_get(holder["last"]["alive"]).sum())
    return {
        "env_steps_per_sec": calls_per_sec * alive_per_call,
        "episodes_per_sec": calls_per_sec * n_games,
    }


def _streaming_selfplay_bench(env_name: str, overrides, duration: float,
                              n_lanes: int = 256, k_steps: int = 32):
    """Streaming on-device self-play: persistent lanes with auto-reset,
    env stepping + net inference + sampling in one jit per k_steps block
    (runtime/device_rollout.py:StreamingDeviceRollout).  This is the
    actor plane with zero host round-trips per step; episode assembly
    (compact-record -> columnar) runs inside the timed window, so the
    number is end-to-end."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

    args = _make_args(env_name, overrides)
    env = make_env(args["env"])
    module = env.net()
    params = init_variables(module, env)["params"]
    roll = StreamingDeviceRollout(
        env.vector_env(), module, args, n_lanes=n_lanes, k_steps=k_steps
    )
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    roll.generate(params, sub)  # compile + warm
    steps0, psteps0 = roll.game_steps, roll.player_steps
    n_eps = 0
    t0 = time.perf_counter()
    # adaptive window: stretch (up to 4x) until at least one episode has
    # completed, so episodes/sec is a measurement, not a silent 0.0 on a
    # slow backend; if even that fails, report null with the reason
    while True:
        dt = time.perf_counter() - t0
        if dt >= duration and (n_eps > 0 or dt >= 4 * duration):
            break
        key, sub = jax.random.split(key)
        n_eps += len(roll.generate(params, sub))
    dt = time.perf_counter() - t0  # before drain: the drained block's steps
    roll.drain()                   # are never counted, so its runtime must
    return {                       # not land in the denominator either
        "env_steps_per_sec": (roll.game_steps - steps0) / dt,
        "player_steps_per_sec": (roll.player_steps - psteps0) / dt,
        "episodes_per_sec": n_eps / dt if n_eps else None,
        "episodes_note": None if n_eps else f"no episode completed in {dt:.0f}s window",
        "lanes": n_lanes,
        "k_steps": k_steps,
    }


def _concurrent_northstar_bench(train_res, duration: float,
                                n_lanes: int = 256, k_steps: int = 32):
    """The north-star loop on ONE chip: streaming on-device self-play
    FEEDING the replay store while the learner trains from it concurrently
    — the architecture that replaces the reference's host worker tree
    (worker.py:110-189).  Captures both planes' rates plus learner input
    starvation; BASELINE.json's target is 100k env-steps/s on a v4-32
    with the learner never starved, i.e. ~3,125 env-steps/s per chip."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.runtime import EpisodeStore
    from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout
    from handyrl_tpu.runtime.trainer import make_pipeline

    args, ctx, module = train_res["args"], train_res["ctx"], train_res["module"]
    env = make_env(args["env"])
    params = train_res["model"].variables["params"]
    if jax.default_backend() != "tpu":
        # fewer lanes so the ~200-step geese episodes start completing
        # within the prefill budget on a slow backend
        n_lanes = min(n_lanes, 32)
    roll = StreamingDeviceRollout(
        env.vector_env(), module, args, n_lanes=n_lanes, k_steps=k_steps,
        mesh=ctx.mesh,
    )
    store = EpisodeStore(8192)
    stop = threading.Event()
    holder = {"key": jax.random.PRNGKey(1), "rollout_error": None}

    def rollout_step():
        holder["key"], sub = jax.random.split(holder["key"])
        eps = roll.generate(params, sub)
        if eps:
            store.extend(eps)

    def rollout_loop():
        try:
            while not stop.is_set():
                rollout_step()
        except Exception:
            holder["rollout_error"] = traceback.format_exc(limit=3)
        finally:
            roll.drain()

    # pre-fill OUTSIDE the timed window so the pipeline can sample at once
    _note(f"northstar: prefilling store via streaming self-play ({n_lanes} lanes)")
    t_fill = time.perf_counter()
    while len(store) < 2 * n_lanes and time.perf_counter() - t_fill < 10 * duration:
        rollout_step()
    if len(store) == 0:
        roll.drain()
        return {
            "skipped": (
                f"no episode completed in the {time.perf_counter() - t_fill:.0f}s "
                f"prefill budget ({n_lanes} lanes)"
            )
        }

    pipe_stop = threading.Event()
    pipe = make_pipeline(args, store, ctx, pipe_stop)
    pipe.start()
    state = ctx.init_state(params)

    _note(f"northstar: {len(store)} episodes staged; timing concurrent train+selfplay")
    thread = threading.Thread(target=rollout_loop, daemon=True)
    counters = {"steps0": 0, "steps1": 0}

    def launch_producer():
        counters["steps0"] = roll.game_steps
        thread.start()

    def snapshot_producer():
        # inside the window only: blocks the producer retires after the
        # clock stops must not inflate the rate
        counters["steps1"] = roll.game_steps

    n, wait_s, dt = _timed_pipeline_train(
        pipe, ctx, state, duration,
        on_timed_start=launch_producer, on_timed_end=snapshot_producer,
    )
    stop.set()
    pipe_stop.set()
    pipe.stop()
    thread.join(timeout=120.0)
    selfplay_rate = (counters["steps1"] - counters["steps0"]) / dt
    # the lanes shard over the mesh: the aggregate rate divides over every
    # participating device before comparison against the 3,125/chip target
    n_chips = ctx.mesh.size
    out = {
        "trained_env_steps_per_sec": n * args["batch_size"] * args["forward_steps"] / dt,
        "selfplay_env_steps_per_sec": selfplay_rate,
        "input_wait_frac": wait_s / dt,
        "episodes_in_store": len(store),
        "per_chip_northstar_frac": selfplay_rate / (3125.0 * n_chips),
    }
    if holder["rollout_error"]:
        out["rollout_error"] = holder["rollout_error"]
    return out


def _device_replay_northstar_bench(train_res, duration: float,
                                   n_lanes: int = 128, k_steps: int = 32,
                                   fused_steps: int = 8,
                                   trains_per_rollout: int = 16):
    """The north-star loop with the DEVICE-RESIDENT replay
    (runtime/device_replay.py): streaming self-play records are ingested
    into on-device ring buffers and training batches are sampled,
    assembled, and stepped in one dispatch — the data path never touches
    the host (VERDICT r2 item 2 follow-up: the v1 loop was bounded by a
    ~43 MB obs upload per update plus every episode round-tripping
    device->host->device).  One iteration = 1 rollout call (k_steps x
    n_lanes game steps) + ``trains_per_rollout`` fused train calls
    (each fused_steps updates), self-play always running under the
    LATEST params.  The train:rollout call ratio sets the chip's duty
    split.  Defaults are the round-4 sweep's best point
    (tools/tune_northstar.py on the v5e, 2026-08-01: 128 lanes x k=32,
    fused 8 x trains 16 -> 176,867 trained steps/s vs 90,683 at the old
    256/2 geometry).  The sweep also settled WHY rollout_time_frac
    cannot reach <= 0.5 here: one self-play env-step costs ~100x one
    trained env-step in device time (sequential small-batch stepping vs
    big batched matmuls), so every geometry stays production-bound —
    raising trains_per_rollout buys trained throughput by re-sampling
    ring windows (produce_consume 0.016 at the tuned point = each
    sample seen ~60x, an off-policy replay-ratio regime the V-Trace/UPGO
    corrections exist for, cf. the soak passes at produce_consume
    well below 1)."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.runtime.device_replay import DeviceReplay
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn

    args, ctx, module = train_res["args"], train_res["ctx"], train_res["module"]
    env = make_env(args["env"])
    venv = env.vector_env()
    if jax.default_backend() != "tpu":
        n_lanes = min(n_lanes, 32)
        fused_steps = min(fused_steps, 2)  # CPU unrolls the fused scan
    mesh = ctx.mesh
    fn = build_streaming_fn(
        venv, module, n_lanes, k_steps,
        mesh=mesh if mesh.size > 1 else None,
        use_observe_mask=bool(args.get("observation", False)),
    )
    replay = DeviceReplay(venv, module, args, mesh, n_lanes, slots=512)
    state = ctx.init_state(train_res["model"].variables["params"])
    key = jax.random.PRNGKey(11)

    from handyrl_tpu.parallel.mesh import dispatch_serialized

    vstate = venv.init(n_lanes, jax.random.PRNGKey(12))
    hidden = module.initial_state((n_lanes, venv.num_players))

    def rollout():
        nonlocal vstate, hidden, key
        key, sub = jax.random.split(key)
        vstate, hidden, records = dispatch_serialized(
            lambda: fn(state["params"], vstate, hidden, sub), mesh
        )
        return replay.ingest(records)

    _note(f"northstar2: prefilling device rings ({n_lanes} lanes)")
    t_fill = time.perf_counter()
    while time.perf_counter() - t_fill < 10 * duration:
        rollout()
        if replay.eligible_count() >= args["batch_size"]:
            break
    else:
        return {
            "skipped": (
                f"no sampleable window after {time.perf_counter() - t_fill:.0f}s "
                f"of ring prefill ({n_lanes} lanes)"
            )
        }

    train = replay.train_fn(ctx, fused_steps=fused_steps)
    # warm both executables outside the timed window
    state, m = train(state, jax.random.PRNGKey(13), 1e-5)
    jax.block_until_ready(m["total"])

    _note("northstar2: timing the all-on-device loop")
    t0 = time.perf_counter()
    updates = 0
    stats = []
    rollout_s = 0.0
    while True:
        tr = time.perf_counter()
        # the rollout stays ASYNC: no per-iteration host sync on its
        # stats (the old block_until_ready here handicapped this fused
        # baseline vs the split-plane stage) — everything drains once
        # after the window.  rollout_s is therefore time spent IN the
        # dispatch: on CPU dispatch_serialized blocks until ready so the
        # duty split is exact; on TPU it is enqueue time only and the
        # trailing block below folds residual execution into dt.
        stats.append(rollout())
        rollout_s += time.perf_counter() - tr
        for _ in range(trains_per_rollout):
            key, sub = jax.random.split(key)
            state, m = train(state, sub, 1e-5)
            updates += fused_steps
        dt = time.perf_counter() - t0
        if dt >= duration and updates > 0:
            break
    jax.block_until_ready(m["total"])
    jax.block_until_ready(stats[-1]["episodes"])  # drain in-flight rollout work
    dt = time.perf_counter() - t0
    fetched = jax.device_get(stats)
    game_steps = sum(int(s["game_steps"]) for s in fetched)
    episodes = sum(int(s["episodes"]) for s in fetched)
    selfplay_rate = game_steps / dt
    n_chips = mesh.size
    consumed = updates * args["batch_size"] * args["forward_steps"] / dt
    return {
        # EFFECTIVE geometry (post the non-TPU clamps above) — sweep rows
        # must echo what actually ran, not what was requested
        "lanes": n_lanes,
        "k_steps": k_steps,
        "fused_steps": fused_steps,
        "trains_per_rollout": trains_per_rollout,
        "trained_env_steps_per_sec": consumed,
        "updates_per_sec": updates / dt,
        "selfplay_env_steps_per_sec": selfplay_rate,
        "rollout_time_frac": rollout_s / dt,
        "episodes": episodes,
        # >1: self-play produces faster than training consumes (fresh
        # data regime); <1: windows are re-sampled (replay-ratio regime).
        # The r4 sweep showed rollout_time_frac <= 0.5 is unreachable on
        # this loop (rollout env-steps cost ~100x trained env-steps in
        # device time), so the tuned default trades reuse for trained
        # throughput; 1/this ratio is the effective replay ratio.
        "produce_consume_ratio": selfplay_rate / consumed if consumed else None,
        "per_chip_northstar_frac": selfplay_rate / (3125.0 * n_chips),
        "loss_finite": bool(jax.numpy.isfinite(jax.device_get(m["total"]))),
    }


def _split_plane_northstar_bench(train_res, duration: float,
                                 actor_chips: Optional[int] = None,
                                 n_lanes: int = 128, k_steps: int = 32,
                                 fused_steps: int = 8,
                                 param_refresh_updates: int = 8):
    """North-star v3: DISAGGREGATED planes — self-play pinned to an actor
    mesh, training to a disjoint learner mesh, running CONCURRENTLY from
    two host threads under the per-device dispatch locks
    (parallel/mesh.py).  The fused loop (northstar2) is production-bound
    by construction: one self-play env-step costs ~100x one trained
    env-step in device time, so one program queue spends >90% of its time
    in rollout at every geometry (round-4 sweep).  Splitting the chips
    removes the time-slicing: the learner plane's rollout share drops to
    zero and the produce/consume ratio becomes a CHIP-ALLOCATION knob
    (actor_chips) instead of a duty-cycle compromise.

    Three phases: ring prefill, the actor plane STANDALONE (its unshared
    rate — the concurrency yardstick), then both planes concurrent.
    Reports per-plane duty, trained + self-play env-steps/s, the
    concurrent/standalone self-play ratio, realized param lag, and the
    cross-mesh transfer rate.

    Reading selfplay_concurrent_frac: on REAL accelerators every chip has
    its own compute, so ~1.0 means training cost self-play nothing.  On
    the VIRTUAL CPU mesh all devices share the host's physical cores, so
    the ratio measures core contention, not plane contention — there the
    architecture proof is rollout_time_frac = 0 with both planes
    progressing inside one window (the 4-device smoke in
    tests/test_plane.py asserts exactly that)."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.parallel import TrainContext
    from handyrl_tpu.parallel.mesh import dispatch_serialized, split_mesh
    from handyrl_tpu.runtime.device_replay import DeviceReplay
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn
    from handyrl_tpu.runtime.plane import PlaneParamCache, RecordTransfer

    devices = jax.devices()
    if len(devices) < 2:
        return {"skipped": f"plane: split needs >= 2 devices, have {len(devices)}"}
    args, module = train_res["args"], train_res["module"]
    env = make_env(args["env"])
    venv = env.vector_env()
    if actor_chips is None:
        actor_chips = max(1, len(devices) // 2)
    if jax.default_backend() != "tpu":
        n_lanes = min(n_lanes, 32)
        # scan-bodied collectives across VIRTUAL devices run at
        # pathological speed on XLA:CPU (see Trainer's fused_steps guard)
        fused_steps = 1
    learner_mesh, actor_mesh = split_mesh(args.get("mesh"), actor_chips)
    ldp = learner_mesh.shape.get("dp", 1)
    adp = actor_mesh.shape.get("dp", 1)
    import math

    largs = dict(args)
    if largs["batch_size"] % ldp:
        largs["batch_size"] = max(ldp, largs["batch_size"] // ldp * ldp)
    # lanes shard over the actor mesh (rollout) AND the learner mesh
    # (rings): round to a multiple of both dp sizes
    lanes_q = ldp * adp // math.gcd(ldp, adp)
    n_lanes = max(lanes_q, n_lanes // lanes_q * lanes_q)

    ctx = TrainContext(module, largs, learner_mesh)
    params0 = train_res["model"].variables["params"]
    state = ctx.init_state(params0)
    fn = build_streaming_fn(
        venv, module, n_lanes, k_steps, mesh=actor_mesh,
        use_observe_mask=bool(args.get("observation", False)),
    )
    replay = DeviceReplay(venv, module, largs, learner_mesh, n_lanes, slots=512)
    xfer = RecordTransfer(learner_mesh)
    cache = PlaneParamCache(actor_mesh)
    cache.publish(params0, 0)

    key = jax.random.PRNGKey(21)
    vstate = venv.init(n_lanes, jax.random.PRNGKey(22))
    hidden = module.initial_state((n_lanes, venv.num_players))

    def rollout():
        nonlocal vstate, hidden, key
        _, params = cache.latest()
        key, sub = jax.random.split(key)
        vstate, hidden, records = dispatch_serialized(
            lambda: fn(params, vstate, hidden, sub), actor_mesh
        )
        return replay.ingest(xfer(records))

    _note(f"northstar3: prefilling rings ({n_lanes} lanes, "
          f"{len(devices) - actor_chips}+{actor_chips} learner+actor chips)")
    t_fill = time.perf_counter()
    while time.perf_counter() - t_fill < 10 * duration:
        rollout()
        if replay.eligible_count() >= largs["batch_size"]:
            break
    else:
        return {
            "skipped": (
                f"no sampleable window after {time.perf_counter() - t_fill:.0f}s "
                f"of ring prefill ({n_lanes} lanes)"
            )
        }

    train = replay.train_fn(ctx, fused_steps=fused_steps)
    state, m = train(state, jax.random.PRNGKey(23), 1e-5)  # warm the train path
    jax.block_until_ready(m["total"])

    def timed_rollout_window(t_window: float):
        """Drive the actor loop for ~t_window; (game_steps, busy_s, dt)."""
        stats, busy = [], 0.0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < t_window or not stats:
            tb = time.perf_counter()
            stats.append(rollout())
            busy += time.perf_counter() - tb
        jax.block_until_ready(stats[-1]["episodes"])
        dt = time.perf_counter() - t0
        fetched = jax.device_get(stats)
        return sum(int(s["game_steps"]) for s in fetched), busy, dt

    _note("northstar3: actor plane standalone")
    sa_steps, _, sa_dt = timed_rollout_window(duration / 2)
    standalone_rate = sa_steps / sa_dt

    _note("northstar3: timing both planes concurrently")
    stop = threading.Event()
    prod = {"steps": 0, "episodes": 0, "busy_s": 0.0, "lag_sum": 0.0,
            "dispatches": 0, "error": None}
    learner_updates = [0]

    def producer():
        stats, busy, lags = [], [], []
        n_window = 0
        try:
            while not stop.is_set():
                tb = time.perf_counter()
                lags.append(max(0, learner_updates[0] - cache.version))
                stats.append(rollout())
                busy.append(time.perf_counter() - tb)
                if not stop.is_set():  # blocks retired inside the window
                    n_window = len(stats)
        except Exception:
            prod["error"] = traceback.format_exc(limit=3)
        finally:
            if stats:
                jax.block_until_ready(stats[-1]["episodes"])
            # trim EVERY counter to the measurement window, or the frac/
            # lag denominators disagree with the steps they pair with
            # (the final rollout can outlive the learner window on CPU)
            fetched = jax.device_get(stats[:n_window])
            prod["steps"] = sum(int(s["game_steps"]) for s in fetched)
            prod["episodes"] = sum(int(s["episodes"]) for s in fetched)
            prod["busy_s"] = sum(busy[:n_window])
            prod["lag_sum"] = float(sum(lags[:n_window]))
            prod["dispatches"] = n_window

    on_cpu = jax.default_backend() == "cpu"
    thread = threading.Thread(target=producer, daemon=True)
    xfer_bytes0 = xfer.bytes_transferred + cache.bytes_transferred
    updates = 0
    train_s = 0.0
    rollout_s_learner = 0.0  # rollout work on the LEARNER thread: none
    tkey = jax.random.PRNGKey(24)
    thread.start()
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < duration or updates == 0:
        tt = time.perf_counter()
        tkey, sub = jax.random.split(tkey)
        state, m = train(state, sub, 1e-5)
        train_s += time.perf_counter() - tt
        updates += fused_steps
        learner_updates[0] += fused_steps
        if learner_updates[0] - cache.version >= param_refresh_updates:
            cache.publish(state["params"], learner_updates[0])
        if on_cpu:
            # hand the learner-plane locks to the producer's ingest (the
            # same unfair-threading.Lock starvation the trainer's sleep
            # documents); on TPU dispatch is async and the gap never forms
            time.sleep(0.005)
    jax.block_until_ready(m["total"])
    dt = time.perf_counter() - t0
    stop.set()
    thread.join(timeout=120.0)
    if thread.is_alive() and not prod["error"]:
        # counters are only written in the producer's finally block — a
        # wedged rollout dispatch would otherwise report 0 self-play
        # env-steps/s as if it were a real measurement
        prod["error"] = "producer thread still running after 120s join timeout"
    selfplay_rate = prod["steps"] / dt
    consumed = updates * largs["batch_size"] * largs["forward_steps"] / dt
    out = {
        "actor_chips": actor_chips,
        "learner_chips": len(devices) - actor_chips,
        "lanes": n_lanes,
        "k_steps": k_steps,
        "fused_steps": fused_steps,
        "batch_size": largs["batch_size"],
        "param_refresh_updates": param_refresh_updates,
        "trained_env_steps_per_sec": consumed,
        "updates_per_sec": updates / dt,
        "selfplay_env_steps_per_sec": selfplay_rate,
        "selfplay_standalone_env_steps_per_sec": standalone_rate,
        # the concurrency proof: ~1.0 means training cost self-play
        # nothing (true disaggregation); the fused loop's equivalent is
        # its duty split
        "selfplay_concurrent_frac": selfplay_rate / standalone_rate
        if standalone_rate else None,
        # rollout work on the learner plane's program queue: structurally
        # zero — the split design's whole point (vs 0.91 fused, round 4)
        "rollout_time_frac": rollout_s_learner / dt,
        "learner_train_time_frac": train_s / dt,
        "actor_busy_frac": prod["busy_s"] / dt,
        "param_lag_mean": prod["lag_sum"] / max(prod["dispatches"], 1),
        "xfer_bytes_per_sec": (
            xfer.bytes_transferred + cache.bytes_transferred - xfer_bytes0
        ) / dt,
        "produce_consume_ratio": selfplay_rate / consumed if consumed else None,
        "per_chip_northstar_frac": selfplay_rate / (3125.0 * len(devices)),
        "episodes": prod["episodes"],
        "loss_finite": bool(jax.numpy.isfinite(jax.device_get(m["total"]))),
    }
    if prod["error"]:
        out["rollout_error"] = prod["error"]
    return out


# child for the northstar3mp leg: one rank of a 2-process pod-slice run —
# 4 virtual CPU devices carved 2 learner (global collective mesh) + 2
# actor (process-local rollout/rings), the full Learner epoch loop
_NORTHSTAR3MP_CHILD = r"""
import json, os, sys

port, hport, pid, nproc, outdir, epochs = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5], int(sys.argv[6]),
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax

jax.config.update("jax_platforms", "cpu")

from handyrl_tpu.config import normalize_args
from handyrl_tpu.parallel import init_distributed

dist = {
    "coordinator_address": f"127.0.0.1:{port}",
    "num_processes": nproc,
    "process_id": pid,
    "initialization_timeout": 180.0,
    "heartbeat_interval": 1.0,
    "heartbeat_timeout": 60.0,
    "collective_timeout": 300.0,
    "health_port": hport,
}
init_distributed(dist)
train = {
    "plane": "split",
    "actor_chips": 2,
    "param_refresh_updates": 2,
    # both ranks compile concurrently on shared cores: the default 120s
    # stall bound would degrade a healthy run split -> fused mid-leg
    "plane_stall_timeout": 600.0,
    "mesh": {"dp": -1},
    "turn_based_training": False,
    "observation": False,
    "batch_size": 8,
    "forward_steps": 4,
    "burn_in_steps": 0,
    "device_rollout_games": 8,
    "device_replay": True,
    "device_replay_slots": 64,
    "device_replay_k_steps": 16,
    "minimum_episodes": 20,
    "update_episodes": 30,
    "maximum_episodes": 10 ** 6,
    "epochs": epochs,
    "num_batchers": 0,
    "batch_pipeline": "thread",
    "eval_rate": 0.0,
    "worker": {"num_parallel": 1},
    "model_dir": os.path.join(outdir, f"models_{pid}"),
    "metrics_path": os.path.join(outdir, f"metrics_{pid}.jsonl"),
    "distributed": dist,
}
args = normalize_args(
    {"env_args": {"env": "ParallelTicTacToe"}, "train_args": train}
)

from handyrl_tpu.runtime.learner import Learner

code = Learner(args).run()

from handyrl_tpu.parallel.distributed import shutdown_distributed

shutdown_distributed()
sys.exit(code)
"""


def _multiprocess_split_plane_bench(epochs: int = 3):
    """North-star v3, POD-SLICE leg (northstar3mp): the same split-plane
    loop as northstar3 but across TWO real OS processes under
    jax.distributed — each rank carves its 4 virtual CPU devices 2+2
    (global collective learner mesh over DCN + process-local actor plane)
    and the per-rank shards meet the collective train step through the
    make_array_from_process_local_data seam.

    Subprocess-based and CPU-forced BY DESIGN: two processes cannot share
    one accelerator, and this leg measures the pod-slice topology's
    mechanics (collective stepping under per-rank device planes, cadence
    agreement, the plane duty/transfer keys) rather than chip throughput
    — the single-process northstar3 stage owns that number.  The
    acceptance is concurrency: some coordinator epoch must show BOTH
    planes' rates nonzero in the same window."""
    import socket
    import subprocess
    import sys
    import tempfile

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    batch_size, forward_steps = 8, 4  # mirrors _NORTHSTAR3MP_CHILD
    with tempfile.TemporaryDirectory(prefix="ns3mp_") as outdir:
        port, hport = free_port(), free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        _note(f"northstar3mp: spawning 2 learner ranks ({epochs} epochs)")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _NORTHSTAR3MP_CHILD, str(port),
                 str(hport), str(pid), "2", outdir, str(epochs)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
            for pid in range(2)
        ]
        try:
            outs = [
                p.communicate(timeout=900)[0].decode(errors="replace")
                for p in procs
            ]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            return {"skipped": "northstar3mp children timed out after 900s"}
        if any(p.returncode != 0 for p in procs):
            return {"skipped": "northstar3mp child failed: rc=%s\n%s" % (
                [p.returncode for p in procs],
                "".join(o[-2000:] for o in outs),
            )}
        records = [
            json.loads(l)
            for l in open(os.path.join(outdir, "metrics_0.jsonl"))
            if l.strip()
        ]
    epoch_rows = [r for r in records if "plane_actor_busy_frac" in r]
    if not epoch_rows:
        return {"skipped": "no plane_* epoch rows in coordinator metrics"}
    both = [
        r for r in epoch_rows
        if r.get("updates_per_sec", 0) > 0 and r.get("episodes_per_sec", 0) > 0
    ]
    best = max(epoch_rows, key=lambda r: r.get("updates_per_sec", 0))
    return {
        "processes": 2,
        "epochs": len(epoch_rows),
        "updates_per_sec": best.get("updates_per_sec", 0.0),
        "trained_env_steps_per_sec": (
            best.get("updates_per_sec", 0.0) * batch_size * forward_steps
        ),
        "episodes_per_sec": best.get("episodes_per_sec", 0.0),
        "actor_busy_frac": max(r["plane_actor_busy_frac"] for r in epoch_rows),
        "xfer_bytes_per_sec": max(
            r.get("plane_xfer_bytes_per_sec", 0.0) for r in epoch_rows
        ),
        "both_planes_concurrent": bool(both),
        "dist_processes": records[-1].get("dist_processes"),
    }


def _geister_device_replay_bench(duration: float):
    """Turn-mode device-resident replay (runtime/device_replay.py turn
    mode): Geister's DRC ConvLSTM trained straight from device rings —
    all-player windows with 4 real burn-in rows + UPGO — concurrent with
    turn-based streaming self-play, same loop shape as northstar2.  The
    on-chip soak this measures the steady state of trained wp 0.519->0.694
    vs random in ~10 min (BASELINE.md)."""
    from types import SimpleNamespace

    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel import TrainContext, make_mesh

    args = _make_args(
        "Geister",
        {"turn_based_training": True, "observation": True,
         "batch_size": 16, "forward_steps": 8, "burn_in_steps": 4,
         "policy_target": "UPGO", "value_target": "UPGO"},
    )
    n_devices = len(jax.devices())
    if args["batch_size"] % n_devices:  # same guard as _train_bench
        args["batch_size"] = max(n_devices, args["batch_size"] // n_devices * n_devices)
    env = make_env(args["env"])
    module = env.net()
    ctx = TrainContext(module, args, make_mesh(args["mesh"]))
    train_res = {"args": args, "ctx": ctx, "module": module,
                 "model": SimpleNamespace(variables=init_variables(module, env))}
    # trains_per_rollout pinned at the r3 value: the tuned default (16) is
    # a HungryGeese-sweep result; Geister's recurrent rows must stay
    # comparable with the recorded r3/r4 captures (80.1 / 79.1 updates/s)
    return _device_replay_northstar_bench(
        train_res, duration, n_lanes=64, k_steps=32, fused_steps=4,
        trains_per_rollout=2,
    )


def _flash_attention_bench(duration: float = 3.0):
    """Masked Pallas flash kernel vs exact einsum on the transformer
    seq-mode semantics (fwd+bwd), at a long-window shape where the O(T^2)
    score tensor starts to matter.  Records the speedup that justifies
    seq_attention='auto' dispatching to the kernel on TPU."""
    import jax
    import jax.numpy as jnp

    from handyrl_tpu.ops.flash_attention import (
        masked_attention_reference,
        masked_flash_attention,
    )

    B, T, H, D = 8, 1024, 4, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, T, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, T, H, D), jnp.float32)
    key_mask = jnp.ones((B, T), jnp.float32)
    slopes = 2.0 ** (-jnp.arange(1, H + 1, dtype=jnp.float32))

    def timed(fn):
        # grad wrt q, k AND v — in training all three come from trained
        # params, so the dk/dv backward path must be in the timing
        loss = jax.jit(
            jax.grad(
                lambda q, k, v: (fn(q, k, v, key_mask, slopes) ** 2).sum(),
                argnums=(0, 1, 2),
            )
        )
        return 1000.0 / _timed_loop(lambda: loss(q, k, v), duration)  # ms/call

    flash_ms = timed(masked_flash_attention)
    einsum_ms = timed(masked_attention_reference)
    return {
        "shape": f"B{B} T{T} H{H} D{D}",
        "flash_ms": round(flash_ms, 2),
        "einsum_ms": round(einsum_ms, 2),
        "speedup": round(einsum_ms / flash_ms, 2),
    }


# ---------------------------------------------------------------------------
# transformer_long: the long-context train step at production shapes
# (ROADMAP item 5) — T x attention-mode sweep + an sp=2 ring leg
# ---------------------------------------------------------------------------

# module-level pins so CI can trace/exercise the exact sweep geometry
# (same contract as TRANSFORMER_TPU_NET_ARGS below).  TPU: the d1536 knee
# shape from the 2026-08-02 width sweep, batch shrinking with T so the
# remat ladder (auto -> 'block' at T >= 512) is what fits T1024 in HBM,
# not a vanishing batch.  CPU: tiny shapes through the IDENTICAL code
# path — interpret-mode Pallas for the flash points, flash_min_t lowered
# so the 'auto' points exercise both sides of the crossover.
TRANSFORMER_LONG_TPU = {
    "net_args": {"d_model": 1536, "n_heads": 16, "n_layers": 8,
                 "memory_len": 32},
    "sweep_t": (64, 512, 1024),
    "batch_by_t": {64: 64, 512: 16, 1024: 8},
    "flash_min_t": 128,
    "compute_dtype": "bfloat16",
    "sp_t": 512,
    "sp_batch": 16,
}
TRANSFORMER_LONG_CPU = {
    "net_args": {"d_model": 64, "n_heads": 2, "n_layers": 2,
                 "memory_len": 16},
    "sweep_t": (8, 16, 32),
    "batch_by_t": {8: 8, 16: 8, 32: 8},
    "flash_min_t": 16,
    "compute_dtype": "float32",
    "sp_t": 16,
    "sp_batch": 8,
}
TRANSFORMER_LONG_MFU_TARGET = 0.40


def _compiled_peak_bytes(ctx, state, batch):
    """Peak on-device bytes of the bound train step, from XLA's compiled
    memory analysis (temp + arguments + outputs).  AOT-compiles the same
    program a second time, so callers only invoke it where that is cheap
    (CPU) or worth a few minutes (the longest-T points of a real-TPU
    capture, where the remat ladder's HBM story is the point)."""
    import jax
    import jax.numpy as jnp

    try:
        lowered = ctx._bind(state).lower(
            state, batch, jax.ShapeDtypeStruct((), jnp.float32)
        )
        ma = lowered.compile().memory_analysis()
        if ma is None:
            return None
        total = 0
        for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes"):
            total += int(getattr(ma, attr, 0) or 0)
        return total or None
    except Exception:
        return None


def _transformer_long_bench(duration: float, n_dev: int, peak):
    """One training semantics from T64 on one chip to T1024 across an sp
    mesh: sweep T x seq_attention {einsum, flash, auto} through the SAME
    TrainContext path as every other stage (real Geister windows, real
    losses, Adam), plus a dp x sp ring-attention leg — each point
    reporting updates/s, tokens/s, MFU and (where measured) peak device
    bytes, judged against transformer_long_mfu >= 0.40.

    The remat ladder rides along as 'auto' (resolve_seq_remat: 'block' at
    T >= 512 on TPU), and the remat-none memory headroom at the longest T
    is recorded from an AOT compile of the same program — the
    OOM-by-construction comparison that motivated the ladder."""
    import jax
    import jax.numpy as jnp

    from handyrl_tpu.parallel import resolve_seq_attention, resolve_seq_remat
    from handyrl_tpu.parallel.train_step import TrainContext
    from handyrl_tpu.parallel.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    pins = TRANSFORMER_LONG_TPU if on_tpu else TRANSFORMER_LONG_CPU
    env_over = {"net": "transformer", "net_args": pins["net_args"]}
    modes = ("einsum", "flash", "auto")
    per_point = max(1.5, duration / (len(pins["sweep_t"]) * len(modes) + 1))

    def overrides(T, mode, B):
        return {
            "batch_size": B, "burn_in_steps": 0, "forward_steps": T,
            "observation": True, "seq_attention": mode,
            "flash_min_t": pins["flash_min_t"],
            "compute_dtype": pins["compute_dtype"], "remat": "auto",
        }

    points = {}
    reuse = None
    mem_tr = None  # the longest-T point, kept for the memory comparison
    for T in pins["sweep_t"]:
        for mode in modes:
            B = pins["batch_by_t"][T]
            _note(f"transformer_long: T{T} {mode} B{B}")
            tr = _train_bench(
                "Geister", overrides(T, mode, B), per_point, n_dev,
                fill_episodes=8, reuse=reuse, env_overrides=env_over,
            )
            reuse = reuse or tr
            args = tr["args"]
            ups = tr["updates_per_sec"]
            tokens = args["batch_size"] * 2 * T  # 2 players per window row
            points[f"T{T}_{mode}"] = {
                "updates_per_sec": ups,
                "tokens_per_sec": ups * tokens,
                "attn": resolve_seq_attention(args, T),
                "remat": resolve_seq_remat(args, T),
                "mfu": (tr["flops_per_step"] * ups / (peak * n_dev))
                if tr["flops_per_step"] and peak else None,
                "peak_bytes": None,
            }
            if T == pins["sweep_t"][-1] and mode == "auto":
                mem_tr = tr
    # peak-memory story at the longest T: the remat-'block' program vs a
    # remat-'none' AOT compile of the SAME step (never executed — at
    # production shapes remat: none is the configuration that OOMs, the
    # d2048 width-sweep collapse)
    remat_headroom = None
    if mem_tr is not None:
        T_max = pins["sweep_t"][-1]
        args = mem_tr["args"]
        try:
            batch_host = _sample_batch(mem_tr["store"], args)
            mems = {}
            for rung in ("block", "none"):
                ctx = TrainContext(
                    mem_tr["module"], dict(args, remat=rung),
                    make_mesh(args["mesh"]),
                )
                state = ctx.init_state(mem_tr["model"].variables["params"])
                mems[rung] = _compiled_peak_bytes(
                    ctx, state, ctx.put_batch(batch_host)
                )
            # the point's peak_bytes must describe the program it MEASURED
            # (auto resolves 'none' on CPU, 'block' on TPU at long T); the
            # block-vs-none pair rides separately as remat_headroom
            measured_rung = points[f"T{T_max}_auto"]["remat"]
            points[f"T{T_max}_auto"]["peak_bytes"] = mems.get(measured_rung)
            if mems["block"] and mems["none"]:
                remat_headroom = {
                    "block": mems["block"], "none": mems["none"],
                    "ratio": round(mems["none"] / mems["block"], 3),
                }
        except Exception:
            _note("transformer_long: peak-memory comparison unavailable "
                  f"({traceback.format_exc(limit=1).splitlines()[-1]})")

    # sp=2 ring leg: the same train step with T sharded over an sp mesh
    sp_leg = None
    sp_note = None
    if n_dev >= 2:
        dp = max(n_dev // 2, 1)
        T, B = pins["sp_t"], pins["sp_batch"]
        B = max(dp, B // dp * dp)
        _note(f"transformer_long: sp=2 ring leg (dp{dp} x sp2, T{T} B{B})")
        tr = _train_bench(
            "Geister",
            dict(overrides(T, "ring", B), mesh={"dp": dp, "sp": 2}),
            per_point, dp, fill_episodes=8, reuse=reuse,
            env_overrides=env_over,
        )
        ups = tr["updates_per_sec"]
        sp_leg = {
            "updates_per_sec": ups,
            "tokens_per_sec": ups * tr["args"]["batch_size"] * 2 * T,
            "attn": "ring",
            "mfu": (tr["flops_per_step"] * ups / (peak * n_dev))
            if tr["flops_per_step"] and peak else None,
        }
    else:
        sp_note = "single device: no sp axis to shard over"

    mfus = [p["mfu"] for p in points.values() if p.get("mfu")]
    best = max(mfus) if mfus else None
    return {
        "points": points,
        "sp2": sp_leg,
        "sp2_note": sp_note,
        "remat_headroom": remat_headroom,
        "mfu": best,
        # judged on real-TPU captures; None (not false) where MFU cannot
        # be computed, so a CPU smoke never reads as a missed target
        "target_met": (best >= TRANSFORMER_LONG_MFU_TARGET)
        if best is not None and on_tpu else None,
    }


# the transformer stage's on-chip shape (module-level so CI can trace the
# EXACT program the driver bench will compile on the TPU — the stage is
# TPU-gated, so without that trace a shape bug would first surface
# mid-capture; tests/test_transformer.py::test_bench_tpu_transformer_config_traces)
# width sweep 2026-08-02 (all einsum, B64/T64): d1024 0.494, d1024/L16
# 0.489 (depth flat), d1536 0.597, d2048 0.185 (HBM pressure — remat/
# spill collapse at 20 TFLOP/step), d1024/B128 0.45 (batch flat).
# Width is the MFU lever until memory pressure bites; d1536 is the knee.
TRANSFORMER_TPU_NET_ARGS = {"d_model": 1536, "n_heads": 16, "n_layers": 8,
                            "memory_len": 32}
TRANSFORMER_TPU_OVERRIDES = {"batch_size": 64, "burn_in_steps": 2,
                             "forward_steps": 62, "observation": True,
                             "compute_dtype": "bfloat16",
                             # einsum at T64: settled on-chip at d1024
                             # (2026-08-02: einsum 18.6 updates/s / MFU
                             # 0.48 vs flash 13.5 / 0.347 — the O(T^2)
                             # term is tiny and XLA-fusable at T64 while
                             # the kernel pays fixed launch overhead), and
                             # the d1536 evidence so far agrees (einsum
                             # MFU 0.597 via tools/tune_transformer.py).
                             # The d1536 crossover now has a DEDICATED
                             # measurement: the transformer_long stage
                             # sweeps T {64, 512, 1024} x {einsum, flash,
                             # auto} at exactly this width — run
                             # BENCH_STAGES=transformer_long on the next
                             # lease and re-pin from its T64 row if flash
                             # ever wins there.  'auto' (flash_min_t 128)
                             # picks einsum at T64 regardless; pinned
                             # explicitly so the stage measures one known
                             # program
                             "seq_attention": "einsum"}

# ---------------------------------------------------------------------------
# serving: the standalone inference serving plane under load (ROADMAP item 2)
# ---------------------------------------------------------------------------

# load-generator geometry (per phase; durations scale with T_TRAIN/QUICK)
SERVING_CLIENTS = 4 if QUICK else 8        # closed-loop connections
SERVING_WINDOW = 8                          # outstanding requests per conn
SERVING_SHED_SLO_MS = 25.0                  # tight budget for the shed legs


def _serving_bench(duration: float):
    """Latency-SLO bench of the serving plane (handyrl_tpu/serving) over
    the REAL framed-socket transport: closed-loop saturation QPS with
    client-measured p50/p99, shed rate at two offered loads against a
    tight SLO (shed-fast must engage under overload and stay quiet under
    it), and a hot-swap leg measuring time-to-first-response on the new
    model with a zero-drop count — the zero-downtime contract measured,
    not asserted."""
    import threading as _threading

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.serving import (
        ModelRouter, ServingClient, ServingError, ServingServer,
    )
    from handyrl_tpu.serving.batcher import percentiles_ms

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    env.reset()
    obs = env.observation(0)
    p1 = init_variables(module, env, seed=1)["params"]
    p2 = init_variables(module, env, seed=2)["params"]

    base_cfg = {
        "port": 0, "max_models": 4, "slo_ms": 1000.0, "shed_policy": "none",
        "max_batch": 64, "max_wait_ms": 1.0,
        # every power-of-two bucket pre-warmed: real traffic reaches them
        # all, and a hot-path compile would both spike p99 and (pre-warm)
        # distort the admission EMA's first samples
        "warm_buckets": [1, 2, 4, 8, 16, 32, 64],
        "queue_bound": 8192, "recv_timeout": 0.0, "watch_interval": 0.0,
        "stats_interval": 0.0,
    }

    def start_server(**overrides):
        cfg = dict(base_cfg, **overrides)
        router = ModelRouter(module, obs, cfg, model_dir=".")
        router.publish(1, p1)
        return router, ServingServer(router, cfg).run()

    def closed_loop(port, dur, lat, counts, models=None, stop=None):
        """One connection keeping SERVING_WINDOW requests outstanding."""
        client = ServingClient("127.0.0.1", port)
        inflight = []
        end = time.perf_counter() + dur
        try:
            while time.perf_counter() < end and not (stop and stop.is_set()):
                while len(inflight) < SERVING_WINDOW:
                    inflight.append((time.perf_counter(), client.submit(obs)))
                t0, fut = inflight.pop(0)
                try:
                    reply = fut.result(timeout=120)
                    lat.append((time.perf_counter() - t0) * 1000.0)
                    counts["ok"] += 1
                    if models is not None:
                        models.append((time.perf_counter(), reply["model"]))
                except Exception:
                    counts["err"] += 1
            for _t0, fut in inflight:
                try:
                    fut.result(timeout=120)
                    counts["ok"] += 1
                except Exception:
                    counts["err"] += 1
        finally:
            client.close()

    out = {"clients": SERVING_CLIENTS, "window": SERVING_WINDOW}

    # -- phase 1: closed-loop saturation + latency percentiles ------------
    router, server = start_server()
    lats = [[] for _ in range(SERVING_CLIENTS)]
    counts = [dict(ok=0, err=0) for _ in range(SERVING_CLIENTS)]
    threads = [
        _threading.Thread(target=closed_loop,
                          args=(server.bound_port, duration, lats[i], counts[i]),
                          daemon=True)
        for i in range(SERVING_CLIENTS)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    total_ok = sum(c["ok"] for c in counts)
    all_lat = [x for l in lats for x in l]
    pct = percentiles_ms(all_lat)
    out["saturation_qps"] = total_ok / max(elapsed, 1e-6)
    out["p50_ms"] = pct[50]
    out["p99_ms"] = pct[99]
    out["requests"] = total_ok
    out["load_errors"] = sum(c["err"] for c in counts)

    # -- phase 3 (same server, still warm): hot-swap under load -----------
    stop = _threading.Event()
    swap_models = [[] for _ in range(max(2, SERVING_CLIENTS // 2))]
    swap_counts = [dict(ok=0, err=0) for _ in swap_models]
    threads = [
        _threading.Thread(target=closed_loop,
                          args=(server.bound_port, 120.0, [], swap_counts[i],
                                swap_models[i], stop),
                          daemon=True)
        for i in range(len(swap_models))
    ]
    for t in threads:
        t.start()
    time.sleep(min(1.0, duration / 4))
    admin = ServingClient("127.0.0.1", server.bound_port)
    t_swap = time.perf_counter()
    swap = admin.swap(2, params=p2)
    time.sleep(min(1.0, duration / 4))
    stop.set()
    for t in threads:
        t.join(60)
    admin.close()
    events = sorted(e for l in swap_models for e in l)
    new_times = [t for t, m in events if m == 2]
    seen = {m for _, m in events}
    out["swap_warm_ms"] = swap["warm_ms"]
    out["swap_ttfr_ms"] = (
        (new_times[0] - t_swap) * 1000.0 if new_times else None
    )
    out["swap_dropped"] = sum(c["err"] for c in swap_counts)
    out["swap_flip_observed"] = seen == {1, 2}
    server.shutdown()

    # -- phase 2: shed rate vs offered load (fresh server, tight SLO) -----
    def open_loop(port, rate, dur, counters):
        """Paced open-loop offered load over several connections (one
        socket serializing the whole rate would throttle the offer);
        callbacks sort the outcomes."""
        clients = [
            ServingClient("127.0.0.1", port)
            for _ in range(max(2, SERVING_CLIENTS // 2))
        ]
        lock = _threading.Lock()
        pending = [0]

        def cb(fut):
            try:
                fut.result()
                kind = "ok"
            except ServingError as exc:
                kind = "shed" if exc.kind in ("shed", "deadline") else "err"
            except Exception:
                kind = "err"
            with lock:
                counters[kind] = counters.get(kind, 0) + 1
                pending[0] -= 1

        start = time.perf_counter()
        sent = 0
        try:
            while time.perf_counter() - start < dur:
                due = int((time.perf_counter() - start) * rate) - sent
                for _ in range(min(max(due, 0), 512)):
                    with lock:
                        pending[0] += 1
                    clients[sent % len(clients)].submit(
                        obs, slo_ms=SERVING_SHED_SLO_MS
                    ).add_done_callback(cb)
                    sent += 1
                time.sleep(0.002)
            counters["offered"] = sent
            deadline = time.perf_counter() + 60.0
            while time.perf_counter() < deadline:
                with lock:
                    if pending[0] == 0:
                        break
                time.sleep(0.005)
        finally:
            for client in clients:
                client.close()

    sat = max(out["saturation_qps"], 1.0)
    router, server = start_server(shed_policy="deadline",
                                  slo_ms=SERVING_SHED_SLO_MS)
    for tag, rate in (("low", 0.25 * sat), ("high", 2.0 * sat)):
        counters: dict = {}
        open_loop(server.bound_port, rate, duration / 2, counters)
        offered = max(counters.get("offered", 0), 1)
        shed = counters.get("shed", 0)
        out[f"offered_{tag}_qps"] = counters.get("offered", 0) / (duration / 2)
        out[f"shed_rate_{tag}"] = shed / offered
        out[f"errors_{tag}"] = counters.get("err", 0)
    server.shutdown()
    return out


# ---------------------------------------------------------------------------
# fleet: the serving tier behind one router front (docs/serving.md §Fleet)
# ---------------------------------------------------------------------------

# stateful load geometry: each connection keeps one request outstanding
# per open session (the honest shape of recurrent traffic — a session's
# steps are serial by definition; concurrency comes from session count)
FLEET_CLIENTS = 4 if QUICK else 6
FLEET_SESSIONS = 8                    # sessions (and window) per connection
FLEET_RATIO_STEPS = 16                # serial steps for the wire-bytes legs


def _fleet_replica_main(pipe, env_name, seed, cfg):
    """Spawn-context entry for one bench replica: a full serving plane in
    its OWN process (the scaling leg measures tier throughput — replicas
    sharing the parent's interpreter would share its GIL and measure
    nothing).  Reports the bound port over the pipe, then blocks until
    the parent sends anything (kill-safe: daemon + terminate backstop)."""
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.serving import ModelRouter, ServingServer

    env = make_env({"env": env_name})
    module = env.net()
    env.reset()
    obs = env.observation(env.players()[0])
    # seeded init: every replica builds IDENTICAL params, so balanced /
    # re-routed traffic is bit-comparable without shipping weights around
    params = init_variables(module, env, seed=seed)["params"]
    router = ModelRouter(module, obs, cfg, model_dir=".")
    router.publish(1, params)
    server = ServingServer(router, cfg).run()
    pipe.send(server.bound_port)
    try:
        pipe.recv()
    except EOFError:
        pass
    server.shutdown()


def _fleet_router_main(pipe, fleet_cfg):
    """Spawn-context entry for the fleet router front: its own process,
    like every other tier component — the scaling leg is only a
    measurement of the REPLICAS if the router's frame proxying does not
    share an interpreter (a GIL) with the load generators."""
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from handyrl_tpu.fleet import FleetRouter

    fleet = FleetRouter(fleet_cfg).run(connect_timeout=600.0)
    pipe.send(fleet.bound_port)
    try:
        pipe.recv()
    except EOFError:
        pass
    fleet.shutdown()


def _fleet_load_main(pipe, port, env_name, dur, sessions, collect_models):
    """Spawn-context entry for one load generator: one connection driving
    ``sessions`` server-resident sessions, each with its one in-order
    request outstanding (a session's steps are serial by definition —
    concurrency comes from session count).  Handshakes ready/go over the
    pipe so every generator's window opens together, then reports its
    own counts and elapsed."""
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import time as _time

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.serving import ServingClient

    env = make_env({"env": env_name})
    env.reset()
    obs = env.observation(env.players()[0])
    client = ServingClient("127.0.0.1", port)
    ok = err = 0
    models = set()
    try:
        sids = [client.open_session() for _ in range(sessions)]
        inflight = [(sid, client.submit(obs, sid=sid)) for sid in sids]
        pipe.send("ready")
        pipe.recv()
        t0 = _time.perf_counter()
        end = t0 + dur
        while _time.perf_counter() < end:
            sid, fut = inflight.pop(0)
            try:
                reply = fut.result(timeout=120)
                ok += 1
                if collect_models:
                    models.add(reply["model"])
            except Exception:
                err += 1
            inflight.append((sid, client.submit(obs, sid=sid)))
        for _sid, fut in inflight:
            try:
                reply = fut.result(timeout=120)
                ok += 1
                if collect_models:
                    models.add(reply["model"])
            except Exception:
                err += 1
        elapsed = _time.perf_counter() - t0
        for sid in sids:
            client.close_session(sid)
        pipe.send({"ok": ok, "err": err, "elapsed": elapsed,
                   "models": sorted(models)})
    finally:
        client.close()


def _fleet_bench(duration: float):
    """Fleet-tier bench over real processes and sockets: saturation QPS
    through the router with one vs two replica processes (the tier must
    SCALE, not just route), a fleet-wide hot-swap under load with a
    zero-drop count, and the session leg's wire-bytes ratio vs
    ship-hidden-state with bit-identical outputs (the session cache must
    be a pure wire optimization, not a numerics change).

    Every tier component runs in its OWN spawn process — N replicas, the
    router, and each load generator — so the replicas are the measured
    bottleneck and the scaling leg reflects tier capacity, not the bench
    parent's GIL.  The leg is still physics-bound by the host: on a
    single-core box two replicas CANNOT beat one (``cores`` lands in the
    result so captures are interpreted against the hardware)."""
    import multiprocessing as _mp
    import threading as _threading

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.serving import ServingClient

    # Geister: the DRC ConvLSTM policy — per-step recurrent state (~27 KB)
    # dwarfs the observation (~1 KB), which is the whole case for server-
    # resident sessions; its compute is heavy enough that the replicas,
    # not the router's Python front, are the tier's bottleneck
    env = make_env({"env": "Geister"})
    module = env.net()
    env.reset()
    obs = env.observation(env.players()[0])
    p2 = init_variables(module, env, seed=2)["params"]
    hidden0 = module.initial_state(())  # the same zeros a fresh session gets

    replica_cfg = {
        "port": 0, "max_models": 4, "slo_ms": 1000.0, "shed_policy": "none",
        "max_batch": 32, "max_wait_ms": 1.0,
        # all reachable buckets pre-warmed (startup AND the swap standby):
        # the zero-drop leg must never pay a hot-path compile
        "warm_buckets": [1, 2, 4, 8, 16, 32],
        "queue_bound": 8192, "recv_timeout": 0.0, "watch_interval": 0.0,
        "stats_interval": 0.0, "session_capacity": 4096, "session_spill": 4096,
    }
    fleet_cfg = {
        "port": 0, "stats_poll_s": 0.5, "replica_stall_s": 60.0,
        "rejoin_backoff_s": 0.5, "rejoin_backoff_max_s": 5.0,
        "stats_interval": 0.0,
    }

    ctx = _mp.get_context("spawn")  # kill-safe: no forked jax runtime state
    procs = []

    def start(target, *args):
        parent, child = ctx.Pipe()
        proc = ctx.Process(target=target, args=(child,) + args, daemon=True)
        proc.start()
        procs.append((proc, parent))
        return proc, parent

    def start_replica():
        _proc, parent = start(_fleet_replica_main, "Geister", 1, replica_cfg)
        if not parent.poll(600):
            raise RuntimeError("fleet bench replica never reported its port")
        return parent.recv()

    def start_router(ports):
        cfg = dict(fleet_cfg, replicas=[f"127.0.0.1:{p}" for p in ports])
        _proc, parent = start(_fleet_router_main, cfg)
        if not parent.poll(600):
            raise RuntimeError("fleet bench router never reported its port")
        return parent.recv(), parent

    def run_load(port, dur, n_clients, collect_models=False, on_go=None):
        gens = [
            start(_fleet_load_main, port, "Geister", dur, FLEET_SESSIONS,
                  collect_models)
            for _ in range(n_clients)
        ]
        # two-phase start: every generator opens its sessions and primes
        # its window FIRST, then all windows open together on "go" — the
        # measured interval never includes a generator's jax import
        for _proc, parent in gens:
            if not parent.poll(600):
                raise RuntimeError("fleet bench load generator never primed")
            parent.recv()
        for _proc, parent in gens:
            parent.send("go")
        if on_go is not None:
            on_go()
        results = []
        for proc, parent in gens:
            if not parent.poll(dur + 600):
                raise RuntimeError("fleet bench load generator hung")
            results.append(parent.recv())
            proc.join(timeout=60)
        ok = sum(r["ok"] for r in results)
        err = sum(r["err"] for r in results)
        elapsed = max(r["elapsed"] for r in results)
        models = set().union(*(set(r["models"]) for r in results))
        return ok / max(elapsed, 1e-6), ok, err, models

    out = {"clients": FLEET_CLIENTS, "sessions": FLEET_SESSIONS,
           # the scaling leg is physics-bound by the host: on one core two
           # replica processes cannot beat one, so captures carry the count
           "cores": os.cpu_count()}
    try:
        # -- one replica up; router (own process) over it ------------------
        port_a = start_replica()
        r1_port, r1_pipe = start_router([port_a])

        # -- wire-bytes leg: ship-state vs session, serial, bit-compared ---
        client = ServingClient("127.0.0.1", r1_port)
        try:
            import numpy as _np

            from handyrl_tpu.utils import tree_map as _tree_map

            hidden = _tree_map(_np.asarray, hidden0)
            shipped = []
            b_sent, b_recv = client.wire_bytes()
            for _ in range(FLEET_RATIO_STEPS):
                reply = client.infer(obs, hidden=hidden, timeout=300)
                hidden = reply["out"].pop("hidden")
                shipped.append(reply["out"])
            ship_bytes = sum(
                a - b for a, b in zip(client.wire_bytes(), (b_sent, b_recv))
            )
            sid = client.open_session()
            b_sent, b_recv = client.wire_bytes()
            sessioned = []
            for _ in range(FLEET_RATIO_STEPS):
                reply = client.infer(obs, sid=sid, timeout=300)
                sessioned.append(reply["out"])
            sess_bytes = sum(
                a - b for a, b in zip(client.wire_bytes(), (b_sent, b_recv))
            )
            client.close_session(sid)
            bitident = all(
                set(a) == set(b) and all(
                    _np.array_equal(_np.asarray(a[k]), _np.asarray(b[k]))
                    for k in a
                )
                for a, b in zip(shipped, sessioned)
            )
            out["session_wire_ratio"] = ship_bytes / max(sess_bytes, 1)
            out["session_bitident"] = bitident
            out["ship_bytes_per_req"] = ship_bytes // FLEET_RATIO_STEPS
            out["session_bytes_per_req"] = sess_bytes // FLEET_RATIO_STEPS
        finally:
            client.close()

        # -- saturation through the router, 1 replica ----------------------
        qps_1, ok_1, err_1, _ = run_load(r1_port, duration, FLEET_CLIENTS)
        out["qps_1"] = qps_1
        out["requests_1"] = ok_1
        out["load_errors"] = err_1
        try:
            r1_pipe.send("stop")
        except (BrokenPipeError, OSError):
            pass

        # -- second replica; same load through a 2-replica tier ------------
        port_b = start_replica()
        r2_port, _r2_pipe = start_router([port_a, port_b])
        qps_2, ok_2, err_2, _ = run_load(r2_port, duration, FLEET_CLIENTS)
        out["qps_2"] = qps_2
        out["requests_2"] = ok_2
        out["load_errors"] += err_2
        out["scaling_x"] = qps_2 / max(qps_1, 1e-6)

        # -- fleet-wide hot-swap under session load: zero drops ------------
        swap_holder = {}

        def do_swap():
            admin = ServingClient("127.0.0.1", r2_port)
            try:
                time.sleep(min(1.0, duration / 4))
                swap_holder["reply"] = admin.swap(2, params=p2, timeout=600)
            finally:
                admin.close()

        # armed by run_load the moment every generator's window opens —
        # started any earlier, the flip could land before the first
        # pre-swap reply and the {1, 2} observation would be vacuous
        swap_thread = _threading.Thread(target=do_swap, daemon=True)
        _qps, ok_s, err_s, models = run_load(
            r2_port, max(duration / 2, 2.0) + 2.0, FLEET_CLIENTS,
            collect_models=True, on_go=swap_thread.start,
        )
        swap_thread.join(600)
        swap = swap_holder.get("reply") or {}
        out["swap_warm_ms"] = swap.get("warm_ms")
        out["swap_replicas"] = swap.get("replicas")
        out["swap_dropped"] = err_s
        out["swap_flip_observed"] = models == {1, 2}

        # -- elastic leg (docs/serving.md §Elastic fleet): a request storm
        # -- scales the fleet up WITHOUT shedding (warm-then-admit), then
        # -- calm scales it back down through the zero-loss migration path
        import shutil as _shutil
        import tempfile as _tempfile

        from handyrl_tpu.config import normalize_args as _normalize
        from handyrl_tpu.fleet import FleetRouter as _FleetRouter
        from handyrl_tpu.fleet.autoscale import ProcessReplicaFactory

        el_dir = _tempfile.mkdtemp(prefix="bench_fleet_elastic_")
        el_args = _normalize({
            "env_args": {"env": "Geister"},
            "train_args": {
                "model_dir": el_dir,
                # max_batch 1 keeps queue depth visible to the autoscaler's
                # polls, so the storm reliably crosses depth_high
                "serving": dict(replica_cfg, max_batch=1, max_wait_ms=0.0,
                                warm_buckets=[1]),
            },
        })
        el_factory = ProcessReplicaFactory(el_args, spawn_timeout_s=600.0)
        el_fleet = _FleetRouter(
            {
                "port": 0, "replicas": [], "stats_poll_s": 0.1,
                "replica_stall_s": 60.0, "rejoin_backoff_s": 0.5,
                "rejoin_backoff_max_s": 5.0, "stats_interval": 0.0,
                "autoscale": {
                    "enabled": True, "min_replicas": 1, "max_replicas": 2,
                    "interval_s": 0.1, "shed_slo": 0.01, "depth_high": 2.0,
                    "depth_low": 1.0, "scale_down_after_s": 1.0,
                    "cooldown_s": 0.5, "warm_timeout_s": 600.0,
                },
            },
            replica_factory=el_factory,
        ).run(connect_timeout=600.0)
        stop_storm = _threading.Event()
        storm_errors = []
        storm_ok = [0]

        def _storm():
            c = ServingClient("127.0.0.1", el_fleet.bound_port)
            try:
                while not stop_storm.is_set():
                    try:
                        c.infer(obs, timeout=300)
                        storm_ok[0] += 1
                    except Exception as exc:
                        storm_errors.append(repr(exc))
                        return
            finally:
                c.close()

        try:
            storm_threads = [
                _threading.Thread(target=_storm, daemon=True)
                for _ in range(8)
            ]
            for t in storm_threads:
                t.start()
            deadline = time.monotonic() + 600.0
            while time.monotonic() < deadline:
                warm = sum(1 for r in el_fleet._reps()
                           if r.alive and r.admitted)
                if el_fleet.scale_ups >= 1 and warm >= 2:
                    break
                time.sleep(0.05)
            stop_storm.set()
            for t in storm_threads:
                t.join(timeout=300)
            admin = ServingClient("127.0.0.1", el_fleet.bound_port)
            try:
                stats = admin.stats()
                shed = sum(r.get("serve_shed") or 0
                           for r in stats["replicas"].values())
                # a session pinned to the newest spawned replica — the
                # calm scale-down must MIGRATE it, not lose it
                victim = [r for r in el_fleet._reps() if r.spawned][-1]
                sid = None
                for _ in range(8):
                    s = admin.open_session()
                    if el_fleet._affinity[s] is victim:
                        sid = s
                        break
                if sid is not None:
                    admin.infer(obs, sid=sid, timeout=300)
                deadline = time.monotonic() + 600.0
                while (el_fleet.scale_downs < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                migrated_ok = (
                    sid is not None
                    and admin.infer(obs, sid=sid, timeout=300) is not None
                )
            finally:
                admin.close()
            out["elastic_scale_ups"] = el_fleet.scale_ups
            out["elastic_scale_downs"] = el_fleet.scale_downs
            out["elastic_storm_requests"] = storm_ok[0]
            out["elastic_storm_errors"] = len(storm_errors)
            out["elastic_scaleup_shed"] = shed
            out["elastic_sessions_migrated"] = el_fleet.sessions_migrated
            out["elastic_handoff_ms"] = round(el_fleet.last_migration_ms, 2)
            out["elastic_migrated_session_ok"] = migrated_ok
        finally:
            stop_storm.set()
            el_fleet.shutdown()
            el_factory.close()
            _shutil.rmtree(el_dir, ignore_errors=True)
    finally:
        for proc, parent in procs:
            try:
                parent.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for proc, _parent in procs:
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=10)
    return out


# league-stage geometry: the training leg is EPOCH-bounded (the gate
# needs whole epoch boundaries, not a wall-clock window)
LEAGUE_EPOCHS = 3 if QUICK else 5
LEAGUE_UPDATE_EPISODES = 16 if QUICK else 24


def _league_bench(duration: float):
    """League plane + autovec stage (docs/league.md §Bench + CI).

    Leg A — the twin-less env compiler's cost, apples to apples: device
    self-play throughput of autovec-lifted TicTacToe vs the hand-written
    VectorTicTacToe (same game, same net, same device set — the per-chip
    frac isolates the lift), judged at ROADMAP item 4's >= 0.5 bar; plus
    lifted ConnectFour absolute throughput (an env with NO hand twin).

    Leg B — a small end-to-end league run (TicTacToe, anchor-seeded):
    PFSP matchmaking, payoff coverage, promotion gate, Elo spread — the
    same path tests/test_league.py::test_league_end_to_end pins, here
    with its realized numbers committed to the bench record.
    """
    import shutil
    import tempfile

    import jax

    from examples.connect_four import ConnectFourRules
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.envs.autovec import autovectorize
    from handyrl_tpu.envs.tictactoe import TicTacToeRules
    from handyrl_tpu.envs.vector_tictactoe import VectorTicTacToe
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.device_rollout import build_selfplay_fn

    n_games = 2048 if jax.default_backend() == "tpu" else 512

    def selfplay_rate(env_name, venv, window):
        env = make_env({"env": env_name})
        module = env.net()
        params = init_variables(module, env)["params"]
        fn = build_selfplay_fn(venv, module, n_games)
        holder = {"key": jax.random.PRNGKey(0)}

        def call():
            holder["key"], sub = jax.random.split(holder["key"])
            cols = fn(params, sub)
            holder["last"] = cols
            return cols["alive"]

        calls_per_sec = _timed_loop(call, window)
        alive = float(jax.device_get(holder["last"]["alive"]).sum())
        return calls_per_sec * alive

    window = max(duration / 4, 2.0)
    hand = selfplay_rate("TicTacToe", VectorTicTacToe, window)
    auto = selfplay_rate("TicTacToe", autovectorize(TicTacToeRules), window)
    c4 = selfplay_rate("ConnectFour", autovectorize(ConnectFourRules), window)
    out = {
        "twin_steps_per_sec": hand,
        "autovec_steps_per_sec": auto,
        # identical device sets on both sides, so the ratio IS per-chip
        "autovec_per_chip_frac": auto / max(hand, 1e-9),
        "autovec_target_met": auto / max(hand, 1e-9) >= 0.5,
        "connectfour_autovec_steps_per_sec": c4,
        "n_games": n_games,
    }

    # -- leg B: end-to-end league run ---------------------------------------
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.league.learner import LeagueLearner

    run_dir = tempfile.mkdtemp(prefix="bench_league_")
    try:
        cfg = normalize_args({
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "update_episodes": LEAGUE_UPDATE_EPISODES,
                "minimum_episodes": 12,
                "maximum_episodes": 500,
                "num_batchers": 0,
                "batch_pipeline": "thread",
                "epochs": LEAGUE_EPOCHS,
                "eval_rate": 0.0,
                "worker": {"num_parallel": 2},
                "metrics_path": os.path.join(run_dir, "metrics.jsonl"),
                "model_dir": os.path.join(run_dir, "models"),
                # the bar below random-vs-random wp: the bench commits the
                # MECHANICS' numbers (coverage, spread, promotions) —
                # candidate strength vs a real bar is a soak concern
                "league": {"promote_winrate": 0.4, "promote_games": 3,
                           "selfplay_rate": 0.15},
            },
        })
        t0 = time.perf_counter()
        learner = LeagueLearner(cfg)
        rc = learner.run()
        out["run_seconds"] = time.perf_counter() - t0
        if rc != 0:
            raise RuntimeError(f"league run exited {rc}")
        from handyrl_tpu.league import ANCHOR, CANDIDATE
        from handyrl_tpu.utils.metrics import read_metrics

        payoff = learner.league.payoff
        pool = [m.name for m in learner.league.opponent_pool()]
        rated = payoff.elo(pool + [CANDIDATE], anchor=ANCHOR)
        out["population"] = len(learner.league.members)
        out["promotions"] = learner.league.promotions
        out["matches"] = payoff.matches
        # a promotion hands the candidate's books to the frozen member, so
        # the FINAL row can legitimately read 0; the coverage story is the
        # best fill any generation reached (1.0 = some gate saw every pair)
        records = read_metrics(cfg["train_args"]["metrics_path"])
        out["payoff_coverage"] = max(
            (r.get("league_payoff_coverage") or 0.0 for r in records),
            default=0.0,
        )
        out["elo_spread"] = (
            max(rated.values()) - min(rated.values()) if len(rated) >= 2 else None
        )
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    return out


def _lowprec_bench(duration: float):
    """Low-precision fast path (docs/performance.md §Low-precision): both
    precision rungs MEASURED in one session so the ratios divide out the
    day's RTT/lease variance.

    Weight rung: resident param bytes fp32 vs int8 (models/quantize.py
    per-channel symmetric), engine inference rate per rung through the
    same jitted-apply path the serving plane dispatches, and the
    publish-time calibration record (measured output deviation over
    replay obs).  Obs rung: identical seeded self-play encoded fp32 vs
    int8 — raw obs bytes moved and compressed wire bytes — plus train
    updates/s consuming each encoding (int8 windows dequantize inside
    the jitted sample/forward programs).  Parity is MEASURED, never
    assumed: a short-trained policy pits its int8 engine against its
    fp32 engine seat-balanced through the league's PayoffMatrix ledger
    (|wp - 0.5| <= 0.03 over >= 400 games; QUICK mode plays 40 — enough
    to exercise the verdict path, not to bank it).  On CPU the byte
    ratios are exact and portable; the rates are proxy numbers (no MXU,
    no HBM) — BENCH_r06 TPU capture instructions in docs/performance.md."""
    import random as _random

    import jax
    import numpy as np

    from handyrl_tpu.agents import Agent
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.league.matchmaker import PayoffMatrix
    from handyrl_tpu.models import build_inference_model
    from handyrl_tpu.models.quantize import (
        calibration_batches_from_store, calibration_report, obs_quant_spec,
        param_bytes, quantize_params,
    )
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.evaluation import evaluate_mp
    from handyrl_tpu.runtime.replay import decompress_block
    from handyrl_tpu.utils import tree_map

    out = {"backend": jax.default_backend()}
    fill = 12 if QUICK else 32

    # -- weight rung ------------------------------------------------------
    args = _make_args("TicTacToe", {"batch_size": 32, "forward_steps": 8})
    _random.seed(1009)
    env, module, model, store = _fill_store(args, fill)
    params = model.variables["params"]
    out["weight_bytes_fp32"] = param_bytes(params)
    out["weight_bytes_int8"] = param_bytes(quantize_params(params))
    out["weight_bytes_ratio"] = out["weight_bytes_fp32"] / out["weight_bytes_int8"]

    env.reset()
    obs = env.observation(env.players()[0])
    B = 64
    obs_b = tree_map(lambda x: np.broadcast_to(np.asarray(x)[None],
                                               (B,) + np.asarray(x).shape).copy(),
                     obs)
    for rung, dtype in (("fp32", "float32"), ("int8", "int8")):
        eng = build_inference_model(module, params, dtype)
        hidden = eng.init_hidden((B,))
        rate = _timed_loop(
            lambda: eng.inference_batch_async(obs_b, hidden), duration / 4
        )
        out[f"infer_qps_{rung}"] = rate * B
    out["infer_int8_vs_fp32"] = out["infer_qps_int8"] / out["infer_qps_fp32"]

    calib = calibration_report(
        module, params, calibration_batches_from_store(store, 4)
    )
    out["calib_batches"] = calib["calib_batches"]
    out["calib_max_dev"] = calib["calib_max_dev"]
    out["calib_mean_dev"] = calib["calib_mean_dev"]

    # -- obs rung: identical seeded self-play, fp32 vs int8 encoding ------
    train_ups = {}
    obs_bytes = {}
    wire_bytes = {}
    ctx_f = state_f = None
    for rung, flag in (("fp32", False), ("int8", True)):
        targs = _make_args("TicTacToe", {"batch_size": 32, "forward_steps": 8,
                                         "obs_int8": flag})
        _random.seed(123)  # SAME trajectories both rungs: only encoding differs
        _, mod2, model2, store2 = _fill_store(targs, fill)
        raw = blob = 0
        for ep in store2.snapshot():
            blob += sum(len(b) for b in ep["blocks"])
            for b in ep["blocks"]:
                raw += sum(
                    leaf.nbytes
                    for leaf in jax.tree.leaves(decompress_block(b)["obs"])
                )
        obs_bytes[rung], wire_bytes[rung] = raw, blob
        if flag:
            targs["_obs_quant"] = obs_quant_spec(make_env(targs["env"]))
        ctx = TrainContext(mod2, targs, make_mesh(targs["mesh"]))
        state = ctx.init_state(model2.variables["params"])
        batches = [ctx.put_batch(_sample_batch(store2, targs)) for _ in range(4)]
        holder = {"state": state, "i": 0}

        def step():
            holder["state"], metrics = ctx.train_step(
                holder["state"], batches[holder["i"] % 4], 1e-3
            )
            holder["i"] += 1
            return metrics["total"]

        train_ups[rung] = _timed_loop(step, duration / 4)
        if not flag:
            ctx_f, state_f, batches_f, holder_f = ctx, state, batches, holder
    out["obs_bytes_fp32"], out["obs_bytes_int8"] = obs_bytes["fp32"], obs_bytes["int8"]
    out["obs_bytes_ratio"] = obs_bytes["fp32"] / obs_bytes["int8"]
    out["wire_bytes_ratio"] = wire_bytes["fp32"] / wire_bytes["int8"]
    out["train_updates_per_sec_fp32"] = train_ups["fp32"]
    out["train_updates_per_sec_int8"] = train_ups["int8"]
    out["train_int8_vs_fp32"] = train_ups["int8"] / train_ups["fp32"]

    # -- wp parity: int8 engine vs fp32 engine, SAME short-trained params --
    # (a uniform random policy would make any parity bar vacuous, so keep
    # training the fp32 context briefly before extracting the params)
    t_end = time.perf_counter() + min(duration, 12.0)
    while time.perf_counter() < t_end:
        holder_f["state"], m = ctx_f.train_step(
            holder_f["state"], batches_f[holder_f["i"] % 4], 1e-3
        )
        holder_f["i"] += 1
    jax.block_until_ready(m["total"])
    trained = tree_map(np.asarray, jax.device_get(holder_f["state"]["params"]))

    games = 40 if QUICK else 400
    a_q = Agent(build_inference_model(module, trained, "int8"),
                temperature=1.0, seed=11)
    a_f = Agent(build_inference_model(module, trained, "float32"),
                temperature=1.0, seed=12)
    results = evaluate_mp({"env": "TicTacToe"}, {0: a_q, 1: a_f},
                          games, num_workers=2)
    payoff = PayoffMatrix()
    for _pat, res in results.items():
        for outcome, count in res.items():
            payoff.record_score("int8", "fp32", float(outcome),
                                -float(outcome), n=count)
    wp = payoff.win_points("int8", "fp32")
    out["wp"] = wp
    out["wp_games"] = payoff.games("int8", "fp32")
    out["wp_delta"] = abs(wp - 0.5)
    out["wp_parity_target_met"] = out["wp_delta"] <= 0.03
    return out


def _flywheel_bench(duration: float):
    """Data-flywheel bench (docs/serving.md §Data flywheel) over the REAL
    framed-socket transport: harvest assembly rate (scripted clients play
    full games through per-player sessions and close each step over the
    harvest protocol), ingest drain rate in wire bytes/s, and the quality
    plane's two latencies — snapshot-available -> gated promotion flip,
    and first bad outcome -> sentinel demote-to-incumbent."""
    import random as _random
    import tempfile

    import numpy as np

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.flywheel import FlywheelPlane
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot
    from handyrl_tpu.serving import ModelRouter, ServingClient, ServingServer

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    env.reset()
    obs0 = env.observation(0)
    p1 = init_variables(module, env, seed=1)["params"]
    p2 = init_variables(module, env, seed=2)["params"]

    model_dir = tempfile.mkdtemp(prefix="bench_flywheel_")
    save_epoch_snapshot(model_dir, 1, p1, {"bench": 0}, 0)

    promote_games = 8
    quality_window = 4
    fly_cfg = {
        "enabled": True, "gate_promotions": True, "promote_winrate": 0.55,
        "promote_games": promote_games, "quality_window": quality_window,
        "demote_drop": 0.1, "shadow_fraction": 0.0,
        "harvest_max_open": 512, "harvest_ttl_s": 600.0,
    }
    gen_args = {"gamma": 0.8, "compress_steps": 8, "observation": True,
                "obs_int8": False}
    cfg = {
        "port": 0, "max_models": 4, "slo_ms": 1000.0, "shed_policy": "none",
        "max_batch": 64, "max_wait_ms": 1.0,
        "warm_buckets": [1, 2, 4, 8, 16],
        "queue_bound": 8192, "recv_timeout": 0.0, "watch_interval": 0.2,
        "stats_interval": 0.0,
    }
    router = ModelRouter(module, obs0, cfg, model_dir=model_dir)
    router.publish(1, p1)
    flywheel = FlywheelPlane(router, model_dir, fly_cfg, gen_args)
    server = ServingServer(router, cfg, flywheel=flywheel).run()
    out = {}
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        players = env.players()

        def play_one():
            """One full game over the wire: per-player sessions bound into
            a harvest episode, policies sampled from the served replies."""
            sids = [client.open_session() for _ in players]
            hid = client.harvest_open(players, sids)
            env.reset()
            while not env.terminal():
                turn_players = env.turns()
                actions = [None] * len(players)
                legal_lists = [None] * len(players)
                moves = {}
                for p in turn_players:
                    j = players.index(p)
                    reply = client.infer(env.observation(p), sid=sids[j])
                    logits = np.asarray(reply["out"]["policy"]).reshape(-1)
                    legal = env.legal_actions(p)
                    action = max(legal, key=lambda a: (logits[a], _random.random()))
                    actions[j] = int(action)
                    legal_lists[j] = list(legal)
                    moves[p] = int(action)
                turn = turn_players[0] if turn_players else None
                env.step(moves)
                reward = env.reward()
                rewards = [reward.get(p) for p in players]
                client.harvest_step(hid, actions, legal_lists, rewards, turn)
            outcome = env.outcome()
            kept = client.harvest_close(hid, [outcome.get(p, 0.0) for p in players])
            for sid in sids:
                client.close_session(sid)
            return kept

        # -- phase 1: harvest assembly over the wire ----------------------
        episodes = 0
        t0 = time.perf_counter()
        end = t0 + duration
        while time.perf_counter() < end:
            if play_one():
                episodes += 1
        harvest_s = time.perf_counter() - t0
        out["episodes"] = episodes
        out["harvest_eps_per_sec"] = episodes / max(harvest_s, 1e-6)

        # -- phase 2: ingest drain rate (the learner poll's wire cost) ----
        _sent0, recv0 = client.wire_bytes()
        pulled = 0
        t0 = time.perf_counter()
        while True:
            eps, counts = client.harvest_pull(max_episodes=64)
            pulled += len(eps)
            if not eps:
                break
        pull_s = time.perf_counter() - t0
        _sent1, recv1 = client.wire_bytes()
        out["pull_episodes"] = pulled
        out["ingest_bytes_per_sec"] = (recv1 - recv0) / max(pull_s, 1e-6)
        out["dropped"] = (counts.get("flywheel_dropped_malformed", 0)
                          + counts.get("flywheel_dropped_truncated", 0))

        def wait_for(pred, timeout=30.0):
            t = time.perf_counter()
            while time.perf_counter() - t < timeout:
                if pred():
                    return True
                time.sleep(0.02)
            return False

        # -- phase 3: gated promotion latency -----------------------------
        # snapshot 2 lands -> watch loop stages it -> live wins clear the
        # gate -> latest flips.  The measured span is the whole mechanism
        t0 = time.perf_counter()
        save_epoch_snapshot(model_dir, 2, p2, {"bench": 0}, 0)
        staged = wait_for(lambda: router.candidate_id() == 2)
        if staged:
            for _ in range(promote_games):
                client.report_outcome(2, 1.0)
        promoted = wait_for(lambda: router.latest_id() == 2)
        out["promote_latency_ms"] = (time.perf_counter() - t0) * 1000.0
        out["promote_observed"] = promoted

        # -- phase 4: sentinel demote latency -----------------------------
        # the promoted snapshot turns bad live: losses past the window
        # drag its EMA under the bar and the sentinel restores epoch 1
        t0 = time.perf_counter()
        demoted = False
        if promoted:
            for _ in range(quality_window * 2):
                client.report_outcome(2, -1.0)
            demoted = wait_for(lambda: router.latest_id() == 1)
        out["demote_ms"] = (time.perf_counter() - t0) * 1000.0
        out["demote_observed"] = demoted

        q = flywheel.stats_record()
        out["promotions"] = q.get("quality_promotions", 0)
        out["demotions"] = q.get("quality_demotions", 0)
        out["games"] = q.get("quality_games", 0)
    finally:
        client.close()
        server.shutdown()
    return out


KNOWN_STAGES = (
    "tictactoe", "device-selfplay", "geese-device-selfplay", "geese-gen",
    "geese-train", "northstar", "northstar2", "northstar3", "northstar3mp",
    "northstar4",
    "geese-bf16", "geister", "geister-device-selfplay", "geister-devreplay",
    "serving", "fleet", "league", "lowprec", "flywheel", "transformer",
    "transformer_long", "flash",
)
# stages that consume another stage's result (main() gates them on it)
STAGE_DEPS = {
    "northstar": ("geese-train",),
    "northstar2": ("geese-train",),
    "northstar3": ("geese-train",),
    "northstar4": ("geese-train",),
    "geese-bf16": ("geese-train",),
}


def _stage_filter() -> Optional[set]:
    """``BENCH_STAGES=a,b,c`` limits the run to the named stages (for
    banking one new stage's numbers on a live chip without re-paying the
    full ~25 min suite).  Unset or empty means all stages — an empty
    string from CI interpolation must not skip everything.  Dependencies
    are pulled in automatically (BENCH_STAGES=northstar2 also runs
    geese-train: the northstar/bf16 stages reuse its store + context and
    are gated on its result in main())."""
    raw = os.environ.get("BENCH_STAGES")
    if raw is None or not raw.strip():
        return None
    names = {s.strip() for s in raw.split(",") if s.strip()}
    for n in tuple(names):
        names.update(STAGE_DEPS.get(n, ()))
    return names


def _run_stage(result: dict, name: str, fn, retries: int = 1,
               retry_delay: float = 20.0):
    """Run one bench stage with a single retry.  One transient failure
    (dropped tunnel connection, axon UNAVAILABLE — the r3s3 capture lost
    the whole flash stage to a single 'remote_compile: Connection
    refused') must not null a stage's numbers: a failed stage re-runs
    once after a short wait, and the per-stage error lands in
    result["error"] only when every attempt fails.  A failed attempt's
    PARTIAL writes to ``result`` are rolled back (a stage that died after
    recording throughput must not leave numbers that read as measured),
    and every attempt's traceback is kept.  Returns the stage's value, or
    None after final failure."""
    only = _stage_filter()
    if only is not None and name not in only:
        result["extra"].setdefault("stages_skipped", []).append(name)
        return None
    deadline = _deadline_s()
    if deadline > 0:
        remaining = deadline - (time.perf_counter() - _T0)
        if remaining < _env_float("BENCH_STAGE_MIN_S", 60.0):
            # too little runway for a meaningful measurement: finish clean
            # (rc=0, honest note) instead of being SIGKILLed mid-stage
            result["extra"].setdefault("stages_deadline_skipped", []).append(name)
            _note(f"{name}: skipped — {remaining:.0f}s of {deadline:.0f}s "
                  f"deadline left")
            _emit_snapshot(result)
            return None
    errs = []
    for attempt in range(retries + 1):
        snap = {k: result[k] for k in ("value", "vs_baseline", "error")}
        snap_extra = dict(result["extra"])
        try:
            val = fn()
            _emit_snapshot(result)
            return val
        except Exception:
            result.update(snap)
            result["extra"] = snap_extra
            errs.append(f"attempt {attempt + 1}: "
                        + traceback.format_exc(limit=3))
            if attempt < retries:
                _note(f"{name}: attempt {attempt + 1} failed; retrying in "
                      f"{retry_delay:.0f}s")
                time.sleep(retry_delay)
    result["error"] = (result["error"] or "") + f" {name}: " + " | ".join(errs)
    _emit_snapshot(result)
    return None


def main() -> None:
    result = {
        "metric": "tictactoe_trained_env_steps_per_sec",
        "value": None,
        "unit": "env-steps/s",
        "vs_baseline": None,
        "platform": None,
        "error": None,
        "extra": {},
    }

    # a typo'd BENCH_STAGES must not burn a scarce lease window on a run
    # that silently skips everything: unknown names fail before the probe
    only = _stage_filter()
    if only and not only.issubset(KNOWN_STAGES):
        result["error"] = (
            f"unknown BENCH_STAGES name(s) {sorted(only - set(KNOWN_STAGES))}; "
            f"valid: {', '.join(KNOWN_STAGES)}"
        )
        _emit_snapshot(result, final=True)
        return

    # a stage-filtered run REFRESHES its stages' numbers in place: seed
    # from the existing side file so the skipped stages' banked metrics
    # survive the rewrite (a BENCH_STAGES=northstar3mp smoke must not
    # clobber the full capture tests/test_perfgate.py loads).  Run
    # bookkeeping (stages_skipped, partial) is always THIS run's.
    if only is not None:
        try:
            with open(_snapshot_path()) as f:
                prev = json.loads(f.readline())
            for k, v in (prev.get("extra") or {}).items():
                if k not in ("stages_skipped", "stages_deadline_skipped"):
                    result["extra"][k] = v
            if "tictactoe" not in only:
                result["value"] = prev.get("value")
                result["vs_baseline"] = prev.get("vs_baseline")
        except (OSError, ValueError):
            pass  # no prior snapshot: the filtered run stands alone

    done = threading.Event()

    # probe-phase watchdog: bounds the lease-wait loop AND the in-process
    # jax.devices() init (which can hang just like the subprocess probe).
    # Under a deadline the budget is simply the REMAINING time minus 30 s
    # — it must fire before the driver's kill (the r04 watchdog armed at
    # wait+900 = 900 s past the kill), and remaining-30 also upper-bounds
    # the wait loop's own worst case (wait is capped at remaining minus a
    # 300 s reserve, so a healthy run that resolves at wait+~240 still
    # clears the watchdog with slack).  No deadline: the old wait+900.
    probe_done = threading.Event()
    if _deadline_s() > 0:
        probe_budget = max(
            60.0, _deadline_s() - (time.perf_counter() - _T0) - 30.0
        )
    else:
        probe_budget = _effective_tpu_wait() + 900.0
    _start_watchdog(result, probe_done, budget=probe_budget)
    devices, backend_err = _devices_with_retry()
    probe_done.set()
    if backend_err:
        result["error"] = str(backend_err)
    if devices is None:
        _emit_snapshot(result, final=True)
        return
    result["platform"] = f"{devices[0].platform}:{getattr(devices[0], 'device_kind', '?')} x{len(devices)}"
    # first parseable line lands the moment the probe resolves: even a
    # kill during the headline stage leaves platform + any probe error
    _emit_snapshot(result)

    # the measuring watchdog clock starts AFTER the probe: waiting out a
    # wedged lease must not eat the measuring budget.  Under a deadline it
    # fires ~30 s before the driver's kill so a wedged dispatch still ends
    # in a clean final JSON + rc=0 instead of SIGKILL.
    wd_budget = _env_float("BENCH_WATCHDOG_S", 2700.0)
    if wd_budget > 0 and _deadline_s() > 0:
        wd_budget = min(
            wd_budget,
            max(60.0, _deadline_s() - (time.perf_counter() - _T0) - 30.0),
        )
    _start_watchdog(result, done, budget=wd_budget)

    peak = _peak_flops(devices[0])
    n_dev = len(devices)

    # 1. headline: TicTacToe train throughput (same metric as round 1)
    def stage_tictactoe():
        ttt = _train_bench("TicTacToe", {}, T_TRAIN, n_dev, fused=True)
        result["value"] = round(ttt["trained_env_steps_per_sec"], 1)
        result["vs_baseline"] = round(
            ttt["trained_env_steps_per_sec"] / REFERENCE_TRAINED_STEPS_PER_SEC, 3
        )
        result["extra"]["tictactoe_updates_per_sec"] = round(ttt["updates_per_sec"], 2)
        if ttt.get("fused_updates_per_sec"):
            result["extra"]["tictactoe_fused_updates_per_sec"] = round(
                ttt["fused_updates_per_sec"], 2
            )
            result["extra"]["tictactoe_fused_env_steps_per_sec"] = round(
                ttt["fused_updates_per_sec"]
                * ttt["args"]["batch_size"] * ttt["args"]["forward_steps"],
                1,
            )
        # MFU at the fastest update rate this model reaches (fused when
        # available); tiny net, so the honest number is tiny — reported
        # anyway (VERDICT r3 item 2: every path states its MFU or why not)
        if ttt["flops_per_step"] and peak:
            ups = ttt.get("fused_updates_per_sec") or ttt["updates_per_sec"]
            result["extra"]["tictactoe_mfu"] = _sig(
                ttt["flops_per_step"] * ups / (peak * n_dev)
            )
        if ttt.get("fused_error"):
            result["error"] = (result["error"] or "") + " ttt-fused: " + ttt["fused_error"]
        return ttt

    _run_stage(result, "tictactoe", stage_tictactoe)

    # 1b. on-device self-play: the zero-host-round-trip actor plane
    def stage_device_selfplay():
        dsp = _device_selfplay_bench(T_GEN / 2)
        result["extra"]["device_selfplay_env_steps_per_sec"] = round(
            dsp["env_steps_per_sec"], 1
        )
        result["extra"]["device_selfplay_vs_reference_gen"] = round(
            dsp["env_steps_per_sec"] / REFERENCE_GEN_STEPS_PER_SEC, 2
        )

    _run_stage(result, "device-selfplay", stage_device_selfplay)

    geese_over = {"turn_based_training": False, "observation": False}

    # 1c. north-star actor plane, on-device: streaming HungryGeese self-play
    def stage_geese_device_selfplay():
        gd = _streaming_selfplay_bench("HungryGeese", geese_over, T_GEN / 2)
        result["extra"]["geese_device_selfplay_env_steps_per_sec"] = round(
            gd["env_steps_per_sec"], 1
        )
        result["extra"]["geese_device_selfplay_player_steps_per_sec"] = round(
            gd["player_steps_per_sec"], 1
        )
        result["extra"]["geese_device_selfplay_episodes_per_sec"] = _sig(
            gd["episodes_per_sec"]
        )
        if gd["episodes_note"]:
            result["extra"]["geese_device_selfplay_episodes_note"] = gd["episodes_note"]
        result["extra"]["geese_device_selfplay_vs_reference_gen"] = round(
            gd["env_steps_per_sec"] / REFERENCE_GEESE_GEN_STEPS_PER_SEC, 2
        )

    _run_stage(result, "geese-device-selfplay", stage_geese_device_selfplay)

    # 2. host actor plane: HungryGeese generation through the engine
    # (32 actors x 4 simultaneous players pre-submit -> deep request queue,
    # so each device round-trip serves a full inference batch even when
    # per-call latency is high, e.g. a tunneled chip)
    def stage_geese_gen():
        gen = _generation_bench("HungryGeese", geese_over, T_GEN, num_actors=32)
        result["extra"]["geese_gen_env_steps_per_sec"] = round(gen["env_steps_per_sec"], 1)
        result["extra"]["geese_gen_vs_reference"] = round(
            gen["env_steps_per_sec"] / REFERENCE_GEESE_GEN_STEPS_PER_SEC, 3
        )
        result["extra"]["geese_gen_mean_infer_batch"] = round(gen["mean_infer_batch"], 1)

    _run_stage(result, "geese-gen", stage_geese_gen)

    # 3. north-star learner plane: GeeseNet train + starvation + MFU
    def stage_geese_train():
        gt = _train_bench("HungryGeese", geese_over, T_TRAIN, n_dev)
        result["extra"]["geese_trained_env_steps_per_sec"] = _sig(
            gt["trained_env_steps_per_sec"], 5
        )
        result["extra"]["geese_updates_per_sec"] = _sig(gt["updates_per_sec"])
        # MFU is ALWAYS reported — as a number, or as null plus the reason
        # (round 2 silently omitted it when the peak-FLOPs lookup missed)
        if gt["flops_per_step"]:
            result["extra"]["geese_flops_per_step"] = gt["flops_per_step"]
            if peak:
                result["extra"]["geese_mfu"] = round(
                    gt["flops_per_step"] * gt["updates_per_sec"] / (peak * n_dev), 4
                )
            else:
                result["extra"]["geese_mfu"] = None
                result["extra"]["geese_mfu_note"] = (
                    "no peak-FLOPs table entry for device kind "
                    f"'{getattr(devices[0], 'device_kind', '?')}'"
                )
        else:
            result["extra"]["geese_mfu"] = None
            result["extra"]["geese_mfu_note"] = (
                "XLA cost analysis returned no flops from either the native "
                "or the CPU-backend lowering, and the analytic jaxpr counter "
                "also came up empty"
            )
        pipe = _pipeline_bench(gt, T_TRAIN)
        result["extra"]["geese_pipeline_updates_per_sec"] = _sig(pipe["updates_per_sec"])
        result["extra"]["geese_input_wait_frac"] = round(pipe["input_wait_frac"], 4)
        # per-stage breakdown (seconds inside the timed window): sample /
        # assemble / free-slot wait / ready wait / device put, plus the
        # mean device-queue depth and which plane ran (shm or thread)
        result["extra"]["geese_pipeline_stages"] = pipe["stages"]
        return gt

    gt = _run_stage(result, "geese-train", stage_geese_train)

    # 3c. the north-star loop itself: device self-play feeding training,
    # concurrently, on the same chip (VERDICT r2 item 2)
    def stage_northstar():
        ns = _concurrent_northstar_bench(gt, T_TRAIN)
        if "skipped" in ns:
            result["extra"]["northstar_note"] = ns["skipped"]
            return
        result["extra"]["northstar_concurrent_trained_env_steps_per_sec"] = _sig(
            ns["trained_env_steps_per_sec"], 5
        )
        result["extra"]["northstar_concurrent_selfplay_env_steps_per_sec"] = _sig(
            ns["selfplay_env_steps_per_sec"], 5
        )
        result["extra"]["northstar_input_wait_frac"] = round(ns["input_wait_frac"], 4)
        result["extra"]["northstar_per_chip_frac"] = _sig(ns["per_chip_northstar_frac"])
        if ns.get("rollout_error"):
            result["error"] = (result["error"] or "") + " northstar-rollout: " + ns["rollout_error"]

    if gt is not None:
        _run_stage(result, "northstar", stage_northstar)

    # 3d. north-star v2: device-resident replay — records ingested into
    # on-device rings, batches sampled + assembled + stepped in ONE
    # dispatch; the data path never touches the host.  Lane/fuse geometry
    # from the round-4 duty-cycle sweep (BASELINE.md): more SGD per
    # rollout call so the chip trains instead of only self-playing.
    def stage_northstar2():
        ns2 = _device_replay_northstar_bench(gt, T_TRAIN)
        if "skipped" in ns2:
            result["extra"]["northstar2_note"] = ns2["skipped"]
            return
        result["extra"]["northstar2_trained_env_steps_per_sec"] = _sig(
            ns2["trained_env_steps_per_sec"], 5
        )
        result["extra"]["northstar2_selfplay_env_steps_per_sec"] = _sig(
            ns2["selfplay_env_steps_per_sec"], 5
        )
        result["extra"]["northstar2_rollout_time_frac"] = round(
            ns2["rollout_time_frac"], 4
        )
        import jax

        if jax.default_backend() != "cpu":
            # the loop no longer host-syncs per rollout (satellite fix:
            # the fused baseline must not be handicapped vs northstar3),
            # so with async dispatch rollout_s is enqueue time only — the
            # duty split is exact on CPU but under-reports here; flag it
            # rather than silently redefining the round-4 headline number
            result["extra"]["northstar2_rollout_time_frac_note"] = (
                "async dispatch: host-side enqueue share, not device duty"
            )
        result["extra"]["northstar2_produce_consume_ratio"] = _sig(
            ns2["produce_consume_ratio"]
        )
        result["extra"]["northstar2_per_chip_frac"] = _sig(
            ns2["per_chip_northstar_frac"]
        )
        # train-plane MFU of the all-on-device loop: same jitted step as
        # stage 3 (same batch geometry), so gt's flops/step applies
        if gt["flops_per_step"] and peak:
            result["extra"]["northstar2_train_mfu"] = _sig(
                gt["flops_per_step"] * ns2["updates_per_sec"] / (peak * n_dev)
            )
        if not ns2["loss_finite"]:
            result["error"] = (result["error"] or "") + " northstar2: non-finite loss"

    if gt is not None:
        _run_stage(result, "northstar2", stage_northstar2)

    # 3e. north-star v3: DISAGGREGATED planes — self-play on an actor
    # mesh, training on a disjoint learner mesh, concurrently (the
    # Podracer/Sebulba split; needs >= 2 devices).  The fused loop's
    # rollout_time_frac 0.91 becomes a chip split here.
    def stage_northstar3():
        ns3 = _split_plane_northstar_bench(gt, T_TRAIN)
        if "skipped" in ns3:
            result["extra"]["northstar3_note"] = ns3["skipped"]
            return
        result["extra"]["northstar3_chips"] = (
            f"{ns3['learner_chips']}L+{ns3['actor_chips']}A"
        )
        result["extra"]["northstar3_trained_env_steps_per_sec"] = _sig(
            ns3["trained_env_steps_per_sec"], 5
        )
        result["extra"]["northstar3_selfplay_env_steps_per_sec"] = _sig(
            ns3["selfplay_env_steps_per_sec"], 5
        )
        result["extra"]["northstar3_selfplay_standalone_env_steps_per_sec"] = _sig(
            ns3["selfplay_standalone_env_steps_per_sec"], 5
        )
        result["extra"]["northstar3_selfplay_concurrent_frac"] = _sig(
            ns3["selfplay_concurrent_frac"]
        )
        result["extra"]["northstar3_rollout_time_frac"] = round(
            ns3["rollout_time_frac"], 4
        )
        result["extra"]["northstar3_learner_train_time_frac"] = round(
            ns3["learner_train_time_frac"], 4
        )
        result["extra"]["northstar3_actor_busy_frac"] = round(
            ns3["actor_busy_frac"], 4
        )
        result["extra"]["northstar3_param_lag_mean"] = _sig(
            ns3["param_lag_mean"]
        )
        result["extra"]["northstar3_xfer_bytes_per_sec"] = _sig(
            ns3["xfer_bytes_per_sec"]
        )
        result["extra"]["northstar3_produce_consume_ratio"] = _sig(
            ns3["produce_consume_ratio"]
        )
        result["extra"]["northstar3_per_chip_frac"] = _sig(
            ns3["per_chip_northstar_frac"]
        )
        if gt["flops_per_step"] and peak:
            # flops_per_step was traced at geese-train's batch size; the
            # split stage may round the batch down to a learner-dp
            # multiple, and update FLOPs scale linearly with batch
            flops = gt["flops_per_step"] * (
                ns3["batch_size"] / gt["args"]["batch_size"]
            )
            result["extra"]["northstar3_train_mfu"] = _sig(
                flops * ns3["updates_per_sec"] / (peak * ns3["learner_chips"])
            )
        if ns3.get("rollout_error"):
            result["error"] = (result["error"] or "") + (
                " northstar3-rollout: " + ns3["rollout_error"]
            )
        if not ns3["loss_finite"]:
            result["error"] = (result["error"] or "") + " northstar3: non-finite loss"

    if gt is not None:
        _run_stage(result, "northstar3", stage_northstar3)

    # 3e'. north-star v3 pod-slice leg: the SAME split plane across TWO
    # OS processes under jax.distributed (subprocess children, CPU-forced
    # 4+4 virtual devices — measures the pod-slice topology's mechanics,
    # not chip throughput; no geese-train dependency, the children build
    # their own ParallelTicTacToe run)
    def stage_northstar3mp():
        mp = _multiprocess_split_plane_bench(epochs=2 if QUICK else 3)
        if "skipped" in mp:
            result["extra"]["northstar3mp_note"] = mp["skipped"]
            return
        result["extra"]["northstar3mp_processes"] = mp["processes"]
        result["extra"]["northstar3mp_updates_per_sec"] = _sig(
            mp["updates_per_sec"]
        )
        result["extra"]["northstar3mp_trained_env_steps_per_sec"] = _sig(
            mp["trained_env_steps_per_sec"], 5
        )
        result["extra"]["northstar3mp_episodes_per_sec"] = _sig(
            mp["episodes_per_sec"]
        )
        result["extra"]["northstar3mp_actor_busy_frac"] = round(
            mp["actor_busy_frac"], 4
        )
        result["extra"]["northstar3mp_xfer_bytes_per_sec"] = _sig(
            mp["xfer_bytes_per_sec"]
        )
        if not mp["both_planes_concurrent"]:
            result["error"] = (result["error"] or "") + (
                " northstar3mp: no epoch with both planes' rates nonzero"
            )

    _run_stage(result, "northstar3mp", stage_northstar3mp)

    # 3f. north-star v4: the host-pipeline scaling curve (shm plane at
    # 1/2/4 batcher processes) + the host-bypass device stage, all fed
    # from geese-train's store, each judged against the direct updates/s
    # (ROADMAP item 3: host-fed >= 50% of direct at input_wait < 0.05)
    def stage_northstar4():
        ns4 = _pipeline_scaling_bench(gt, T_TRAIN)
        for name, p in ns4["points"].items():
            result["extra"][f"northstar4_{name}_updates_per_sec"] = _sig(
                p["updates_per_sec"]
            )
            result["extra"][f"northstar4_{name}_input_wait_frac"] = round(
                p["input_wait_frac"], 4
            )
            result["extra"][f"northstar4_{name}_mode"] = p["mode"]
            result["extra"][f"northstar4_{name}_stages"] = p["stages"]
        result["extra"]["northstar4_direct_updates_per_sec"] = _sig(
            ns4["direct_updates_per_sec"]
        )
        result["extra"]["northstar4_best_host"] = ns4["best_host"]
        result["extra"]["northstar4_best_host_vs_direct"] = _sig(
            ns4["best_host_vs_direct"]
        )
        result["extra"]["northstar4_device_vs_direct"] = _sig(
            ns4["device_vs_direct"]
        )
        result["extra"]["northstar4_host_target_met"] = ns4["host_target_met"]
        result["extra"]["northstar4_device_target_met"] = ns4["device_target_met"]

    if gt is not None:
        _run_stage(result, "northstar4", stage_northstar4)

    # 3b. bf16 mixed precision (MXU-rate forward/backward, fp32 master
    # weights) on the same store — the compute_dtype knob's headroom
    def stage_geese_bf16():
        gt16 = _train_bench(
            "HungryGeese", {**geese_over, "compute_dtype": "bfloat16"},
            T_TRAIN, n_dev, reuse=gt,
        )
        result["extra"]["geese_bf16_updates_per_sec"] = _sig(gt16["updates_per_sec"])

    if gt is not None:
        _run_stage(result, "geese-bf16", stage_geese_bf16)

    # 4. recurrent path: Geister DRC ConvLSTM with burn-in + UPGO — the
    # long-horizon imperfect-info config (BASELINE.json configs[3]); the
    # train step here is a T-step lax.scan with masked hidden carry
    def stage_geister():
        geister = _train_bench(
            "Geister",
            {"burn_in_steps": 8, "forward_steps": 16, "observation": True,
             "policy_target": "UPGO", "value_target": "UPGO"},
            T_TRAIN,
            n_dev,
            fill_episodes=12,  # 200-turn episodes; filling dominates otherwise
        )
        result["extra"]["geister_rnn_updates_per_sec"] = _sig(
            geister["updates_per_sec"]
        )
        result["extra"]["geister_rnn_trained_env_steps_per_sec"] = _sig(
            geister["trained_env_steps_per_sec"], 5
        )

    _run_stage(result, "geister", stage_geister)

    # 4b. recurrent on-device self-play: Geister with the DRC ConvLSTM —
    # turn-based streaming lanes carrying per-player hidden state
    def stage_geister_device_selfplay():
        gsd = _streaming_selfplay_bench(
            "Geister", {"observation": True}, T_GEN / 2,
            n_lanes=128, k_steps=32,
        )
        result["extra"]["geister_device_selfplay_env_steps_per_sec"] = round(
            gsd["env_steps_per_sec"], 1
        )
        result["extra"]["geister_device_selfplay_episodes_per_sec"] = _sig(
            gsd["episodes_per_sec"]
        )
        if gsd["episodes_note"]:
            result["extra"]["geister_device_selfplay_episodes_note"] = gsd["episodes_note"]

    _run_stage(result, "geister-device-selfplay", stage_geister_device_selfplay)

    # 4b2. the standalone serving plane under client load (ROADMAP item 2):
    # saturation QPS + p50/p99 over the real socket transport, shed rate at
    # two offered loads against a tight SLO, hot-swap TTFR + zero-drop count
    def stage_serving():
        sv = _serving_bench(T_TRAIN)
        result["extra"]["serving_saturation_qps"] = _sig(sv["saturation_qps"])
        result["extra"]["serving_p50_ms"] = _sig(sv["p50_ms"])
        result["extra"]["serving_p99_ms"] = _sig(sv["p99_ms"])
        result["extra"]["serving_requests"] = sv["requests"]
        result["extra"]["serving_clients"] = sv["clients"]
        result["extra"]["serving_swap_warm_ms"] = _sig(sv["swap_warm_ms"])
        if sv["swap_ttfr_ms"] is not None:
            result["extra"]["serving_swap_ttfr_ms"] = _sig(sv["swap_ttfr_ms"])
        result["extra"]["serving_swap_dropped"] = sv["swap_dropped"]
        result["extra"]["serving_swap_flip_observed"] = sv["swap_flip_observed"]
        for tag in ("low", "high"):
            result["extra"][f"serving_offered_{tag}_qps"] = _sig(
                sv[f"offered_{tag}_qps"]
            )
            result["extra"][f"serving_shed_rate_{tag}"] = round(
                sv[f"shed_rate_{tag}"], 4
            )
        if sv["load_errors"] or sv["errors_low"] or sv["errors_high"]:
            result["error"] = (result["error"] or "") + (
                f" serving: {sv['load_errors']}+{sv['errors_low']}"
                f"+{sv['errors_high']} non-shed request failures"
            )
        if sv["swap_dropped"]:
            result["error"] = (result["error"] or "") + (
                f" serving: hot-swap dropped {sv['swap_dropped']} requests"
            )

    _run_stage(result, "serving", stage_serving)

    # 3f. fleet tier over the serving plane (docs/serving.md §Fleet):
    # router saturation with one vs two REAL replica processes (the tier
    # must scale ~linearly, not merely proxy), fleet-wide hot-swap under
    # session load with a zero-drop bar, and the server-resident session
    # leg's wire savings at bit-identical outputs
    def stage_fleet():
        fl = _fleet_bench(T_TRAIN)
        result["extra"]["fleet_qps_1"] = _sig(fl["qps_1"])
        result["extra"]["fleet_qps_2"] = _sig(fl["qps_2"])
        result["extra"]["fleet_scaling_x"] = round(fl["scaling_x"], 3)
        result["extra"]["fleet_cores"] = fl["cores"]
        result["extra"]["fleet_requests"] = fl["requests_1"] + fl["requests_2"]
        result["extra"]["fleet_clients"] = fl["clients"]
        result["extra"]["fleet_sessions"] = fl["clients"] * fl["sessions"]
        if fl["swap_warm_ms"] is not None:
            result["extra"]["fleet_swap_warm_ms"] = _sig(fl["swap_warm_ms"])
        result["extra"]["fleet_swap_replicas"] = fl["swap_replicas"]
        result["extra"]["fleet_swap_dropped"] = fl["swap_dropped"]
        result["extra"]["fleet_swap_flip_observed"] = fl["swap_flip_observed"]
        result["extra"]["fleet_session_wire_ratio"] = round(
            fl["session_wire_ratio"], 2
        )
        result["extra"]["fleet_session_bitident"] = fl["session_bitident"]
        result["extra"]["fleet_session_bytes_per_req"] = fl[
            "session_bytes_per_req"
        ]
        result["extra"]["fleet_ship_bytes_per_req"] = fl["ship_bytes_per_req"]
        # elastic leg (docs/serving.md §Elastic fleet): shed-free scale-up
        # under the storm, zero-loss scale-down migration, handoff wall ms
        result["extra"]["fleet_elastic_scale_ups"] = fl["elastic_scale_ups"]
        result["extra"]["fleet_elastic_scale_downs"] = fl[
            "elastic_scale_downs"
        ]
        result["extra"]["fleet_elastic_storm_requests"] = fl[
            "elastic_storm_requests"
        ]
        result["extra"]["fleet_elastic_storm_errors"] = fl[
            "elastic_storm_errors"
        ]
        result["extra"]["fleet_elastic_scaleup_shed"] = fl[
            "elastic_scaleup_shed"
        ]
        result["extra"]["fleet_elastic_sessions_migrated"] = fl[
            "elastic_sessions_migrated"
        ]
        result["extra"]["fleet_elastic_handoff_ms"] = fl["elastic_handoff_ms"]
        result["extra"]["fleet_elastic_migrated_session_ok"] = fl[
            "elastic_migrated_session_ok"
        ]
        if fl["elastic_storm_errors"] or fl["elastic_scaleup_shed"]:
            result["error"] = (result["error"] or "") + (
                f" fleet: elastic storm shed/errored "
                f"({fl['elastic_scaleup_shed']} shed, "
                f"{fl['elastic_storm_errors']} errors)"
            )
        if not fl["elastic_migrated_session_ok"]:
            result["error"] = (result["error"] or "") + (
                " fleet: migrated session lost on scale-down"
            )
        if fl["load_errors"]:
            result["error"] = (result["error"] or "") + (
                f" fleet: {fl['load_errors']} request failures under load"
            )
        if fl["swap_dropped"]:
            result["error"] = (result["error"] or "") + (
                f" fleet: hot-swap dropped {fl['swap_dropped']} requests"
            )
        if not fl["session_bitident"]:
            result["error"] = (result["error"] or "") + (
                " fleet: session outputs diverged from ship-state"
            )

    _run_stage(result, "fleet", stage_fleet)

    # 3g. league plane + the twin-less env compiler (ROADMAP item 4): the
    # autovec-vs-hand-twin per-chip frac at the >= 0.5 bar, lifted
    # ConnectFour with NO hand twin, and a small end-to-end league run's
    # payoff coverage / Elo spread / promotions
    def stage_league():
        lg = _league_bench(T_TRAIN)
        result["extra"]["league_twin_steps_per_sec"] = _sig(
            lg["twin_steps_per_sec"], 4
        )
        result["extra"]["league_autovec_steps_per_sec"] = _sig(
            lg["autovec_steps_per_sec"], 4
        )
        result["extra"]["league_autovec_per_chip_frac"] = _sig(
            lg["autovec_per_chip_frac"]
        )
        result["extra"]["league_autovec_target_met"] = lg["autovec_target_met"]
        result["extra"]["league_connectfour_autovec_steps_per_sec"] = _sig(
            lg["connectfour_autovec_steps_per_sec"], 4
        )
        result["extra"]["league_population"] = lg["population"]
        result["extra"]["league_promotions"] = lg["promotions"]
        result["extra"]["league_matches"] = lg["matches"]
        result["extra"]["league_payoff_coverage"] = round(
            lg["payoff_coverage"], 4
        )
        if lg["elo_spread"] is not None:
            result["extra"]["league_elo_spread"] = _sig(lg["elo_spread"], 4)
        result["extra"]["league_run_seconds"] = _sig(lg["run_seconds"], 4)
        if not lg["autovec_target_met"]:
            result["error"] = (result["error"] or "") + (
                " league: autovec per-chip frac %.3f below the 0.5 bar"
                % lg["autovec_per_chip_frac"]
            )

    _run_stage(result, "league", stage_league)

    # 3h. low-precision fast path (docs/performance.md §Low-precision):
    # both precision rungs measured in one session — weight/obs bytes
    # moved, engine rate and train updates/s per rung, the measured
    # calibration record, and the pinned wp-parity verdict
    def stage_lowprec():
        lp = _lowprec_bench(T_TRAIN)
        result["extra"]["lowprec_backend_note"] = (
            f"{lp['backend']}: byte ratios exact/portable; rates are "
            "proxy off-TPU (no MXU/HBM)" if lp["backend"] != "tpu"
            else "tpu"
        )
        result["extra"]["lowprec_weight_bytes_fp32"] = lp["weight_bytes_fp32"]
        result["extra"]["lowprec_weight_bytes_int8"] = lp["weight_bytes_int8"]
        result["extra"]["lowprec_weight_bytes_ratio"] = round(
            lp["weight_bytes_ratio"], 3
        )
        result["extra"]["lowprec_infer_qps_fp32"] = _sig(lp["infer_qps_fp32"])
        result["extra"]["lowprec_infer_qps_int8"] = _sig(lp["infer_qps_int8"])
        result["extra"]["lowprec_infer_int8_vs_fp32"] = round(
            lp["infer_int8_vs_fp32"], 3
        )
        result["extra"]["lowprec_calib_batches"] = lp["calib_batches"]
        result["extra"]["lowprec_calib_max_dev"] = lp["calib_max_dev"]
        result["extra"]["lowprec_calib_mean_dev"] = lp["calib_mean_dev"]
        result["extra"]["lowprec_obs_bytes_ratio"] = round(
            lp["obs_bytes_ratio"], 3
        )
        result["extra"]["lowprec_wire_bytes_ratio"] = round(
            lp["wire_bytes_ratio"], 3
        )
        result["extra"]["lowprec_train_updates_per_sec_fp32"] = _sig(
            lp["train_updates_per_sec_fp32"]
        )
        result["extra"]["lowprec_train_updates_per_sec_int8"] = _sig(
            lp["train_updates_per_sec_int8"]
        )
        result["extra"]["lowprec_train_int8_vs_fp32"] = round(
            lp["train_int8_vs_fp32"], 3
        )
        result["extra"]["lowprec_wp"] = round(lp["wp"], 4)
        result["extra"]["lowprec_wp_games"] = lp["wp_games"]
        result["extra"]["lowprec_wp_delta"] = round(lp["wp_delta"], 4)
        result["extra"]["lowprec_wp_parity_target_met"] = lp[
            "wp_parity_target_met"
        ]
        if not lp["wp_parity_target_met"] and not QUICK:
            result["error"] = (result["error"] or "") + (
                " lowprec: |wp - 0.5| = %.4f above the 0.03 parity bar "
                "over %d games" % (lp["wp_delta"], lp["wp_games"])
            )

    _run_stage(result, "lowprec", stage_lowprec)

    # 3i. data flywheel (docs/serving.md §Data flywheel): harvest assembly
    # rate over the real wire, ingest drain bytes/s, and the quality
    # plane's promotion-gate and sentinel-demote latencies
    def stage_flywheel():
        fw = _flywheel_bench(T_TRAIN)
        result["extra"]["flywheel_episodes"] = fw["episodes"]
        result["extra"]["flywheel_harvest_eps_per_sec"] = _sig(
            fw["harvest_eps_per_sec"]
        )
        result["extra"]["flywheel_pull_episodes"] = fw["pull_episodes"]
        result["extra"]["flywheel_ingest_bytes_per_sec"] = _sig(
            fw["ingest_bytes_per_sec"]
        )
        result["extra"]["flywheel_dropped"] = fw["dropped"]
        result["extra"]["flywheel_promote_latency_ms"] = _sig(
            fw["promote_latency_ms"]
        )
        result["extra"]["flywheel_demote_ms"] = _sig(fw["demote_ms"])
        result["extra"]["flywheel_promotions"] = fw["promotions"]
        result["extra"]["flywheel_demotions"] = fw["demotions"]
        result["extra"]["flywheel_live_games"] = fw["games"]
        if fw["dropped"]:
            result["error"] = (result["error"] or "") + (
                f" flywheel: {fw['dropped']} harvested episodes dropped"
            )
        if not fw["promote_observed"]:
            result["error"] = (result["error"] or "") + (
                " flywheel: gated promotion never flipped"
            )
        if not fw["demote_observed"]:
            result["error"] = (result["error"] or "") + (
                " flywheel: quality sentinel never demoted"
            )

    _run_stage(result, "flywheel", stage_flywheel)

    # 4c. turn-mode device-resident replay: Geister DRC trained straight
    # from device rings (all-player burn-in windows, runtime/device_replay
    # turn mode) concurrent with streaming self-play — TPU-gated: on CPU
    # the DRC window compile dominates any timed window
    def stage_geister_devreplay():
        gdr = _geister_device_replay_bench(T_TRAIN)
        if "skipped" in gdr:  # benign prefill timeout, like stage 3d
            result["extra"]["geister_devreplay_note"] = gdr["skipped"]
            return
        result["extra"]["geister_devreplay_updates_per_sec"] = _sig(
            gdr["updates_per_sec"]
        )
        result["extra"]["geister_devreplay_trained_env_steps_per_sec"] = _sig(
            gdr["trained_env_steps_per_sec"], 5
        )
        result["extra"]["geister_devreplay_selfplay_env_steps_per_sec"] = _sig(
            gdr["selfplay_env_steps_per_sec"]
        )
        if not gdr["loss_finite"]:
            result["error"] = (result["error"] or "") + " geister-devreplay: non-finite loss"

    # 4d. MXU-saturation probe: the generic transformer family
    # (models/transformer.py) scaled to matmul-dominated shapes via
    # env_args.net_args, through the SAME TrainContext path as every other
    # stage — real env (Geister windows, ~full-length episodes), real
    # losses, Adam, whole-window einsum attention (the measured winner at
    # the pinned T64 shape; flash wins at T >= flash_min_t), bf16 compute
    # with fp32 master weights.  The game-net MFUs (tictactoe/geese/northstar2) are
    # honest-but-tiny because those convs are tiny; this stage states the
    # framework's MFU where the model actually offers the MXU work.
    def stage_transformer():
        import jax

        on_tpu = jax.default_backend() == "tpu"
        if on_tpu:
            # shapes from the 2026-08-01/02 v5e sweeps (tools/tune_transformer.py):
            # T64 windows amortize the step's fixed ops best (d768: MFU 0.311
            # vs 0.253 at T32), doubling batch was flat (0.247 — already
            # device-bound at B64), widening to d1024 lifts the matmul share
            # (0.347 under flash), and einsum attention at this short window
            # lifts it again: 18.6 updates/s, MFU 0.48 (2026-08-02)
            net_args = TRANSFORMER_TPU_NET_ARGS
            t_over = dict(TRANSFORMER_TPU_OVERRIDES)
        else:
            # tiny-shape coverage of the identical code path (einsum
            # attention: the Pallas kernel is TPU-only)
            net_args = {"d_model": 96, "n_heads": 4, "n_layers": 2,
                        "memory_len": 16}
            t_over = {"batch_size": 8, "burn_in_steps": 2,
                      "forward_steps": 14, "observation": True,
                      "seq_attention": "einsum"}
        # no fused variant: the k-step lax.scan of this big step compiled
        # to a SLOWER per-update program than the pipelined single-dispatch
        # loop (19.8 vs 35.1 updates/s, v5e 2026-08-01) and costs a second
        # multi-minute compile — dispatch amortization only pays when the
        # step is dispatch-bound, i.e. the tiny game nets
        tr = _train_bench(
            "Geister", t_over, T_TRAIN, n_dev,
            fill_episodes=8,
            env_overrides={"net": "transformer", "net_args": net_args},
        )
        result["extra"]["transformer_net"] = (
            f"d{net_args['d_model']} L{net_args['n_layers']} "
            f"H{net_args['n_heads']} T{t_over['burn_in_steps'] + t_over['forward_steps']} "
            f"B{t_over['batch_size']}x2p "
            + ("bf16" if t_over.get("compute_dtype") else "fp32")
        )
        result["extra"]["transformer_updates_per_sec"] = _sig(tr["updates_per_sec"])
        ups = tr["updates_per_sec"]
        tokens = (t_over["batch_size"] * 2
                  * (t_over["burn_in_steps"] + t_over["forward_steps"]))
        result["extra"]["transformer_tokens_per_sec"] = _sig(ups * tokens, 4)
        if tr["flops_per_step"]:
            result["extra"]["transformer_flops_per_step"] = tr["flops_per_step"]
            if peak:
                result["extra"]["transformer_mfu"] = _sig(
                    tr["flops_per_step"] * ups / (peak * n_dev)
                )
            else:
                result["extra"]["transformer_mfu"] = None
                result["extra"]["transformer_mfu_note"] = (
                    "no peak-FLOPs table entry for device kind "
                    f"'{getattr(devices[0], 'device_kind', '?')}'"
                )
        else:
            result["extra"]["transformer_mfu"] = None
            result["extra"]["transformer_mfu_note"] = "no flops from any lowering"
    _run_stage(result, "transformer", stage_transformer)

    # 4e. long-context transformer at production shapes (ROADMAP item 5):
    # T {64, 512, 1024} x attention mode {einsum, flash, auto} + an sp=2
    # ring leg, all through the one TrainContext training semantics — the
    # stage that records the d1536 flash-vs-einsum crossover, the remat
    # ladder's HBM headroom, and the transformer_long_mfu >= 0.40 verdict.
    # Runs on every backend: the CPU leg (tiny pins, interpret-mode
    # Pallas) is the CI smoke that keeps the sweep + auto-pick + ring
    # composition from rotting unexercised between TPU captures.
    def stage_transformer_long():
        tl = _transformer_long_bench(T_TRAIN, n_dev, peak)
        for name, p in tl["points"].items():
            key = f"transformer_long_{name}"
            result["extra"][f"{key}_updates_per_sec"] = _sig(p["updates_per_sec"])
            result["extra"][f"{key}_tokens_per_sec"] = _sig(p["tokens_per_sec"], 4)
            result["extra"][f"{key}_attn"] = p["attn"]
            result["extra"][f"{key}_remat"] = p["remat"]
            if p["mfu"] is not None:
                result["extra"][f"{key}_mfu"] = _sig(p["mfu"])
            if p["peak_bytes"]:
                result["extra"][f"{key}_peak_hbm_bytes"] = p["peak_bytes"]
        if tl["sp2"]:
            sp = tl["sp2"]
            result["extra"]["transformer_long_sp2_updates_per_sec"] = _sig(
                sp["updates_per_sec"]
            )
            result["extra"]["transformer_long_sp2_tokens_per_sec"] = _sig(
                sp["tokens_per_sec"], 4
            )
            result["extra"]["transformer_long_sp2_attn"] = sp["attn"]
            if sp["mfu"] is not None:
                result["extra"]["transformer_long_sp2_mfu"] = _sig(sp["mfu"])
        if tl["sp2_note"]:
            result["extra"]["transformer_long_sp2_note"] = tl["sp2_note"]
        if tl["remat_headroom"]:
            result["extra"]["transformer_long_remat_headroom"] = tl["remat_headroom"]
        if tl["mfu"] is not None:
            result["extra"]["transformer_long_mfu"] = _sig(tl["mfu"])
        result["extra"]["transformer_long_target_met"] = tl["target_met"]

    _run_stage(result, "transformer_long", stage_transformer_long)

    # 5. seq-attention kernel crossover (einsum vs Pallas flash, fwd+bwd)
    def stage_flash():
        result["extra"]["flash_attention"] = _flash_attention_bench()

    import jax

    if jax.default_backend() == "tpu":
        _run_stage(result, "geister-devreplay", stage_geister_devreplay)
        _run_stage(result, "flash", stage_flash)  # kernel path is TPU-only

    done.set()
    _emit_snapshot(result, final=True)


if __name__ == "__main__":
    main()
