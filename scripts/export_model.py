"""Export a trained checkpoint to a deployable StableHLO artifact.

Parity with reference scripts/make_onnx_model.py:28-58 (ONNX export with
a dynamic batch axis), TPU-native: the artifact is serialized StableHLO
with params baked in and a symbolic batch dimension, loadable by
``handyrl_tpu.models.ExportedModel`` (and by ``--eval`` via a ``.hlo``
path) without the model's python code.

Usage:
    python scripts/export_model.py <ckpt_path> [out_path]

``out_path`` ending in ``.tf`` writes a TF SavedModel via jax2tf instead
— the bridge for non-JAX runtimes (TF Serving / TFLite).  ``out_path``
ending in ``.onnx`` produces the reference's exact artifact kind via the
jaxpr -> torch bridge (``models/torch_export.py``): torch's C++ ONNX
serializer, numerics verified against jax at two batch sizes before the
file is written; no optional packages needed to EXPORT (onnxruntime is
only needed to load it back).

Reads env from ./config.yaml (like the reference reads config.yaml for
the env to export).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    import yaml

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env, prepare_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.models.export import export_model
    from handyrl_tpu.runtime.checkpoint import load_params

    ckpt = sys.argv[1] if len(sys.argv) >= 2 else "models/latest.ckpt"
    out = sys.argv[2] if len(sys.argv) >= 3 else os.path.splitext(ckpt)[0] + ".hlo"

    with open("config.yaml") as f:
        args = normalize_args(yaml.safe_load(f) or {})
    prepare_env(args["env_args"])
    env = make_env(args["env_args"])
    module = env.net()
    variables = init_variables(module, env)
    params = load_params(ckpt, variables["params"])
    env.reset()
    obs = env.observation(env.players()[0])
    if out.endswith(".tf"):  # TF SavedModel bridge (TFLite / TF Serving)
        from handyrl_tpu.models.export import export_savedmodel

        export_savedmodel(module, {"params": params}, obs, out)
    elif out.endswith(".onnx"):  # reference-parity ONNX artifact (optional dep)
        from handyrl_tpu.models.export import export_onnx

        # ``model.int8.onnx`` ships per-channel int8 kernels with explicit
        # dequantize nodes (docs/performance.md §Low-precision fast path);
        # the edge replica loads it through the same OnnxModel suffix branch
        wd = "int8" if out.endswith(".int8.onnx") else "float32"
        export_onnx(module, {"params": params}, obs, out, weight_dtype=wd)
    else:
        export_model(module, {"params": params}, obs, out)
    print(f"exported {ckpt} -> {out}")


if __name__ == "__main__":
    main()
