"""Shared training-log parsing for the plot scripts.

The learner emits two streams (runtime/learner.py): a machine-readable
``metrics.jsonl`` (one record per epoch) and human log lines whose format
is parity with the reference's stdout convention — the reference's
plotters regex-parse exactly those prefixes (win_rate_plot.py:34-45,
loss_plot.py:33-42, stats_plot.py:36-42), so both inputs work here.
"""

from __future__ import annotations

import json

import re
from typing import Any, Dict, List

_WIN_RE = re.compile(r"win rate(?: \((?P<opp>[^)]*)\))? = (?P<wr>[\d.]+) \([\d.]+ / (?P<n>\d+)\)")
_LOSS_RE = re.compile(r"loss = (?P<terms>(?:\w+:[-\d.]+ ?)+)")
_GEN_RE = re.compile(r"generation stats = (?P<mean>[-\d.]+) \+- (?P<std>[-\d.]+)")
_EPOCH_RE = re.compile(r"^epoch (?P<epoch>\d+)")
_UPDATED_RE = re.compile(r"updated model\((?P<steps>\d+)\)")


try:  # tolerate a truncated final line (killed run mid-append)
    from handyrl_tpu.utils.metrics import read_metrics as _read_metrics
except ImportError:  # standalone script use outside the repo: same logic
    def _read_metrics(path, strict=False):
        with open(path) as f:
            lines = f.readlines()
        out = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1 and not strict:
                    break  # half-written tail from a kill mid-append
                raise
        return out


def parse_records(path: str) -> List[Dict[str, Any]]:
    """Parse metrics.jsonl or a captured stdout log into epoch records."""
    with open(path) as f:
        first = f.read(1)
    if first == "{":
        return _read_metrics(path)
    return _parse_stdout(path)


def _parse_stdout(path: str) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    rec: Dict[str, Any] = {}
    with open(path) as f:
        for line in f:
            m = _EPOCH_RE.match(line)
            if m:
                if rec:
                    records.append(rec)
                rec = {"epoch": int(m.group("epoch"))}
                continue
            m = _WIN_RE.search(line)
            if m and rec:
                rec.setdefault("win_rate", {})[m.group("opp") or "total"] = float(m.group("wr"))
                rec.setdefault("eval_games", {})[m.group("opp") or "total"] = int(m.group("n"))
                continue
            m = _GEN_RE.search(line)
            if m and rec:
                rec["generation_mean"] = float(m.group("mean"))
                rec["generation_std"] = float(m.group("std"))
                continue
            m = _LOSS_RE.search(line)
            if m and rec:
                terms = {}
                for part in m.group("terms").split():
                    k, v = part.split(":")
                    terms[k] = float(v)
                rec.setdefault("loss", terms)  # first loss line after the epoch header
                continue
            m = _UPDATED_RE.search(line)
            if m and rec:
                rec["steps"] = int(m.group("steps"))
    if rec:
        records.append(rec)
    return records


def time_axis(records: List[Dict[str, Any]]) -> tuple:
    """(xs, xlabel) for plotting: prefer the records' own clocks over
    their position in the file.

    Every record since the observability plane carries ``ts`` (wall) and
    ``t_mono`` (monotonic) from the single ``_write_metrics`` seam —
    minutes-since-start on those is the honest axis (epochs are not
    equal-duration, and the record INDEX lies as soon as a resume appends
    to an old file).  ``t_mono`` wins within one process (immune to NTP
    steps) but does not survive a resume (each process has its own zero),
    so it is only used when it is monotone across the whole file; ``ts``
    is the cross-run fallback.  Files predating both fall back to
    ``epoch``, then to the record index.
    """
    monos = [r.get("t_mono") for r in records]
    if all(m is not None for m in monos) and monos == sorted(monos) and records:
        base = monos[0]
        return [(m - base) / 60.0 for m in monos], "minutes (monotonic)"
    walls = [r.get("ts") for r in records]
    if all(w is not None for w in walls) and records:
        base = walls[0]
        return [(w - base) / 60.0 for w in walls], "minutes"
    if all(r.get("epoch") is not None for r in records) and records:
        return [r["epoch"] for r in records], "epoch"
    return list(range(len(records))), "record"


def smooth(values: List[float], k: int = 5) -> List[float]:
    """Centered moving average, like the reference's smoothing windows."""
    if k <= 1 or len(values) < 3:
        return list(values)
    out = []
    for i in range(len(values)):
        lo, hi = max(0, i - k // 2), min(len(values), i + k // 2 + 1)
        out.append(sum(values[lo:hi]) / (hi - lo))
    return out


def save_or_show(fig, out_path: str | None) -> None:
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
        print(f"wrote {out_path}")
    else:
        import matplotlib.pyplot as plt

        plt.show()
