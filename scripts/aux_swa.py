"""Stochastic Weight Averaging over epoch checkpoints.

Parity with reference scripts/aux_swa.py:24-57: running equal-weight
average of params from ``models/{ed-length+1}.ckpt`` .. ``models/{ed}.ckpt``
written to ``models/swa.ckpt``, followed by a strict reload check.

Usage:
    python scripts/aux_swa.py [model_dir] [end_epoch] [length]

Defaults: model_dir=models, end_epoch=newest on disk, length=all
available.  The averaged file loads anywhere a normal checkpoint does
(same flax-msgpack tree).
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def epoch_checkpoints(model_dir: str):
    eps = []
    for fname in os.listdir(model_dir):
        m = re.fullmatch(r"(\d+)\.ckpt", fname)
        if m:
            eps.append(int(m.group(1)))
    return sorted(eps)


def main() -> None:
    from handyrl_tpu.runtime.checkpoint import load_params, model_path, save_params
    from handyrl_tpu.utils import tree_map

    model_dir = sys.argv[1] if len(sys.argv) >= 2 else "models"
    epochs = epoch_checkpoints(model_dir)
    if not epochs:
        print(f"no epoch checkpoints in {model_dir}/")
        sys.exit(1)
    end = int(sys.argv[2]) if len(sys.argv) >= 3 else epochs[-1]
    length = int(sys.argv[3]) if len(sys.argv) >= 4 else len(epochs)
    window = [e for e in epochs if end - length + 1 <= e <= end]
    if not window:
        print(f"no checkpoints in window [{end - length + 1}, {end}]")
        sys.exit(1)

    # template tree from the first snapshot; running equal-weight average
    template = load_params(model_path(model_dir, window[0]), None)
    avg = tree_map(lambda x: np.asarray(x, np.float64), template)
    for i, e in enumerate(window[1:], start=2):
        params = load_params(model_path(model_dir, e), template)
        avg = jax.tree_util.tree_map(lambda a, p: a + (np.asarray(p, np.float64) - a) / i, avg, params)
    avg = jax.tree_util.tree_map(lambda a, t: np.asarray(a, np.asarray(t).dtype), avg, template)

    out = os.path.join(model_dir, "swa.ckpt")
    save_params(out, avg)

    # strict reload check (reference aux_swa.py:50-57)
    reloaded = load_params(out, template)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6),
        avg,
        reloaded,
    )
    print(f"averaged epochs {window} -> {out}")


if __name__ == "__main__":
    main()
