"""Plot generation statistics (mean outcome ± std) over epochs.

Parity with reference scripts/stats_plot.py:32-49; also reads
metrics.jsonl directly.

Usage: python scripts/stats_plot.py <log-or-metrics-path> [out.png]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

from _logparse import parse_records, save_or_show, smooth, time_axis


def main() -> None:
    path = sys.argv[1] if len(sys.argv) >= 2 else "metrics.jsonl"
    out = sys.argv[2] if len(sys.argv) >= 3 else "stats.png"
    # None = an epoch with an explicit null record (no episodes returned)
    records = [r for r in parse_records(path) if r.get("generation_mean") is not None]
    if not records:
        print("no generation-stats records found")
        sys.exit(1)

    # records carry their own clocks (ts/t_mono) since the observability
    # plane: a real time axis instead of equal-width epochs
    xs, xlabel = time_axis(records)
    means = smooth([r["generation_mean"] for r in records])
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.plot(xs, means, label="generation mean")
    stds = [r.get("generation_std") for r in records]
    if all(s is not None for s in stds):
        lo = [m - s for m, s in zip(means, stds)]
        hi = [m + s for m, s in zip(means, stds)]
        ax.fill_between(xs, lo, hi, alpha=0.2, label="±1 std")
    ax.set_xlabel(xlabel)
    ax.set_ylabel("outcome")
    ax.legend()
    ax.set_title("generation stats")
    save_or_show(fig, out)


if __name__ == "__main__":
    main()
