"""Plot win rate per evaluation opponent over epochs.

Parity with reference scripts/win_rate_plot.py:33-51 (regex-parsed
stdout -> smoothed curves); also reads metrics.jsonl directly.

Usage: python scripts/win_rate_plot.py <log-or-metrics-path> [out.png]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

from _logparse import parse_records, save_or_show, smooth


def main() -> None:
    path = sys.argv[1] if len(sys.argv) >= 2 else "metrics.jsonl"
    out = sys.argv[2] if len(sys.argv) >= 3 else "win_rate.png"
    records = [r for r in parse_records(path) if r.get("win_rate")]
    if not records:
        print("no win-rate records found")
        sys.exit(1)

    opponents = sorted({opp for r in records for opp in r["win_rate"]})
    fig, ax = plt.subplots(figsize=(8, 5))
    for opp in opponents:
        pts = [(r["epoch"], r["win_rate"][opp]) for r in records if opp in r["win_rate"]]
        xs, ys = zip(*pts)
        ax.plot(xs, smooth(list(ys)), label=opp)
    ax.axhline(0.5, color="gray", lw=0.5, ls="--")
    ax.set_xlabel("epoch")
    ax.set_ylabel("win rate")
    ax.set_ylim(0, 1)
    ax.legend()
    ax.set_title("win rate vs opponents")
    save_or_show(fig, out)


if __name__ == "__main__":
    main()
