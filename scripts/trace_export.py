"""Export trace.jsonl span files to Chrome trace-event JSON.

Usage::

    python scripts/trace_export.py trace.jsonl [trace.rank1.jsonl ...] \
        [-o trace_export.json]

The output opens directly in ``chrome://tracing`` or https://ui.perfetto.dev.
Each input file is one process's span stream (``handyrl_tpu/utils/trace.py``
writes one per rank); the files' ``__trace_meta__`` anchors (wall-clock +
monotonic pair) align ranks whose monotonic epochs differ — each process,
and each HOST, has its own monotonic zero, so cross-host spans can only be
placed on a shared axis through the wall clock.

Mapping (deterministic, golden-pinned by tests/test_trace.py):

* one complete event (``ph: "X"``) per span, ``ts``/``dur`` in
  microseconds relative to the earliest span across all inputs;
* ``pid`` = the span's rank (so Perfetto groups tracks per process),
  ``tid`` = a stable per-rank index over the sorted thread names;
* ``cat`` = the span's ``plane`` attr when present, else ``trace``;
* process/thread name metadata events (``ph: "M"``) label the tracks.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List

try:
    from handyrl_tpu.utils.trace import META_NAME, read_trace
except ImportError:  # standalone use outside the repo: same tail tolerance
    META_NAME = "__trace_meta__"

    def read_trace(path, strict=False):
        with open(path) as f:
            lines = f.readlines()
        out = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1 and not strict:
                    break  # half-written tail from a killed run
                raise
        return out


def export_chrome(record_lists: List[List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Convert per-file span record lists into one Chrome trace dict."""
    # place every span on the shared wall-clock axis: wall_start =
    # t_mono + (meta.ts - meta.t_mono); a file with no meta (hand-built
    # fixtures) uses its monotonic values directly
    spans: List[Dict[str, Any]] = []
    for records in record_lists:
        meta = next((r for r in records if r.get("name") == META_NAME), None)
        offset = (meta["ts"] - meta["t_mono"]) if meta else 0.0
        for r in records:
            if r.get("name") == META_NAME:
                continue
            spans.append({
                "name": r.get("name", "?"),
                "start": float(r.get("t_mono", 0.0)) + offset,
                "dur": max(0.0, float(r.get("dur_s", 0.0))),
                "rank": int(r.get("rank", 0)),
                "thread": str(r.get("thread", "?")),
                "attrs": r.get("attrs") or {},
            })
    base = min((s["start"] for s in spans), default=0.0)
    threads: Dict[int, List[str]] = {}
    for s in spans:
        names = threads.setdefault(s["rank"], [])
        if s["thread"] not in names:
            names.append(s["thread"])
    tid_of = {
        (rank, name): i
        for rank, names in threads.items()
        for i, name in enumerate(sorted(names))
    }
    events: List[Dict[str, Any]] = []
    for rank in sorted(threads):
        events.append({
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"},
        })
        for name in sorted(threads[rank]):
            events.append({
                "name": "thread_name", "ph": "M", "pid": rank,
                "tid": tid_of[(rank, name)], "args": {"name": name},
            })
    for s in sorted(spans, key=lambda s: (s["rank"], s["start"], s["name"])):
        events.append({
            "name": s["name"],
            "cat": str(s["attrs"].get("plane", "trace")),
            "ph": "X",
            "ts": round((s["start"] - base) * 1e6, 3),
            "dur": round(s["dur"] * 1e6, 3),
            "pid": s["rank"],
            "tid": tid_of[(s["rank"], s["thread"])],
            "args": s["attrs"],
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+", help="trace.jsonl file(s), one per rank")
    ap.add_argument("-o", "--out", default="trace_export.json",
                    help="output path (Chrome trace-event JSON)")
    args = ap.parse_args(argv)
    record_lists = [read_trace(path) for path in args.traces]
    n_spans = sum(
        1 for recs in record_lists for r in recs if r.get("name") != META_NAME
    )
    out = export_chrome(record_lists)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(
        f"wrote {args.out}: {n_spans} span(s) from {len(record_lists)} "
        "file(s) — open in chrome://tracing or ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
