"""Plot training loss components over epochs.

Parity with reference scripts/loss_plot.py:32-49; also reads
metrics.jsonl directly.

Usage: python scripts/loss_plot.py <log-or-metrics-path> [out.png]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

from _logparse import parse_records, save_or_show, smooth


def main() -> None:
    path = sys.argv[1] if len(sys.argv) >= 2 else "metrics.jsonl"
    out = sys.argv[2] if len(sys.argv) >= 3 else "loss.png"
    records = [r for r in parse_records(path) if r.get("loss")]
    if not records:
        print("no loss records found")
        sys.exit(1)

    terms = sorted({t for r in records for t in r["loss"]})
    fig, ax = plt.subplots(figsize=(8, 5))
    for term in terms:
        pts = [(r["epoch"], r["loss"][term]) for r in records if term in r["loss"]]
        xs, ys = zip(*pts)
        ax.plot(xs, smooth(list(ys)), label=term)
    ax.set_xlabel("epoch")
    ax.set_ylabel("loss")
    ax.legend()
    ax.set_title("loss components")
    save_or_show(fig, out)


if __name__ == "__main__":
    main()
