"""Self-healing run plane (docs/fault_tolerance.md): divergence sentinel,
plane watchdog, preemption-safe drain.

The contract, pinned on the virtual CPU mesh:

* The compiled train step SKIPS any update whose loss / grad global-norm
  / lr is nonfinite (the flag rides back with the existing metrics — no
  extra host sync), so a single NaN can never poison params or Adam
  moments; with ``sentinel: false`` the step is bit-identical to the
  pre-sentinel one and the poison lands (the old failure mode).
* The host-side loss-spike EMA detector extends the same
  consecutive-bad streak, and the streak escalates to a rollback onto
  the newest VERIFIED manifest checkpoint.
* The plane watchdog restarts a dead/stalled rollout thread up to
  ``plane_max_restarts``, then degrades split -> fused loudly.
* SIGTERM/SIGINT drain the run into a final manifest-verified
  checkpoint and exit resumable (75), composing with ``restart_epoch:
  -1`` for a full preempt -> resume loop.

Fast tests run in the tier-1 sweep; the injection-driven end-to-ends are
marked ``slow`` and run standalone in CI under ``-m sentinel`` on the
4-virtual-device mesh.
"""

import json
import os
import random
import threading
import time

import jax
import numpy as np
import pytest

import handyrl_tpu.runtime.checkpoint as cp
from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime import faults
from handyrl_tpu.runtime.trainer import SENTINEL_EVENT_KEYS, Trainer
from handyrl_tpu.utils import read_metrics

pytestmark = pytest.mark.sentinel

needs4 = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >= 4 (virtual) devices"
)


# ------------------------------------------------------------ injection env


def test_fault_env_parsing(monkeypatch):
    for var in ("HANDYRL_FAULT_NAN_AT_STEP", "HANDYRL_FAULT_WEDGE_ROLLOUT",
                "HANDYRL_FAULT_SIGTERM_AT_STEP"):
        monkeypatch.delenv(var, raising=False)
    assert faults.nan_window() is None
    assert faults.wedge_rollout() is None
    assert faults.sigterm_at_step() is None

    monkeypatch.setenv("HANDYRL_FAULT_NAN_AT_STEP", "7")
    assert faults.nan_window() == (7, 1)
    monkeypatch.setenv("HANDYRL_FAULT_NAN_AT_STEP", "7:3")
    assert faults.nan_window() == (7, 3)

    monkeypatch.setenv("HANDYRL_FAULT_WEDGE_ROLLOUT", "2")
    assert faults.wedge_rollout() == (2, False)
    monkeypatch.setenv("HANDYRL_FAULT_WEDGE_ROLLOUT", "2:all")
    assert faults.wedge_rollout() == (2, True)
    # a typo'd injection must raise, not silently not-inject (a fake
    # green e2e is worse than a red one)
    monkeypatch.setenv("HANDYRL_FAULT_WEDGE_ROLLOUT", "2:first")
    with pytest.raises(ValueError):
        faults.wedge_rollout()

    monkeypatch.setenv("HANDYRL_FAULT_SIGTERM_AT_STEP", "11")
    assert faults.sigterm_at_step() == 11


# ------------------------------------------------- crash-safe metrics.jsonl


def test_read_metrics_tolerates_truncated_tail_only(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    good = [{"epoch": 1, "steps": 10}, {"epoch": 2, "steps": 20}]
    with open(path, "w") as f:
        for rec in good:
            f.write(json.dumps(rec) + "\n")
        f.write('{"epoch": 3, "st')  # killed mid-append

    assert read_metrics(path) == good
    # strict mode surfaces the truncation instead of hiding it
    with pytest.raises(ValueError):
        read_metrics(path, strict=True)

    # mid-file corruption is NOT the append protocol's signature: raise
    bad = str(tmp_path / "corrupt.jsonl")
    with open(bad, "w") as f:
        f.write('{"epoch": 1}\n')
        f.write("garbage\n")
        f.write('{"epoch": 2}\n')
    with pytest.raises(ValueError):
        read_metrics(bad)


def test_write_metrics_is_one_flushed_line_per_record(tmp_path):
    """One write() + flush + fsync per record: re-reading right after the
    call must see the full line (no buffered half-records a kill could
    truncate beyond the final line)."""
    from handyrl_tpu.runtime.learner import Learner

    path = str(tmp_path / "metrics.jsonl")

    class Stub:
        args = {"metrics_path": path}
        _repair_metrics_tail = Learner._repair_metrics_tail

    for epoch in (1, 2):
        Learner._write_metrics(Stub(), {"epoch": epoch, "win_rate": None})
        last = read_metrics(path)[-1]
        # the single timestamp seam stamps both clocks onto every record
        assert last.pop("ts") > 0 and last.pop("t_mono") > 0
        assert last == {"epoch": epoch, "win_rate": None}
    assert len(read_metrics(path)) == 2


def test_resumed_run_repairs_truncated_metrics_tail(tmp_path):
    """A relaunch after a kill mid-append must DROP the half-written tail
    before appending: gluing the resumed run's first record onto it would
    turn tolerated end-of-file truncation into mid-file corruption every
    reader refuses."""
    from handyrl_tpu.runtime.learner import Learner

    path = str(tmp_path / "metrics.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"epoch": 1}) + "\n")
        f.write('{"epoch": 2, "st')  # the kill window

    class Stub:
        args = {"metrics_path": path}
        _repair_metrics_tail = Learner._repair_metrics_tail

    stub = Stub()  # fresh process: tail check re-arms
    Learner._write_metrics(stub, {"epoch": 2})
    Learner._write_metrics(stub, {"epoch": 3})
    # strict: NO invalid line survives anywhere in the file (the appended
    # records additionally carry the ts/t_mono timestamp seam)
    records = read_metrics(path, strict=True)
    assert [r["epoch"] for r in records] == [1, 2, 3]
    assert all("ts" in r and "t_mono" in r for r in records[1:])


# ----------------------------------------------------- in-step finite check


def _train_setup(sentinel: bool):
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.batch import make_batch
    from handyrl_tpu.runtime.generation import Generator
    from handyrl_tpu.runtime.replay import EpisodeStore

    targs = normalize_args(
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 8,
                "sentinel": sentinel,
            },
        }
    )["train_args"]
    random.seed(0)
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env, seed=0)
    model = InferenceModel(module, variables)
    gen = Generator(env, targs)
    models = {p: model for p in env.players()}
    gargs = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    store = EpisodeStore(100)
    while len(store) < 10:
        ep = gen.generate(models, gargs)
        if ep is not None:
            store.extend([ep])
    mesh = make_mesh({"dp": -1})
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(variables["params"])
    batch = ctx.put_batch(
        make_batch([store.sample_window(8, 0, 4) for _ in range(8)], targs)
    )
    return ctx, state, batch


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(tree))]


def test_in_step_sentinel_skips_nonfinite_update():
    """A NaN lr (the injection's poison vector — same flag path as a NaN
    loss or grad) must leave params AND Adam moments bit-identical, zero
    the step's loss contributions, and raise the sentinel_bad flag."""
    ctx, state, batch = _train_setup(sentinel=True)
    state1, m1 = ctx.train_step(state, batch, 1e-5)
    assert float(jax.device_get(m1["sentinel_bad"])) == 0.0
    # the step donates its input state: snapshot to host BEFORE stepping on
    host1 = jax.device_get(state1)

    state2, m2 = ctx.train_step(state1, batch, float("nan"))
    host2 = jax.device_get(state2)
    assert float(jax.device_get(m2["sentinel_bad"])) == 1.0
    # the skipped step contributes nothing to the epoch's loss averages
    assert float(jax.device_get(m2["total"])) == 0.0
    assert float(jax.device_get(m2["dcnt"])) == 0.0
    # params and optimizer state byte-identical to before the bad step
    for a, b in zip(_leaves(host1["params"]), _leaves(host2["params"])):
        assert np.array_equal(a, b)
    for a, b in zip(_leaves(host1["opt_state"]), _leaves(host2["opt_state"])):
        assert np.array_equal(a, b)
    # the step counter stays monotone (lr schedule / publish versions)
    assert int(host2["steps"]) == int(host1["steps"]) + 1

    # ... and the run keeps learning afterwards: the next finite step
    # moves params again
    state3, m3 = ctx.train_step(state2, batch, 1e-5)
    host3 = jax.device_get(state3)
    assert float(jax.device_get(m3["sentinel_bad"])) == 0.0
    assert np.isfinite(float(jax.device_get(m3["total"])))
    moved = any(
        not np.array_equal(a, b)
        for a, b in zip(_leaves(host2["params"]), _leaves(host3["params"]))
    )
    assert moved


def test_sentinel_off_reproduces_the_poisoning_failure_mode():
    """``sentinel: false`` is the pre-sentinel step: a NaN lr lands in the
    params forever (why the sentinel defaults on)."""
    ctx, state, batch = _train_setup(sentinel=False)
    state1, m1 = ctx.train_step(state, batch, float("nan"))
    assert "sentinel_bad" not in m1
    poisoned = any(
        not np.isfinite(leaf).all() for leaf in _leaves(state1["params"])
    )
    assert poisoned


def test_sentinel_happy_path_bit_identical_to_off():
    """With finite inputs the guarded step must produce byte-identical
    params to the unguarded one — the sentinel costs a predicate and a
    select, never a different numeric path."""
    ctx_on, state_on, batch_on = _train_setup(sentinel=True)
    ctx_off, state_off, batch_off = _train_setup(sentinel=False)
    s_on, _ = ctx_on.train_step(state_on, batch_on, 1e-5)
    s_off, _ = ctx_off.train_step(state_off, batch_off, 1e-5)
    for a, b in zip(_leaves(s_on["params"]), _leaves(s_off["params"])):
        assert np.array_equal(a, b)


# ------------------------------------------------ host spike detector unit


def _bare_trainer(rollback_after=3, spike_factor=10.0, fused=1):
    t = object.__new__(Trainer)
    t.sentinel = True
    t.sentinel_rollback_after = rollback_after
    t._spike_factor = spike_factor
    t._loss_ema_decay = 0.9
    t._loss_ema = None
    t._sentinel_streak = 0
    t.sentinel_events = {k: 0 for k in SENTINEL_EVENT_KEYS}
    t.fused = fused
    t.cadence = None  # single-process: no multi-host rollback broadcasts
    t.rolled = 0
    t._sentinel_rollback = lambda: setattr(t, "rolled", t.rolled + 1) or _reset(t)
    return t


def _reset(t):
    t._sentinel_streak = 0
    t._loss_ema = None


def _m(total=1.0, dcnt=1.0, bad=0.0):
    return {"total": total, "dcnt": dcnt, "sentinel_bad": bad}


def test_spike_detector_streak_escalates_and_resets():
    t = _bare_trainer(rollback_after=3)
    # warm the EMA with clean steps
    t._sentinel_account([_m(1.0), _m(1.1), _m(0.9)])
    assert t._sentinel_streak == 0 and t.rolled == 0
    # two spikes + one in-step skip = streak 3 -> rollback
    t._sentinel_account([_m(50.0), _m(60.0), _m(bad=1.0)])
    assert t.rolled == 1
    assert t.sentinel_events["sentinel_spike_steps"] == 2
    assert t.sentinel_events["sentinel_skipped_steps"] == 1

    # a clean step RESETS the streak: isolated spikes never escalate
    t2 = _bare_trainer(rollback_after=3)
    t2._sentinel_account([_m(1.0), _m(1.0)])
    t2._sentinel_account([_m(50.0), _m(1.0), _m(50.0), _m(1.0), _m(50.0)])
    assert t2.rolled == 0
    assert t2.sentinel_events["sentinel_spike_steps"] == 3


def test_spike_detector_ema_ignores_bad_steps():
    """A diverging loss must not drag the EMA baseline up: after a run of
    spikes the detector still judges against the pre-spike EMA."""
    t = _bare_trainer(rollback_after=100)
    t._sentinel_account([_m(1.0), _m(1.0)])
    ema0 = t._loss_ema
    t._sentinel_account([_m(500.0), _m(900.0)])
    assert t._loss_ema == ema0  # spikes never fed the EMA
    # a loss 10x the REAL baseline still counts as a spike
    t._sentinel_account([_m(20.0)])
    assert t.sentinel_events["sentinel_spike_steps"] == 3


def test_rollback_without_verified_snapshot_keeps_params(tmp_path):
    """The escalation with nothing to roll back to must not crash: the
    streak resets and the run continues (the in-step skip already
    suppressed the bad updates)."""
    t = _bare_trainer(rollback_after=1)
    t._sentinel_rollback = Trainer._sentinel_rollback.__get__(t)
    t.args = {"model_dir": str(tmp_path / "models"), "seed": 0}
    t._sentinel_streak = 5
    t._sentinel_rollback()  # no manifest at all
    assert t._sentinel_streak == 0
    assert t.sentinel_events["sentinel_rollbacks"] == 0


# -------------------------------------------------- watchdog escalation


def test_watchdog_restarts_then_degrades():
    """A dead rollout thread burns the restart budget, then a split-plane
    run degrades to fused and the watchdog keeps supervising the new
    plane (returning only once it is fused AND out of budget)."""
    from handyrl_tpu.runtime.learner import WATCHDOG_EVENT_KEYS, Learner

    lrn = object.__new__(Learner)
    lrn.args = {"plane_stall_timeout": 0.2, "plane_max_restarts": 1,
                "plane_param_lag_bound": 0}
    lrn.shutdown_flag = False
    lrn._drain_requested = False
    lrn._plane = "split"
    lrn._param_cache = None
    lrn._watchdog_events = {k: 0 for k in WATCHDOG_EVENT_KEYS}
    lrn._rollout_progress_t = time.monotonic()
    calls = {"restarts": 0, "degrades": 0}

    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    lrn._rollout_thread = dead

    def fake_restart():
        calls["restarts"] += 1
        lrn._watchdog_events["plane_watchdog_restarts"] += 1
        lrn._rollout_progress_t = time.monotonic()
        return dead  # the restarted thread dies again immediately

    def fake_degrade():
        calls["degrades"] += 1
        lrn._watchdog_events["plane_watchdog_degraded"] = 1
        lrn._plane = "fused"  # the real degrade flips the topology

    lrn._start_rollout_thread = fake_restart
    lrn._degrade_to_fused = fake_degrade

    t = threading.Thread(target=lrn._watchdog_loop, daemon=True)
    t.start()
    t.join(timeout=30.0)
    assert not t.is_alive(), "watchdog never escalated through its ladder"
    assert calls == {"restarts": 1, "degrades": 1}
    assert lrn._watchdog_events["plane_watchdog_stalls"] >= 2
    assert lrn._watchdog_events["plane_watchdog_degraded"] == 1


def test_watchdog_stall_waits_for_first_dispatch():
    """First-dispatch silence is jit compile time, not a stall: an ALIVE
    thread that has not completed a dispatch yet must never trip the
    stall detector (restarting mid-compile would burn the whole budget on
    a healthy warm-up); the first completed dispatch arms it."""
    from handyrl_tpu.runtime.learner import WATCHDOG_EVENT_KEYS, Learner

    lrn = object.__new__(Learner)
    lrn.args = {"plane_stall_timeout": 0.15, "plane_max_restarts": 5,
                "plane_param_lag_bound": 0}
    lrn.shutdown_flag = False
    lrn._drain_requested = False
    lrn._plane = "fused"
    lrn._param_cache = None
    lrn._watchdog_events = {k: 0 for k in WATCHDOG_EVENT_KEYS}
    lrn._rollout_progress_t = time.monotonic()
    lrn._rollout_dispatched = False      # "still compiling"
    stop = threading.Event()
    alive = threading.Thread(target=stop.wait, daemon=True)
    alive.start()
    lrn._rollout_thread = alive
    lrn._start_rollout_thread = lambda: (_ for _ in ()).throw(
        AssertionError("restarted a compiling thread")
    )

    t = threading.Thread(target=lrn._watchdog_loop, daemon=True)
    t.start()
    try:
        time.sleep(0.6)  # 4x the timeout with no beat: still no stall
        assert lrn._watchdog_events["plane_watchdog_stalls"] == 0
        # first dispatch lands -> detection arms -> the next silent
        # window IS a stall
        lrn._start_rollout_thread = lambda: setattr(
            lrn, "_rollout_progress_t", time.monotonic()
        )
        lrn._rollout_dispatched = True
        deadline = time.monotonic() + 10.0
        while (
            not lrn._watchdog_events["plane_watchdog_stalls"]
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert lrn._watchdog_events["plane_watchdog_stalls"] >= 1
    finally:
        lrn.shutdown_flag = True
        stop.set()
        t.join(timeout=10.0)


# ------------------------------------------------------- config validation


def test_config_validates_sentinel_knobs():
    def check(**over):
        return normalize_args(
            {"env_args": {"env": "TicTacToe"}, "train_args": over}
        )

    check(sentinel=False)  # knob exists and validates
    with pytest.raises(ValueError):
        check(sentinel_rollback_after=0)
    with pytest.raises(ValueError):
        check(sentinel_spike_factor=1.0)
    with pytest.raises(ValueError):
        check(sentinel_loss_ema_decay=1.0)
    with pytest.raises(ValueError):
        check(plane_stall_timeout=0)
    with pytest.raises(ValueError):
        check(plane_max_restarts=-1)
    with pytest.raises(ValueError):
        check(plane_param_lag_bound=-1)
    with pytest.raises(ValueError):
        check(drain_deadline_seconds=0)


# --------------------------------------------------- injection end-to-ends


def _device_replay_args(**over):
    train = {
        "mesh": {"dp": 2},
        "turn_based_training": False,
        "observation": False,
        "batch_size": 8,
        "forward_steps": 4,
        "burn_in_steps": 0,
        "device_rollout_games": 8,
        "device_replay": True,
        "device_replay_slots": 64,
        "device_replay_k_steps": 16,
        "minimum_episodes": 20,
        "update_episodes": 30,
        "maximum_episodes": 400,
        "epochs": 3,
        "num_batchers": 1,
        "eval_rate": 0.0,
        "worker": {"num_parallel": 1},
    }
    train.update(over)
    return normalize_args(
        {"env_args": {"env": "ParallelTicTacToe"}, "train_args": train}
    )


@pytest.mark.slow
def test_nan_injection_skips_rolls_back_and_finishes(tmp_path, monkeypatch):
    """The headline e2e: with a NaN poisoning every lr from step 5 on
    (epoch 1 trains clean and lands a verified checkpoint first), the run
    skips every poisoned update, escalates the streak to a verified-
    checkpoint rollback, and still finishes with finite params and the
    sentinel_* counters in metrics.jsonl."""
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("HANDYRL_FAULT_NAN_AT_STEP", "5:1000000")
    # epochs are EPISODE-counted and device generation floods the books,
    # so a slow/loaded host fits only ~1 SGD step per epoch — with 4
    # epochs the run could end at exactly step 5 (the fault onset) with
    # every recorded epoch still clean, flaking the assertions below.
    # 8 epochs guarantees the recorded run crosses the fault window with
    # the SAME assertions (observed marginal on this container 2026-08-04)
    args = _device_replay_args(sentinel_rollback_after=2, epochs=8)
    learner = Learner(args)
    assert learner.run() == 0

    records = read_metrics("metrics.jsonl")
    assert records and records[-1]["steps"] > 5
    last = records[-1]
    # cumulative counters: poisoned steps were skipped, and at least one
    # streak escalated to a rollback onto a verified snapshot
    assert last["sentinel_skipped_steps"] > 0
    assert last["sentinel_rollbacks"] >= 1
    # loss stayed finite through the whole run (the pre-sentinel run ends
    # with loss=nan everywhere)
    for rec in records:
        for v in (rec.get("loss") or {}).values():
            assert np.isfinite(v)
    # ... and so did the params that came out the other end
    for leaf in jax.tree.leaves(learner.trainer.state_host["params"]):
        assert np.isfinite(np.asarray(leaf)).all()
    # the rollback target still exists (GC pinned it)
    assert cp.latest_verified_epoch("models") > 0


@needs4
@pytest.mark.slow
def test_wedged_split_plane_degrades_to_fused_and_finishes(tmp_path, monkeypatch):
    """A rollout thread that wedges after 2 dispatches (simulated stuck
    XLA execute) trips the watchdog; with a zero restart budget the split
    run degrades to fused, keeps generating on the learner mesh, and
    completes its epochs."""
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("HANDYRL_FAULT_WEDGE_ROLLOUT", "2")
    args = _device_replay_args(
        plane="split",
        actor_chips=2,
        param_refresh_updates=2,
        plane_stall_timeout=1.0,
        plane_max_restarts=0,
        epochs=2,
    )
    learner = Learner(args)
    assert learner.run() == 0

    assert os.path.exists("models/latest.ckpt")
    records = read_metrics("metrics.jsonl")
    last = records[-1]
    assert last["steps"] > 0                      # training kept going
    assert last["plane"] == "fused"               # topology flipped loudly
    assert last["plane_watchdog_stalls"] >= 1
    assert last["plane_watchdog_degraded"] == 1
    assert learner._plane == "fused"
    for v in (last.get("loss") or {}).values():
        assert np.isfinite(v)


@pytest.mark.slow
def test_sigterm_drains_to_verified_checkpoint_and_resumes(tmp_path, monkeypatch):
    """Preemption loop: SIGTERM mid-epoch -> pipelines drain -> final
    manifest-verified checkpoint -> exit resumable (75) -> a relaunch
    with ``restart_epoch: -1`` picks the drain checkpoint up and
    finishes."""
    from handyrl_tpu.runtime.learner import EXIT_RESUMABLE, Learner

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("HANDYRL_FAULT_SIGTERM_AT_STEP", "6")
    args = _device_replay_args(epochs=50, drain_deadline_seconds=45.0)
    learner = Learner(args)
    code = learner.run()
    assert code == EXIT_RESUMABLE

    drain_epoch = cp.latest_verified_epoch("models")
    assert drain_epoch > 0                        # the drain's final save
    assert cp.verify_snapshot("models", drain_epoch)
    # a truncated metrics tail from the kill window must not break readers
    records = read_metrics("metrics.jsonl") if os.path.exists("metrics.jsonl") else []

    # relaunch the way a supervisor would: auto-resume, run to completion
    # (epochs is an ABSOLUTE target vs model_epoch: one more than the
    # drain checkpoint = one full resumed epoch)
    monkeypatch.delenv("HANDYRL_FAULT_SIGTERM_AT_STEP")
    args2 = _device_replay_args(epochs=drain_epoch + 1, restart_epoch=-1)
    resumed = Learner(args2)
    assert resumed.model_epoch == drain_epoch     # landed on the drain save
    assert resumed.run() == 0
    assert resumed.model_epoch > drain_epoch      # and made progress past it
    final = read_metrics("metrics.jsonl")
    assert len(final) >= len(records)
