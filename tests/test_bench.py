"""Unit coverage for bench.py's capture-reliability layer (round 4).

Three rounds of driver captures were lost to exactly these paths — a
wedged chip lease surrendered after one probe (BENCH_r02/r03 "CPU
fallback"), and a transient tunnel error nulling a whole stage (r3s3
flash stage) — so the wait-out loop, the stage retry, and the
partial-result rollback get direct tests.  The probe subprocess is
monkeypatched; no accelerator is touched.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


@pytest.fixture(autouse=True)
def _fast_sleep(monkeypatch):
    """The wait loop sleeps minutes between re-probes; record instead."""
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    yield sleeps


def test_env_float_parses_and_falls_back(monkeypatch):
    monkeypatch.delenv("X_BENCH_T", raising=False)
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    monkeypatch.setenv("X_BENCH_T", "3")
    assert bench._env_float("X_BENCH_T", 7.5) == 3.0
    monkeypatch.setenv("X_BENCH_T", "junk")
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    # set-but-empty (CI interpolation of an unset variable) means default,
    # NOT 0 — 0 would silently disable the lease wait / watchdog
    monkeypatch.setenv("X_BENCH_T", "")
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    monkeypatch.setenv("X_BENCH_T", "0")
    assert bench._env_float("X_BENCH_T", 7.5) == 0.0


def test_hung_probe_is_reprobed_until_budget(monkeypatch, _fast_sleep):
    """A hung probe (wedged lease) must be re-probed on a backoff loop —
    not surrendered after one try (the r02/r03 failure) — and fall back
    to CPU only once the BENCH_TPU_WAIT budget is spent."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    probes = []

    def fake_probe(timeout=120.0):
        probes.append(timeout)
        return ("hung", "accelerator backend init hung >120s")

    monkeypatch.setattr(bench, "_probe_accelerator", fake_probe)
    # wall clock advances only with sleep(); probe itself is instant here,
    # so the loop runs until the sleeps alone exhaust the budget
    t = [0.0]
    monkeypatch.setattr(bench.time, "perf_counter", lambda: t[0])
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: t.__setitem__(0, t[0] + s)
    )

    devices, err = bench._devices_with_retry()
    assert len(probes) > 3, "hung probe was not persistently re-probed"
    assert err and "CPU fallback" in err and "hung" in err
    assert devices is not None and devices[0].platform == "cpu"


def test_hung_probe_wait_disabled(monkeypatch, _fast_sleep):
    """BENCH_TPU_WAIT=0 keeps the old immediate-fallback behavior."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "0")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda timeout=120.0: probes.append(1) or ("hung", "hung >120s"),
    )
    devices, err = bench._devices_with_retry()
    assert len(probes) == 1
    assert err and "CPU fallback" in err


def test_failed_probe_keeps_short_retries(monkeypatch, _fast_sleep):
    """A quick FAILURE (probe raises, not hangs) retries a bounded number
    of times on the short delay, not the 30-min lease budget."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda timeout=120.0: probes.append(1) or ("failed", "UNAVAILABLE"),
    )
    devices, err = bench._devices_with_retry(retries=3, delay=1.0)
    assert len(probes) == 3
    assert err and "UNAVAILABLE" in err and "CPU fallback" in err


def test_run_stage_rolls_back_partial_writes(_fast_sleep):
    """A stage that dies after recording throughput must not leave numbers
    that read as measured; every attempt's traceback is kept."""
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    calls = []

    def stage():
        calls.append(1)
        result["extra"]["partial"] = 123
        result["value"] = 999.0
        raise RuntimeError(f"boom{len(calls)}")

    out = bench._run_stage(result, "s", stage, retry_delay=0.0)
    assert out is None and len(calls) == 2
    assert "partial" not in result["extra"] and result["value"] is None
    assert "attempt 1" in result["error"] and "attempt 2" in result["error"]
    assert "boom1" in result["error"] and "boom2" in result["error"]


def test_run_stage_retry_succeeds_and_keeps_writes(_fast_sleep):
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    calls = []

    def stage():
        calls.append(1)
        if len(calls) == 1:
            result["extra"]["junk"] = 1  # partial write from the failure
            raise ConnectionRefusedError("remote_compile: Connection refused")
        result["extra"]["rate"] = 42.0
        return "ok"

    assert bench._run_stage(result, "s", stage, retry_delay=0.0) == "ok"
    assert result["error"] is None
    assert result["extra"] == {"rate": 42.0}


def test_stage_filter_parsing(monkeypatch):
    monkeypatch.delenv("BENCH_STAGES", raising=False)
    assert bench._stage_filter() is None
    # set-but-empty (CI interpolation) means all stages, not none
    monkeypatch.setenv("BENCH_STAGES", "")
    assert bench._stage_filter() is None
    monkeypatch.setenv("BENCH_STAGES", "transformer, flash")
    assert bench._stage_filter() == {"transformer", "flash"}


def test_stage_filter_expands_dependencies(monkeypatch):
    """BENCH_STAGES=northstar2 must also run geese-train: the dependent
    stages are gated on its result in main() and would otherwise be
    silently skipped with no numbers and no note."""
    monkeypatch.setenv("BENCH_STAGES", "northstar2")
    assert bench._stage_filter() == {"northstar2", "geese-train"}
    # the dependency map only names real stages
    for k, deps in bench.STAGE_DEPS.items():
        assert k in bench.KNOWN_STAGES
        assert set(deps) <= set(bench.KNOWN_STAGES)


def test_stage_filter_skips_unlisted_stages(monkeypatch, _fast_sleep):
    """With BENCH_STAGES set, unlisted stages never run (their fn is not
    called) and are recorded in extra.stages_skipped; listed ones run."""
    monkeypatch.setenv("BENCH_STAGES", "keep")
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    ran = []
    assert bench._run_stage(result, "drop", lambda: ran.append("drop")) is None
    assert bench._run_stage(result, "keep", lambda: ran.append("keep") or "ok") == "ok"
    assert ran == ["keep"]
    assert result["extra"]["stages_skipped"] == ["drop"]
    assert result["error"] is None


def test_known_stages_matches_run_stage_call_sites():
    """KNOWN_STAGES is the BENCH_STAGES validation whitelist; a stage
    added to main() without updating it would be impossible to select
    (the filter would reject its name as unknown).  Parse the source for
    _run_stage call sites and pin exact agreement."""
    import re
    from pathlib import Path

    src = Path(bench.__file__).read_text()
    called = set(re.findall(r'_run_stage\(result, "([^"]+)"', src))
    assert called == set(bench.KNOWN_STAGES), (
        f"KNOWN_STAGES drift: called-but-unknown {called - set(bench.KNOWN_STAGES)}, "
        f"known-but-never-called {set(bench.KNOWN_STAGES) - called}"
    )


def test_sig_preserves_small_rates():
    assert bench._sig(0.0021234) == 0.00212
    assert bench._sig(None) is None
    assert bench._sig(0) == 0
    assert bench._sig(123456.0) == 123456.0  # never truncates above the decimal
