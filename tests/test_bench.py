"""Unit coverage for bench.py's capture-reliability layer (round 4).

Three rounds of driver captures were lost to exactly these paths — a
wedged chip lease surrendered after one probe (BENCH_r02/r03 "CPU
fallback"), and a transient tunnel error nulling a whole stage (r3s3
flash stage) — so the wait-out loop, the stage retry, and the
partial-result rollback get direct tests.  The probe subprocess is
monkeypatched; no accelerator is touched.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


@pytest.fixture(autouse=True)
def _fast_sleep(monkeypatch):
    """The wait loop sleeps minutes between re-probes; record instead."""
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    yield sleeps


@pytest.fixture(autouse=True)
def _snapshot_tmp(monkeypatch, tmp_path):
    """Stage runs now emit snapshot side files; keep them out of the repo."""
    monkeypatch.setenv("BENCH_SNAPSHOT", str(tmp_path / "snap.json"))
    yield tmp_path / "snap.json"


def test_env_float_parses_and_falls_back(monkeypatch):
    monkeypatch.delenv("X_BENCH_T", raising=False)
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    monkeypatch.setenv("X_BENCH_T", "3")
    assert bench._env_float("X_BENCH_T", 7.5) == 3.0
    monkeypatch.setenv("X_BENCH_T", "junk")
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    # set-but-empty (CI interpolation of an unset variable) means default,
    # NOT 0 — 0 would silently disable the lease wait / watchdog
    monkeypatch.setenv("X_BENCH_T", "")
    assert bench._env_float("X_BENCH_T", 7.5) == 7.5
    monkeypatch.setenv("X_BENCH_T", "0")
    assert bench._env_float("X_BENCH_T", 7.5) == 0.0


def test_hung_probe_is_reprobed_until_budget(monkeypatch, _fast_sleep):
    """A hung probe (wedged lease) must be re-probed on a backoff loop —
    not surrendered after one try (the r02/r03 failure) — and fall back
    to CPU only once the BENCH_TPU_WAIT budget is spent."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    probes = []

    def fake_probe(timeout=120.0):
        probes.append(timeout)
        return ("hung", "accelerator backend init hung >120s")

    monkeypatch.setattr(bench, "_probe_accelerator", fake_probe)
    # wall clock advances only with sleep(); probe itself is instant here,
    # so the loop runs until the sleeps alone exhaust the budget
    t = [0.0]
    monkeypatch.setattr(bench.time, "perf_counter", lambda: t[0])
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: t.__setitem__(0, t[0] + s)
    )

    devices, err = bench._devices_with_retry()
    assert len(probes) > 3, "hung probe was not persistently re-probed"
    assert err and "CPU fallback" in err and "hung" in err
    assert devices is not None and devices[0].platform == "cpu"


def test_hung_probe_wait_disabled(monkeypatch, _fast_sleep):
    """BENCH_TPU_WAIT=0 keeps the old immediate-fallback behavior."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "0")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda timeout=120.0: probes.append(1) or ("hung", "hung >120s"),
    )
    devices, err = bench._devices_with_retry()
    assert len(probes) == 1
    assert err and "CPU fallback" in err


def test_failed_probe_keeps_short_retries(monkeypatch, _fast_sleep):
    """A quick FAILURE (probe raises, not hangs) retries a bounded number
    of times on the short delay, not the 30-min lease budget."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda timeout=120.0: probes.append(1) or ("failed", "UNAVAILABLE"),
    )
    devices, err = bench._devices_with_retry(retries=3, delay=1.0)
    assert len(probes) == 3
    assert err and "UNAVAILABLE" in err and "CPU fallback" in err


def test_run_stage_rolls_back_partial_writes(_fast_sleep):
    """A stage that dies after recording throughput must not leave numbers
    that read as measured; every attempt's traceback is kept."""
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    calls = []

    def stage():
        calls.append(1)
        result["extra"]["partial"] = 123
        result["value"] = 999.0
        raise RuntimeError(f"boom{len(calls)}")

    out = bench._run_stage(result, "s", stage, retry_delay=0.0)
    assert out is None and len(calls) == 2
    assert "partial" not in result["extra"] and result["value"] is None
    assert "attempt 1" in result["error"] and "attempt 2" in result["error"]
    assert "boom1" in result["error"] and "boom2" in result["error"]


def test_run_stage_retry_succeeds_and_keeps_writes(_fast_sleep):
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    calls = []

    def stage():
        calls.append(1)
        if len(calls) == 1:
            result["extra"]["junk"] = 1  # partial write from the failure
            raise ConnectionRefusedError("remote_compile: Connection refused")
        result["extra"]["rate"] = 42.0
        return "ok"

    assert bench._run_stage(result, "s", stage, retry_delay=0.0) == "ok"
    assert result["error"] is None
    assert result["extra"] == {"rate": 42.0}


def test_stage_filter_parsing(monkeypatch):
    monkeypatch.delenv("BENCH_STAGES", raising=False)
    assert bench._stage_filter() is None
    # set-but-empty (CI interpolation) means all stages, not none
    monkeypatch.setenv("BENCH_STAGES", "")
    assert bench._stage_filter() is None
    monkeypatch.setenv("BENCH_STAGES", "transformer, flash")
    assert bench._stage_filter() == {"transformer", "flash"}


def test_stage_filter_expands_dependencies(monkeypatch):
    """BENCH_STAGES=northstar2 must also run geese-train: the dependent
    stages are gated on its result in main() and would otherwise be
    silently skipped with no numbers and no note."""
    monkeypatch.setenv("BENCH_STAGES", "northstar2")
    assert bench._stage_filter() == {"northstar2", "geese-train"}
    # the dependency map only names real stages
    for k, deps in bench.STAGE_DEPS.items():
        assert k in bench.KNOWN_STAGES
        assert set(deps) <= set(bench.KNOWN_STAGES)


def test_stage_filter_skips_unlisted_stages(monkeypatch, _fast_sleep):
    """With BENCH_STAGES set, unlisted stages never run (their fn is not
    called) and are recorded in extra.stages_skipped; listed ones run."""
    monkeypatch.setenv("BENCH_STAGES", "keep")
    result = {"value": None, "vs_baseline": None, "error": None, "extra": {}}
    ran = []
    assert bench._run_stage(result, "drop", lambda: ran.append("drop")) is None
    assert bench._run_stage(result, "keep", lambda: ran.append("keep") or "ok") == "ok"
    assert ran == ["keep"]
    assert result["extra"]["stages_skipped"] == ["drop"]
    assert result["error"] is None


def test_known_stages_matches_run_stage_call_sites():
    """KNOWN_STAGES is the BENCH_STAGES validation whitelist; a stage
    added to main() without updating it would be impossible to select
    (the filter would reject its name as unknown).  Parse the source for
    _run_stage call sites and pin exact agreement."""
    import re
    from pathlib import Path

    src = Path(bench.__file__).read_text()
    called = set(re.findall(r'_run_stage\(result, "([^"]+)"', src))
    assert called == set(bench.KNOWN_STAGES), (
        f"KNOWN_STAGES drift: called-but-unknown {called - set(bench.KNOWN_STAGES)}, "
        f"known-but-never-called {set(bench.KNOWN_STAGES) - called}"
    )


def test_sig_preserves_small_rates():
    assert bench._sig(0.0021234) == 0.00212
    assert bench._sig(None) is None
    assert bench._sig(0) == 0
    assert bench._sig(123456.0) == 123456.0  # never truncates above the decimal


# ---- round-5 deadline-proofing: incremental snapshots + outer deadline ----


def _fresh_result():
    return {"metric": "m", "value": None, "unit": "u", "vs_baseline": None,
            "platform": None, "error": None, "extra": {}}


def test_emit_snapshot_stdout_and_side_file(capsys, _snapshot_tmp):
    """Every emission is a complete parseable JSON line on stdout AND an
    atomically-replaced side file; partial lines carry the marker, the
    final line does not (r04 printed once at the end and was killed
    first — nothing parseable survived)."""
    import json

    result = _fresh_result()
    result["value"] = 1.0
    bench._emit_snapshot(result)
    result["value"] = 2.0
    bench._emit_snapshot(result, final=True)

    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 2
    first, last = json.loads(lines[0]), json.loads(lines[-1])
    assert first["value"] == 1.0 and "partial" in first
    assert first["partial"]["at"]  # names where the run was
    assert last["value"] == 2.0 and "partial" not in last
    # side file holds the newest state, no tmp litter left behind
    on_disk = json.loads(_snapshot_tmp.read_text())
    assert on_disk["value"] == 2.0
    assert not list(_snapshot_tmp.parent.glob("*.tmp.*"))


def test_run_stage_emits_snapshot_after_success_and_failure(capsys):
    """A kill at ANY moment between stages leaves the newest accumulated
    state as the last parseable stdout line."""
    import json

    result = _fresh_result()

    def ok():
        result["value"] = 42.0
        return "ok"

    assert bench._run_stage(result, "s1", ok) == "ok"

    def bad():
        raise RuntimeError("boom")

    bench._run_stage(result, "s2", bad, retry_delay=0.0)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) >= 2
    last = json.loads(lines[-1])
    assert last["value"] == 42.0          # s1's number survived s2's failure
    assert "s2" in (last["error"] or "")  # s2's failure is in the snapshot


def test_effective_tpu_wait_capped_by_deadline(monkeypatch):
    """The lease wait may never eat the measuring window: with 1700 s of
    deadline and a 300 s headline reserve, a 1800 s BENCH_TPU_WAIT is
    capped to what actually fits (the r04 rc=124 failure: the wait spent
    1741 s of the driver's ~1800 s budget)."""
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    monkeypatch.setenv("BENCH_DEADLINE_S", "1700")
    monkeypatch.setenv("BENCH_RESERVE_S", "300")
    monkeypatch.setattr(bench, "_T0", 0.0)
    t = [100.0]  # 100 s already elapsed (imports, setup)
    monkeypatch.setattr(bench.time, "perf_counter", lambda: t[0])
    assert bench._effective_tpu_wait() == pytest.approx(1300.0)
    # deadline disabled -> raw BENCH_TPU_WAIT
    monkeypatch.setenv("BENCH_DEADLINE_S", "0")
    assert bench._effective_tpu_wait() == 1800.0
    # deadline nearly spent -> no negative budgets
    monkeypatch.setenv("BENCH_DEADLINE_S", "1700")
    t[0] = 1650.0
    assert bench._effective_tpu_wait() == 0.0


def test_lease_wait_respects_deadline(monkeypatch):
    """End-to-end through _devices_with_retry: with the deadline close,
    a wedged lease is surrendered early enough to leave the reserve."""
    monkeypatch.delenv("HANDYRL_PLATFORM", raising=False)
    monkeypatch.setenv("BENCH_TPU_WAIT", "1800")
    monkeypatch.setenv("BENCH_DEADLINE_S", "700")
    monkeypatch.setenv("BENCH_RESERVE_S", "300")
    monkeypatch.setattr(bench, "_T0", 0.0)
    t = [0.0]
    monkeypatch.setattr(bench.time, "perf_counter", lambda: t[0])
    monkeypatch.setattr(bench.time, "sleep", lambda s: t.__setitem__(0, t[0] + s))
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda timeout=120.0: probes.append(1) or ("hung", "hung >120s"),
    )
    devices, err = bench._devices_with_retry()
    assert err and "CPU fallback" in err
    # budget was 700-300=400 s -> at most ~3 re-probe sleeps of 150 s,
    # nowhere near the 1800 s raw wait
    assert t[0] <= 400.0


def test_run_stage_deadline_skip(monkeypatch, capsys):
    """Stages that would start with too little runway are skipped with an
    honest note (clean rc=0 finish beats a SIGKILL mid-stage)."""
    import json

    monkeypatch.setenv("BENCH_DEADLINE_S", "1000")
    monkeypatch.setattr(bench, "_T0", 0.0)
    monkeypatch.setattr(bench.time, "perf_counter", lambda: 970.0)
    result = _fresh_result()
    ran = []
    assert bench._run_stage(result, "late", lambda: ran.append(1)) is None
    assert ran == []
    assert result["extra"]["stages_deadline_skipped"] == ["late"]
    assert result["error"] is None
    last = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert last["extra"]["stages_deadline_skipped"] == ["late"]
