"""Low-precision fast path tests (marker: lowprec).

Two int8 rungs (handyrl_tpu/models/quantize.py, docs/performance.md
§Low-precision fast path), each pinned against its fp32 reference:

* weights — per-channel symmetric int8 quantization of the serving/
  fleet/league engine params: round-trip error bounds, per-channel scale
  correctness, int8 residency through ``build_inference_model`` and the
  ``ModelRouter`` publish path (with publish-time MEASURED calibration),
  and the RecompileSentinel pin that flipping ``serving.weight_dtype``
  compiles each warm bucket at most once;

* observations — the int8 obs/wire plane: exact round-trip for the
  0/1-occupancy planes, generator-attached per-episode quant spec, and
  the acceptance bar inherited from the device-stage suite: a window
  sampled/assembled on device from int8-staged episodes must equal,
  key by key, the fp32 ``make_batch`` reference for the SAME episode,
  window start, and target player — with zero added host syncs.

Win-rate parity is MEASURED, never assumed: the slow leg pits the int8
engine against the fp32 engine holding identical params through the
league's ``PayoffMatrix`` ledger (the full |dwp| <= 0.03 / >= 400 games
bar banks in the ``lowprec`` bench stage; the test leg plays fewer games
against a looser bound to keep CI honest without making it flaky).
"""

import random
import threading

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, build_inference_model, init_variables
from handyrl_tpu.models.quantize import (
    QuantizedInferenceModel,
    calibration_batches_from_store,
    calibration_report,
    dequantize_leaf,
    dequantize_obs_tree,
    dequantize_params,
    has_quantized_leaves,
    is_quantized_leaf,
    obs_quant_spec,
    obs_tree_is_int8,
    param_bytes,
    quantize_leaf,
    quantize_obs_tree,
    quantize_params,
)
from handyrl_tpu.parallel import TrainContext, make_mesh
from handyrl_tpu.runtime.batch import make_batch
from handyrl_tpu.runtime.device_replay import DeviceEpisodeStage
from handyrl_tpu.runtime.generation import Generator
from handyrl_tpu.runtime.replay import EpisodeStore, decompress_block
from handyrl_tpu.utils import tree_map
from handyrl_tpu.utils.sanitizers import HostSyncSanitizer, RecompileSentinel

pytestmark = pytest.mark.lowprec


def _targs(env="TicTacToe", **over):
    base = {"mesh": {"dp": 1}}
    base.update(over)
    cfg = normalize_args({"env_args": {"env": env}, "train_args": base})
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    return args


def _gen_episodes(env_name, n, targs, seed=0):
    random.seed(seed)
    env = make_env({"env": env_name})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=seed))
    gen = Generator(env, targs)
    models = {p: model for p in env.players()}
    gen_args = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    eps = []
    while len(eps) < n:
        ep = gen.generate(models, gen_args)
        if ep is not None:
            eps.append(ep)
    return env, module, eps


# ---------------------------------------------------------------------------
# weight quantization units
# ---------------------------------------------------------------------------


def test_quantize_leaf_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 32)).astype(np.float32) * 3.0
    node = quantize_leaf(w)
    assert is_quantized_leaf(node)
    assert node["int8_q"].dtype == np.int8
    assert node["int8_scale"].dtype == np.float32
    assert node["int8_scale"].shape == (32,)
    # symmetric codes: -128 unused
    assert node["int8_q"].min() >= -127
    # round-to-nearest: per-element error <= half a quantization step
    deq = dequantize_leaf(node)
    assert np.all(np.abs(deq - w) <= node["int8_scale"][None, :] / 2 + 1e-7)


def test_quantize_leaf_per_channel_scale_correctness():
    # hand-built per-OUT-channel absmax (flax puts out channels LAST)
    w = np.zeros((4, 3), np.float32)
    w[:, 0] = [0.5, -1.27, 0.1, 0.0]
    w[:, 1] = [2.0, 1.0, -2.54, 0.3]
    # column 2 all-zero: scale pins to 1.0 and codes to exact zeros
    node = quantize_leaf(w)
    np.testing.assert_allclose(
        node["int8_scale"], [1.27 / 127.0, 2.54 / 127.0, 1.0], rtol=1e-6
    )
    # the absmax element hits the full code range exactly
    assert node["int8_q"][1, 0] == -127
    assert node["int8_q"][2, 1] == -127
    assert np.all(node["int8_q"][:, 2] == 0)
    np.testing.assert_array_equal(dequantize_leaf(node)[:, 2], 0.0)

    # conv kernel layout (kh, kw, in, out): granule is still the last axis
    rng = np.random.default_rng(1)
    k = rng.standard_normal((3, 3, 4, 8)).astype(np.float32)
    knode = quantize_leaf(k)
    assert knode["int8_scale"].shape == (8,)
    np.testing.assert_allclose(
        knode["int8_scale"], np.abs(k).max(axis=(0, 1, 2)) / 127.0, rtol=1e-6
    )


def test_quantize_params_selective_and_idempotent():
    env = make_env({"env": "TicTacToe"})
    env.reset()
    module = env.net()
    params = init_variables(module, env, seed=3)["params"]

    q = quantize_params(params)
    assert has_quantized_leaves(q) and not has_quantized_leaves(params)

    n_kernels, n_small = [0], [0]

    def _walk(tree):
        if is_quantized_leaf(tree):
            n_kernels[0] += 1
            return
        if isinstance(tree, dict) or type(tree).__name__ == "FrozenDict":
            for v in tree.values():
                _walk(v)
            return
        # every unwrapped leaf is a small (< 2-d) fp32 tensor: biases and
        # norm params stay full precision by design
        assert np.asarray(tree).ndim < 2, np.asarray(tree).shape
        n_small[0] += 1

    _walk(q)
    assert n_kernels[0] > 0 and n_small[0] > 0

    # idempotent: re-quantizing a quantized tree is a no-op
    q2 = quantize_params(q)
    for a, b in zip(jax.tree.leaves(q), jax.tree.leaves(q2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the byte shrink is the point: conv/dense-dominated nets land ~4x
    assert param_bytes(params) / param_bytes(q) > 3.0

    # dequantize restores an all-fp32 wrapper-free tree
    deq = dequantize_params(q)
    assert not has_quantized_leaves(deq)
    assert jax.tree.structure(deq) == jax.tree.structure(dict(params))


# ---------------------------------------------------------------------------
# engine build + router residency
# ---------------------------------------------------------------------------


def _tictactoe():
    env = make_env({"env": "TicTacToe"})
    env.reset()
    module = env.net()
    return env, module, env.observation(0)


def test_engine_build_int8_residency_and_fidelity():
    env, module, obs = _tictactoe()
    params = init_variables(module, env, seed=5)["params"]

    engine = build_inference_model(module, params, "int8")
    assert isinstance(engine, QuantizedInferenceModel)
    assert has_quantized_leaves(engine.variables["params"])
    with pytest.raises(ValueError, match="weight_dtype"):
        build_inference_model(module, params, "int4")

    fp32 = build_inference_model(module, params, "float32")
    assert isinstance(fp32, InferenceModel)

    batch = tree_map(lambda x: np.repeat(np.asarray(x)[None], 8, axis=0), obs)
    out_q = engine.inference_batch(batch)
    out_f = fp32.inference_batch(batch)
    for key, vf in out_f.items():
        if key == "hidden" or vf is None:
            continue
        np.testing.assert_allclose(
            np.asarray(out_q[key]), np.asarray(vf), atol=0.05
        )

    # the honest calibration record measures the same deviation
    rep = calibration_report(module, params, [batch])
    assert rep["calib_batches"] == 1.0
    assert 0.0 <= rep["calib_mean_dev"] <= rep["calib_max_dev"] <= 0.05


def test_router_publish_builds_int8_engine_and_calibrates(tmp_path):
    from handyrl_tpu.serving import ModelRouter

    env, module, obs = _tictactoe()
    params = init_variables(module, env, seed=7)["params"]
    cfg = {
        "port": 0, "max_models": 3, "slo_ms": 2000.0, "shed_policy": "none",
        "max_batch": 8, "max_wait_ms": 1.0, "warm_buckets": [1, 4],
        "queue_bound": 64, "recv_timeout": 0.0, "watch_interval": 0.0,
        "stats_interval": 0.0,
        "weight_dtype": "int8", "calibration_batches": 2,
    }
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    batch = tree_map(lambda x: np.repeat(np.asarray(x)[None], 4, axis=0), obs)
    router.calibration_source = lambda: [batch, batch]
    try:
        router.publish(1, params)
        mid, engine = router.resolve(1)
        assert mid == 1
        # the resident engine holds int8 params, not a dequantized copy
        assert has_quantized_leaves(engine.model.variables["params"])
        # publish-time calibration MEASURED against the provided batches
        assert router.last_calibration is not None
        assert router.last_calibration["calib_batches"] == 2.0
        assert router.last_calibration["calib_max_dev"] <= 0.05
        # the serialization template stays fp32 (int8 wrappers don't
        # round-trip flax serialization; cold resolve re-quantizes)
        assert not has_quantized_leaves(router._params_template())
    finally:
        router.stop()


def test_weight_dtype_flip_compiles_each_bucket_at_most_once():
    """The serving plane's warm-bucket contract survives the dtype knob:
    after the fp32 engine warmed buckets [1, 4], flipping to int8 costs
    at most one compile per bucket, and a second pass over BOTH engines
    and BOTH buckets is compile-free."""
    env, module, obs = _tictactoe()
    params = init_variables(module, env, seed=9)["params"]
    fp32 = build_inference_model(module, params, "float32")
    q = build_inference_model(module, params, "int8")

    def _batch(b):
        return tree_map(lambda x: np.repeat(np.asarray(x)[None], b, axis=0), obs)

    for b in (1, 4):  # fp32 warms its buckets first
        jax.block_until_ready(fp32.inference_batch_async(_batch(b)))

    with RecompileSentinel() as flip:
        for b in (1, 4):
            jax.block_until_ready(q.inference_batch_async(_batch(b)))
    assert flip.count <= 2, flip.report()

    with RecompileSentinel() as warm:
        for b in (1, 4):
            jax.block_until_ready(fp32.inference_batch_async(_batch(b)))
            jax.block_until_ready(q.inference_batch_async(_batch(b)))
    warm.assert_no_recompiles("weight_dtype flip, warm buckets")


# ---------------------------------------------------------------------------
# observation int8 plane
# ---------------------------------------------------------------------------


def test_obs_roundtrip_exact_for_01_planes():
    env, _, obs = _tictactoe()
    spec = obs_quant_spec(env, obs=obs)
    assert all(s == 1.0 and z == 0.0 for s, z in spec)

    q = quantize_obs_tree(obs, spec)
    assert obs_tree_is_int8(q)
    deq = dequantize_obs_tree(tree_map(jax.numpy.asarray, q), spec)
    for a, b in zip(jax.tree.leaves(deq), jax.tree.leaves(obs)):
        assert np.asarray(a).dtype == np.float32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_obs_nonunit_spec_roundtrip_and_validation():
    x = {"p": np.linspace(-1.0, 1.0, 32, dtype=np.float32).reshape(4, 8)}
    spec = [(2.0 / 254.0, 0.0)]
    q = quantize_obs_tree(x, spec)
    deq = dequantize_obs_tree(tree_map(jax.numpy.asarray, q), spec)
    assert np.max(np.abs(np.asarray(deq["p"]) - x["p"])) <= spec[0][0] / 2 + 1e-7

    class _BadEnv:
        def obs_int8_spec(self):
            return [(0.0, 0.0)]

    with pytest.raises(ValueError, match="scale"):
        obs_quant_spec(_BadEnv())


def test_generator_attaches_int8_obs_and_spec():
    targs_f = _targs("TicTacToe", compress_steps=4, forward_steps=4)
    targs_q = dict(targs_f, obs_int8=True)
    _, _, eps_f = _gen_episodes("TicTacToe", 3, targs_f, seed=17)
    _, _, eps_q = _gen_episodes("TicTacToe", 3, targs_q, seed=17)

    for ef, eq in zip(eps_f, eps_q):
        assert eq.get("obs_scale") is not None and eq.get("obs_zero") is not None
        spec = list(zip(
            np.asarray(eq["obs_scale"], np.float32).tolist(),
            np.asarray(eq["obs_zero"], np.float32).tolist(),
        ))
        assert ef["steps"] == eq["steps"]  # same seed -> same trajectory
        for bf, bq in zip(ef["blocks"], eq["blocks"]):
            of = decompress_block(bf)["obs"]
            oq = decompress_block(bq)["obs"]
            assert obs_tree_is_int8(oq) and not obs_tree_is_int8(of)
            deq = dequantize_obs_tree(oq, spec)
            for a, b in zip(jax.tree.leaves(deq), jax.tree.leaves(of)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the host-side calibration feed dequantizes back to the fp32 planes
    store = EpisodeStore(10)
    store.extend(eps_q)
    batches = calibration_batches_from_store(store, 2)
    assert len(batches) == 2
    assert not any(obs_tree_is_int8(b) for b in batches)


def test_int8_obs_ring_parity_vs_make_batch(monkeypatch):
    """The device-stage acceptance bar on the int8 plane: windows sampled
    and assembled ON DEVICE from int8-staged episodes equal, key by key,
    the fp32 ``make_batch`` reference for the same (episode, train_start,
    target player) — the int8 obs planes dequantize EXACTLY (0/1
    occupancy, scale 1.0 / zp 0), so the comparison is equality, not
    allclose-with-slack.  The sampled window dispatch is also pinned
    host-sync-free and recompile-free."""
    targs = _targs("HungryGeese", batch_size=8, forward_steps=8,
                   turn_based_training=False, observation=False,
                   obs_int8=True)
    env, module, eps = _gen_episodes("HungryGeese", 24, targs, seed=23)
    assert all(ep.get("obs_scale") is not None for ep in eps)
    mesh = make_mesh({"dp": 1})
    stage = DeviceEpisodeStage(module, targs, mesh, n_lanes=4, slots=256,
                               chunk_steps=8, track_episodes=True)
    for ep in eps:
        stage.add_episode(ep)
    stage.flush()
    stage.drain()

    replay = stage.replay
    # int8 residency: the staged ring record slots hold int8 obs planes
    rec = replay.rings["rec"]
    obs_dtypes = {k: np.dtype(rec[k].dtype) for k in rec
                  if k.startswith("obs") and k[3:].isdigit()}
    assert obs_dtypes and all(dt == np.int8 for dt in obs_dtypes.values()), obs_dtypes

    S = stage.slots
    G = int(jax.device_get(replay.rings["g"]))
    n = 16

    # warm the sampler, then pin the hot window clean
    first = replay.sample(jax.random.PRNGKey(2), n)
    jax.block_until_ready(jax.tree.leaves(first)[0])
    with HostSyncSanitizer() as sync, RecompileSentinel() as sentinel:
        warm = replay.sample(jax.random.PRNGKey(4), n)
    sync.assert_clean("int8 ring sample window")
    sentinel.assert_no_recompiles("int8 ring sample window")
    jax.block_until_ready(jax.tree.leaves(warm)[0])

    batch, info = replay.sample(jax.random.PRNGKey(3), n, with_info=True)
    batch = tree_map(np.asarray, batch)
    fwd, cs = targs["forward_steps"], targs["compress_steps"]

    checked = 0
    for i in range(n):
        lane, slot, player = (
            int(info["lane"][i]), int(info["slot"][i]), int(info["player"][i])
        )
        gs0 = G - 1 - ((G - 1 - slot) % S)
        hits = [s for s in stage.spans[lane] if s[0] <= gs0 <= s[1]]
        assert hits, f"sampled slot maps to no staged episode (lane {lane})"
        g0, _, ep = hits[0]
        train_start = gs0 - g0
        start = max(0, train_start - targs["burn_in_steps"])
        end = min(train_start + fwd, ep["steps"])
        first_block = start // cs
        last_block = (end - 1) // cs + 1
        window = {
            "args": ep["args"],
            "outcome": np.asarray(
                [ep["outcome"][p] for p in ep["players"]], np.float32
            ),
            "players": ep["players"],
            "blocks": ep["blocks"][first_block:last_block],
            "base": first_block * cs,
            "start": start, "end": end,
            "train_start": train_start, "total": ep["steps"],
        }
        if player >= 0:
            monkeypatch.setattr(
                "handyrl_tpu.runtime.batch.random.randrange", lambda _n: player
            )
        host = make_batch([window], targs)
        spec = list(zip(
            np.asarray(ep["obs_scale"], np.float32).tolist(),
            np.asarray(ep["obs_zero"], np.float32).tolist(),
        ))
        for key in host:
            hval = host[key]
            if key == "observation":
                assert obs_tree_is_int8(hval)  # int8 end-to-end on the host path
                hval = dequantize_obs_tree(
                    tree_map(jax.numpy.asarray, hval), spec)
            for hleaf, dleaf in zip(
                jax.tree.leaves(hval), jax.tree.leaves(batch[key])
            ):
                np.testing.assert_array_equal(
                    np.asarray(dleaf)[i], np.asarray(hleaf)[0],
                    err_msg=f"window {i} key {key}",
                )
        checked += 1
    assert checked == n


def test_int8_obs_train_step_matches_fp32():
    """forward/backward parity through the real train step: the SAME
    seeded trajectories encoded fp32 vs int8 must produce bit-equal
    observations after in-graph dequantize, and the int8-fed train step
    must run to a finite loss."""
    over = dict(batch_size=4, forward_steps=4, compress_steps=4)
    targs_f = _targs("TicTacToe", **over)
    targs_q = dict(_targs("TicTacToe", obs_int8=True, **over))
    env, module, eps_f = _gen_episodes("TicTacToe", 6, targs_f, seed=31)
    _, _, eps_q = _gen_episodes("TicTacToe", 6, targs_q, seed=31)

    store_f, store_q = EpisodeStore(20), EpisodeStore(20)
    store_f.extend(eps_f)
    store_q.extend(eps_q)

    random.seed(7)
    wins_f = [store_f.sample_window(targs_f["forward_steps"],
                                    targs_f["burn_in_steps"],
                                    targs_f["compress_steps"])
              for _ in range(4)]
    random.seed(7)
    wins_q = [store_q.sample_window(targs_q["forward_steps"],
                                    targs_q["burn_in_steps"],
                                    targs_q["compress_steps"])
              for _ in range(4)]
    batch_f = make_batch(wins_f, targs_f)
    batch_q = make_batch(wins_q, targs_q)
    assert obs_tree_is_int8(batch_q["observation"])

    env.reset()
    targs_q["_obs_quant"] = obs_quant_spec(env, obs=env.observation(0))
    params = init_variables(module, env, seed=13)["params"]

    from handyrl_tpu.parallel.train_step import forward_prediction

    out_f = forward_prediction(
        module, params, tree_map(jax.numpy.asarray, batch_f), targs_f)
    out_q = forward_prediction(
        module, params, tree_map(jax.numpy.asarray, batch_q), targs_q)
    for key, vf in out_f.items():
        if vf is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(vf)),
            np.asarray(jax.device_get(out_q[key])), err_msg=key)

    ctx = TrainContext(module, targs_q, make_mesh({"dp": 1}))
    state = ctx.init_state(params)
    state, metrics = ctx.train_step(state, ctx.put_batch(batch_q), 1e-4)
    assert np.isfinite(float(jax.device_get(metrics["total"])))


# ---------------------------------------------------------------------------
# slow legs: measured win-rate parity + bf16 compute e2e
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_wp_parity_int8_vs_fp32_pit():
    """MEASURED parity: the int8 engine pits against the fp32 engine
    holding IDENTICAL params, seat-balanced through the PayoffMatrix
    ledger.  The test leg plays 64 games against a generous bound (the
    binomial noise floor at 64 games is ~0.13 at 2 sigma); the full
    >= 400-game |dwp| <= 0.03 bar banks in the lowprec bench stage."""
    from handyrl_tpu.agents import Agent
    from handyrl_tpu.league.matchmaker import PayoffMatrix
    from handyrl_tpu.runtime.evaluation import evaluate_mp

    env, module, _ = _tictactoe()
    params = init_variables(module, env, seed=21)["params"]
    a_q = Agent(build_inference_model(module, params, "int8"),
                temperature=1.0, seed=11)
    a_f = Agent(build_inference_model(module, params, "float32"),
                temperature=1.0, seed=12)
    results = evaluate_mp({"env": "TicTacToe"}, {0: a_q, 1: a_f},
                          64, num_workers=2)
    payoff = PayoffMatrix()
    for _pat, res in results.items():
        for outcome, count in res.items():
            payoff.record_score("int8", "fp32", float(outcome),
                                -float(outcome), n=count)
    wp = payoff.win_points("int8", "fp32")
    assert payoff.games("int8", "fp32") == 64
    assert abs(wp - 0.5) <= 0.2, (
        f"int8 vs fp32 wp {wp} over 64 games — far outside sampling noise; "
        "quantization is changing the policy"
    )


@pytest.mark.slow
def test_bf16_compute_e2e_trains_clean(tmp_path, monkeypatch):
    """compute_dtype: bfloat16 end to end: bf16 forward/backward over
    fp32 master params trains through the full Learner stack to a finite
    loss with ZERO divergence-sentinel skips — the knob changes compute
    width, not training health."""
    import json

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "compute_dtype": "bfloat16",
            "batch_size": 8,
            "forward_steps": 4,
            "compress_steps": 4,
            "minimum_episodes": 8,
            "update_episodes": 16,
            "maximum_episodes": 500,
            "epochs": 2,
            "eval_rate": 0.0,
            "mesh": {"dp": 1},
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    learner.run()

    records = [json.loads(l) for l in open("metrics.jsonl")]
    trained = [r for r in records if r.get("loss") is not None]
    assert trained, "no trained epoch recorded a loss"
    for r in trained:  # loss is the per-component dict: pin the total
        assert np.isfinite(float(r["loss"]["total"])), r["loss"]
    assert records[-1]["steps"] > 0
    assert sum(r.get("sentinel_skipped_steps", 0) for r in records) == 0
