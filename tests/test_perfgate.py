"""tools/perfgate.py: the perf-regression CI gate (ROADMAP item 6).

Pins the acceptance contract: the gate PASSES the banked captures (a
capture judged against itself is clean), FAILS a synthetically regressed
snapshot on a hard-class metric, treats absolute-throughput moves as
soft (BASELINE.md: r5 absolutes moved 0.6x on identical code — tunnel
RTT, not regressions), goes advisory across platforms, and carries the
graftlint-style content-addressed baseline for burn-down.
"""

import io
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.obs

from tools.perfgate import (
    classify,
    fingerprint,
    judge,
    load_snapshot,
    run,
)

REPO = Path(__file__).parent.parent
R05 = str(REPO / "BENCH_r05.json")


# -- sensitivity classes ------------------------------------------------------


@pytest.mark.parametrize("key,value,want_cls,want_dir", [
    # hard: ratio-of-internal-baseline — RTT/session variance divides out
    ("northstar2_per_chip_frac", 1.14, "hard", 1),
    ("northstar2_produce_consume_ratio", 0.015, "hard", 1),
    ("league_payoff_coverage", 1.0, "hard", 1),
    ("flash_attention_speedup", 1.54, "hard", 1),
    ("serving_swap_dropped", 0, "hard", -1),
    ("northstar2_rollout_time_frac", 0.91, "hard", -1),
    ("geese_input_wait_frac", 0.17, "hard", -1),
    # soft: absolute throughput/latency — BASELINE.md's 0.6x-on-identical-
    # code lesson
    ("tictactoe_updates_per_sec", 506.0, "soft", 1),
    ("serving_saturation_qps", 6400.0, "soft", 1),
    ("geese_mfu", 0.18, "soft", 1),
    ("serving_p99_ms", 7.1, "soft", -1),
    ("device_selfplay_vs_reference_gen", 6613.0, "soft", 1),
    # exact pins: categorical values must not move
    ("transformer_long_target_met", True, "exact", 0),
    ("northstar4_device_mode", "device", "exact", 0),
    ("transformer_long_T512_auto_attn", "flash", "exact", 0),
    # info: counts / run lengths / shapes — reported, never gated
    ("league_run_seconds", 8.9, "info", 1),
    ("transformer_net", "d1536 L8 H16", "info", 0),
    ("geese_flops_per_step", 9.4e10, "info", 1),
])
def test_classification_table(key, value, want_cls, want_dir):
    cls, direction = classify(key, value)
    assert (cls, direction) == (want_cls, want_dir), key


# -- judgment -----------------------------------------------------------------


def test_hard_regression_detected_soft_variance_tolerated():
    base = {
        "northstar2_per_chip_frac": 1.0,
        "tictactoe_updates_per_sec": 1000.0,
    }
    # the r5 story: absolutes at 0.6x (RTT), internal ratio intact -> OK
    ok = judge(base, {"northstar2_per_chip_frac": 0.98,
                      "tictactoe_updates_per_sec": 600.0}, 0.10, 0.50)
    assert all(v.status in ("ok",) for v in ok)
    # the internal ratio collapsing IS a code regression
    bad = judge(base, {"northstar2_per_chip_frac": 0.5,
                       "tictactoe_updates_per_sec": 1000.0}, 0.10, 0.50)
    hard = [v for v in bad if v.status == "regressed"]
    assert [v.key for v in hard] == ["northstar2_per_chip_frac"]
    assert hard[0].cls == "hard"
    # an absolute falling past soft tolerance is at least REPORTED
    soft = judge(base, {"northstar2_per_chip_frac": 1.0,
                        "tictactoe_updates_per_sec": 100.0}, 0.10, 0.50)
    assert [v.key for v in soft if v.status == "regressed"] == [
        "tictactoe_updates_per_sec"
    ]


def test_lower_is_better_and_zero_baselines():
    base = {"serving_p99_ms": 10.0, "serving_swap_dropped": 0,
            "geese_input_wait_frac": 0.05}
    vs = judge(base, {"serving_p99_ms": 9.0, "serving_swap_dropped": 3,
                      "geese_input_wait_frac": 0.30}, 0.10, 0.50)
    by = {v.key: v for v in vs}
    assert by["serving_p99_ms"].status == "ok"          # got faster
    assert by["serving_swap_dropped"].status == "regressed"  # was 0
    assert by["serving_swap_dropped"].cls == "hard"
    assert by["geese_input_wait_frac"].status == "regressed"  # 6x the wait


def test_exact_pins():
    base = {"transformer_long_target_met": True,
            "northstar4_device_mode": "device"}
    vs = judge(base, {"transformer_long_target_met": False,
                      "northstar4_device_mode": "shm"}, 0.10, 0.50)
    assert all(v.status == "regressed" and v.cls == "exact" for v in vs)
    # False -> True is progress, not a pin violation
    vs = judge({"x_target_met": False}, {"x_target_met": True}, 0.10, 0.50)
    assert vs[0].status == "ok"


def test_missing_keys_reported_not_failed():
    vs = judge({"a_per_chip_frac": 1.0}, {}, 0.10, 0.50)
    assert vs[0].status == "missing"


def test_missing_hard_metric_fails_enforcing_unless_allowed(tmp_path):
    """A stage that crashes or stops emitting numbers makes its banked
    hard metrics VANISH — the exact regression class the gate exists to
    catch, so enforcing mode fails on it; --allow-missing is the explicit
    escape for a deliberate BENCH_STAGES subset."""
    base = tmp_path / "base.json"
    base.write_text(json.dumps({
        "serving_saturation_qps": 6400.0,        # soft: may go missing
        "northstar2_per_chip_frac": 1.14,        # hard: must not vanish
    }))
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps({"serving_saturation_qps": 6000.0}))
    buf = io.StringIO()
    assert run(str(cur), str(base), out=buf) == 1
    assert "northstar2_per_chip_frac" in buf.getvalue()
    assert run(str(cur), str(base), allow_missing=True, out=io.StringIO()) == 0
    assert run(str(cur), str(base), advisory=True, out=io.StringIO()) == 0
    # a missing SOFT metric alone never fails
    cur2 = tmp_path / "cur2.json"
    cur2.write_text(json.dumps({"northstar2_per_chip_frac": 1.10}))
    assert run(str(cur2), str(base), out=io.StringIO()) == 0


# -- snapshot loading ---------------------------------------------------------


def test_loads_banked_capture_and_flat_snapshot(tmp_path):
    metrics, platform = load_snapshot(R05)
    assert platform == "tpu:TPU v5 lite x1"
    assert metrics["northstar2_per_chip_frac"] == 1.14
    assert metrics["flash_attention_speedup"] == 1.54  # nested dict flattened
    # the repo's own bench_snapshot.json (record form)
    metrics2, platform2 = load_snapshot(str(REPO / "bench_snapshot.json"))
    assert "league_autovec_per_chip_frac" in metrics2
    assert platform2 and platform2 != platform
    # flat dict (synthetic)
    p = tmp_path / "flat.json"
    p.write_text(json.dumps({"platform": "x", "k_frac": 1.0}))
    m3, p3 = load_snapshot(str(p))
    assert m3 == {"k_frac": 1.0} and p3 == "x"


# -- the gate end to end ------------------------------------------------------


def _regressed_r05(tmp_path) -> str:
    """BENCH_r05 with one hard-class metric synthetically collapsed."""
    metrics, platform = load_snapshot(R05)
    metrics["northstar2_per_chip_frac"] = metrics["northstar2_per_chip_frac"] * 0.4
    out = tmp_path / "regressed.json"
    out.write_text(json.dumps(dict(metrics, platform=platform)))
    return str(out)


def test_banked_capture_passes_against_itself():
    buf = io.StringIO()
    assert run(R05, R05, out=buf) == 0
    assert "PASS" in buf.getvalue()
    assert "REGRESSED" not in buf.getvalue()


def test_synthetic_hard_regression_fails_enforcing_passes_advisory(tmp_path):
    bad = _regressed_r05(tmp_path)
    buf = io.StringIO()
    assert run(bad, R05, out=buf) == 1
    text = buf.getvalue()
    assert "northstar2_per_chip_frac" in text and "FAIL" in text
    # advisory mode (the CI stance until BENCH_r06 is banked): reported,
    # never failed
    buf = io.StringIO()
    assert run(bad, R05, advisory=True, out=buf) == 0
    assert "northstar2_per_chip_frac" in buf.getvalue()


def test_platform_mismatch_forces_advisory():
    """A CPU smoke judged against the TPU capture must never fail CI —
    the numbers are not comparable, only reportable."""
    buf = io.StringIO()
    rc = run(str(REPO / "bench_snapshot.json"), R05, out=buf)
    assert rc == 0
    assert "ADVISORY" in buf.getvalue()


def test_baseline_burn_down_round_trip(tmp_path):
    bad = _regressed_r05(tmp_path)
    baseline = tmp_path / "PERFGATE_BASELINE.json"
    buf = io.StringIO()
    # bank the known regression...
    assert run(bad, R05, write_baseline_path=str(baseline), out=buf) == 1
    fps = json.loads(baseline.read_text())["findings"]["PERFGATE"]
    assert fps == [fingerprint("northstar2_per_chip_frac", "hard", 1)]
    # ...now it suppresses (burn-down list), and the gate passes
    buf = io.StringIO()
    assert run(bad, R05, baseline_path=str(baseline), out=buf) == 0
    assert "suppressed" in buf.getvalue()
    # a fixed regression turns the entry STALE so the baseline shrinks
    buf = io.StringIO()
    assert run(R05, R05, baseline_path=str(baseline), out=buf) == 0
    assert "stale baseline entry" in buf.getvalue()


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=str(REPO))
    ok = subprocess.run(
        [sys.executable, "-m", "tools.perfgate", R05, "--against", R05],
        capture_output=True, text=True, cwd=str(REPO), env=env,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "tools.perfgate", _regressed_r05(tmp_path),
         "--against", R05],
        capture_output=True, text=True, cwd=str(REPO), env=env,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    garbage = tmp_path / "garbage.json"
    garbage.write_text("[]")
    usage = subprocess.run(
        [sys.executable, "-m", "tools.perfgate", str(garbage), "--against", R05],
        capture_output=True, text=True, cwd=str(REPO), env=env,
    )
    assert usage.returncode == 2, usage.stdout + usage.stderr
