"""Distributed actor plane tests: codec, framing, TCP workers, battle mode.

These exercise the multi-node surface the reference validates only
implicitly (SURVEY.md §4: the delta-sync replica test is the reference's
sole multi-node surrogate): the pickle-free wire codec, framed RPC over
real sockets, a full --train-server/--worker run on localhost, and the
network battle mode.
"""

import socket
import sys
import threading

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime import codec
from handyrl_tpu.runtime.connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    connect_socket_connection,
    send_recv,
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def connect_retry(host: str, port: int) -> FramedConnection:
    return connect_socket_connection(host, port, retry_seconds=10.0)


# -- codec ------------------------------------------------------------------


def test_codec_roundtrip_scalars_and_containers():
    samples = [
        None,
        True,
        False,
        0,
        -(2**40),
        3.5,
        "hello ∑",
        b"\x00\xffbytes",
        [1, [2, "x"], None],
        (1, 2.5, "t"),
        {"a": 1, 0: "int-key", 1: {"nested": b"ok"}},
    ]
    for obj in samples:
        assert codec.loads(codec.dumps(obj)) == obj


def test_codec_roundtrip_numpy():
    arrays = [
        np.arange(12, dtype=np.int32).reshape(3, 4),
        np.random.randn(2, 3, 5).astype(np.float32),
        np.array(True),
        np.zeros((0, 7), np.float64),
    ]
    for arr in arrays:
        out = codec.loads(codec.dumps(arr))
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)
    # numpy scalars decay to python scalars
    assert codec.loads(codec.dumps(np.float32(2.5))) == 2.5
    assert codec.loads(codec.dumps(np.int64(7))) == 7


def test_codec_roundtrip_episode_like():
    episode = {
        "args": {"role": "g", "player": [0, 1], "model_id": {0: 3, 1: -1}},
        "steps": 9,
        "players": [0, 1],
        "outcome": {0: 1.0, 1: -1.0},
        "blocks": [b"compressed-block-1", b"compressed-block-2"],
    }
    assert codec.loads(codec.dumps(episode)) == episode


def _codec_corpus():
    return [
        None, True, False, 0, -(2**40), 2**62, 3.5, float("inf"), "hello ∑",
        b"\x00\xffbytes", bytearray(b"ba"), memoryview(b"mv"),
        [1, [2, "x"], None], (1, 2.5, "t"),
        {"a": 1, 0: "int-key", 1: {"nested": b"ok"}},
        np.arange(12, dtype=np.int32).reshape(3, 4),
        np.random.RandomState(3).randn(2, 3, 5).astype(np.float32),
        np.array(True), np.zeros((0, 7), np.float64),
        np.float32(2.5), np.int64(7), np.bool_(True),
        {"blocks": [b"z" * 300] * 4, "outcome": {0: 1.0, 1: -1.0}},
    ]


def test_codec_accel_loads_on_linux():
    """The C accelerator must actually build here — a silent fallback to
    pure Python on a platform with a compiler would hide a regression."""
    if sys.platform != "linux":
        pytest.skip("accelerator is best-effort off Linux")
    if codec._accel_disabled():
        pytest.skip("HANDYRL_NO_CODEC_ACCEL disables the accelerator")
    assert codec._accel is not None


def test_codec_impls_byte_identical_and_interop():
    """The C accelerator and the pure-Python codec must produce the SAME
    bytes (the format has one spec) and decode each other's output."""
    if codec._accel is None:
        pytest.skip("accelerator unavailable")
    for obj in _codec_corpus():
        b_py = codec.py_dumps(obj)
        b_c = codec._accel.dumps(obj)
        assert b_py == b_c, f"byte mismatch for {obj!r}"
        for decoded in (codec.py_loads(b_c), codec._accel.loads(b_py)):
            if isinstance(obj, np.ndarray):
                assert decoded.dtype == obj.dtype and decoded.shape == obj.shape
                np.testing.assert_array_equal(decoded, obj)
            elif isinstance(obj, (bytearray, memoryview)):
                assert decoded == bytes(obj)
            elif isinstance(obj, (np.bool_, np.integer, np.floating)):
                assert decoded == obj.item()
            else:
                assert decoded == obj


def test_codec_accel_malformed_frames():
    """Every strict prefix of a valid frame, and hostile headers, must
    surface as CodecError from BOTH implementations — connection receive
    loops drop the peer on CodecError; anything else would kill them."""
    impls = [codec.py_loads] + ([codec._accel.loads] if codec._accel else [])
    frame = codec.py_dumps(
        {"a": [1, 2.5, "s"], "arr": np.arange(6, dtype=np.float32).reshape(2, 3)}
    )
    for loads in impls:
        for i in range(len(frame)):
            with pytest.raises(codec.CodecError):
                loads(frame[:i])
        with pytest.raises(codec.CodecError):
            loads(frame + b"x")
        # hostile array header: junk dtype
        with pytest.raises(codec.CodecError):
            loads(b"a\x00\x00\x00\x02zz\x00\x00\x00\x01\x00\x00\x00\x05"
                  b"\x00\x00\x00\x04abcd")
        # raw-size / shape mismatch -> reshape error -> CodecError
        with pytest.raises(codec.CodecError):
            loads(b"a\x00\x00\x00\x03<f4\x00\x00\x00\x01\x00\x00\x00\x05"
                  b"\x00\x00\x00\x04abcd")
        # unknown tag
        with pytest.raises(codec.CodecError):
            loads(b"Z")


def test_codec_accel_depth_guard():
    """A deeply nested frame must fail bounded (CodecError), not smash the
    C stack: 'l' with count 1, nested a few thousand deep."""
    deep = b"l\x00\x00\x00\x01" * 4000 + b"N"
    impls = [codec.py_loads] + ([codec._accel.loads] if codec._accel else [])
    for loads in impls:
        with pytest.raises(codec.CodecError):
            loads(deep)
    if codec._accel is not None:
        lst = None
        for _ in range(4000):
            lst = [lst]
        with pytest.raises(codec.CodecError):
            codec._accel.dumps(lst)


def test_codec_oversized_length_is_codec_error():
    """Exception-type parity on >= 2**32 lengths: the C accelerator raises
    CodecError via enc_len_u32; the pure-Python fallback must match — an
    accelerated host and a fallback host have to fail the same way on the
    same oversized frame.  (Allocating a real 4 GiB payload is off the
    table on the 1-core host, so the length pack is exercised directly.)"""
    with pytest.raises(codec.CodecError):
        codec._pack_u32(2**32)
    with pytest.raises(codec.CodecError):
        codec._pack_u32(-1)
    assert codec._pack_u32(2**32 - 1) == b"\xff\xff\xff\xff"


def test_codec_impls_agree_on_random_structures():
    """Seeded structural fuzz: both implementations must byte-agree and
    round-trip on arbitrary nested payloads, not just the fixed corpus."""
    if codec._accel is None:
        pytest.skip("accelerator unavailable")
    rng = np.random.RandomState(1234)

    def gen(depth):
        kinds = ["int", "float", "str", "bytes", "none", "bool", "arr"]
        if depth < 3:
            kinds += ["list", "tuple", "dict"] * 2
        k = kinds[rng.randint(len(kinds))]
        if k == "int":
            return int(rng.randint(-(2**62), 2**62))
        if k == "float":
            return float(rng.randn() * 10 ** rng.randint(-8, 8))
        if k == "str":
            return "".join(chr(rng.randint(32, 0x2FF)) for _ in range(rng.randint(0, 12)))
        if k == "bytes":
            return bytes(rng.bytes(rng.randint(0, 32)))
        if k == "none":
            return None
        if k == "bool":
            return bool(rng.randint(2))
        if k == "arr":
            dt = [np.float32, np.float64, np.int32, np.int8, np.bool_][rng.randint(5)]
            shape = tuple(rng.randint(0, 4) for _ in range(rng.randint(0, 3)))
            # outer asarray AFTER the arithmetic: numpy returns a SCALAR
            # from 0-d math, and np scalars decay to python scalars on the
            # wire by design — this branch must produce a true ndarray
            # (including the 0-d case, the historical codec edge)
            return np.asarray(rng.randn(*shape) * 100).astype(dt)
        n = rng.randint(0, 5)
        if k == "list":
            return [gen(depth + 1) for _ in range(n)]
        if k == "tuple":
            return tuple(gen(depth + 1) for _ in range(n))
        return {f"k{i}": gen(depth + 1) for i in range(n)}

    def eq(a, b):
        if isinstance(a, np.ndarray):
            return (isinstance(b, np.ndarray) and a.dtype == b.dtype
                    and a.shape == b.shape and np.array_equal(a, b))
        if isinstance(a, (list, tuple)):
            return (type(a) is type(b) and len(a) == len(b)
                    and all(eq(x, y) for x, y in zip(a, b)))
        if isinstance(a, dict):
            return (isinstance(b, dict) and a.keys() == b.keys()
                    and all(eq(a[k], b[k]) for k in a))
        return a == b and type(a) is type(b)

    for _ in range(200):
        obj = gen(0)
        b_py = codec.py_dumps(obj)
        assert b_py == codec._accel.dumps(obj), repr(obj)
        assert eq(codec._accel.loads(b_py), codec.py_loads(b_py)), repr(obj)
        assert eq(codec.py_loads(b_py), obj), repr(obj)


def test_codec_fallback_forced(tmp_path):
    """HANDYRL_NO_CODEC_ACCEL=1 must leave the pure-Python codec fully
    functional (the accelerator is strictly optional) — checked in a
    subprocess because the dispatch is bound at import time."""
    import os
    import subprocess
    import sys as _sys

    script = (
        "from handyrl_tpu.runtime import codec\n"
        "assert codec._accel is None, 'accelerator loaded despite disable'\n"
        "assert codec.dumps is codec.py_dumps\n"
        "b = codec.dumps({'x': [1, 2.5, 'y']})\n"
        "assert codec.loads(b) == {'x': [1, 2.5, 'y']}\n"
        "print('fallback-ok')\n"
    )
    out = subprocess.run(
        [_sys.executable, "-c", script],
        env={**os.environ, "HANDYRL_NO_CODEC_ACCEL": "1",
             "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr.decode(errors="replace")
    assert b"fallback-ok" in out.stdout


def test_codec_rejects_unencodable():
    with pytest.raises(codec.CodecError):
        codec.dumps(object())
    with pytest.raises(codec.CodecError):
        codec.dumps(np.array([object()]))
    with pytest.raises(codec.CodecError):
        codec.loads(codec.dumps([1, 2]) + b"junk")


# -- framing + RPC over real sockets ---------------------------------------


def test_framed_send_recv_over_socket():
    port = free_port()
    server_obj = {"reply": np.ones((4, 4), np.float32), "n": 1}
    got = {}

    def server():
        for conn in accept_socket_connections(port=port, maxsize=1):
            got["req"] = conn.recv()
            conn.send(server_obj)
            conn.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    conn = connect_retry("localhost", port)
    reply = send_recv(conn, ("args", None))
    conn.close()
    t.join(timeout=5)

    assert got["req"] == ("args", None)
    assert reply["n"] == 1
    np.testing.assert_array_equal(reply["reply"], np.ones((4, 4), np.float32))


def test_queue_communicator_echo():
    port = free_port()
    hub_box = {}

    def server():
        hub = QueueCommunicator()
        hub_box["hub"] = hub
        for conn in accept_socket_connections(port=port, maxsize=2):
            hub.add_connection(conn)
            break
        for _ in range(3):
            conn, data = hub.recv(timeout=5)
            hub.send(conn, ("echo", data))

    t = threading.Thread(target=server, daemon=True)
    t.start()
    conn = connect_retry("localhost", port)
    for i in range(3):
        assert send_recv(conn, i) == ("echo", i)
    conn.close()
    t.join(timeout=5)
    assert hub_box["hub"].connection_count() >= 0


# -- full remote training over localhost TCP --------------------------------


@pytest.mark.slow
def test_train_server_with_remote_worker(tmp_path, monkeypatch):
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner
    from handyrl_tpu.runtime.server import worker_main

    monkeypatch.chdir(tmp_path)
    entry_port, data_port = free_port(), free_port()
    args = normalize_args(
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "minimum_episodes": 10,
                "update_episodes": 12,
                "maximum_episodes": 100,
                "epochs": 2,
                "num_batchers": 1,
                "eval_rate": 0.2,
                # 1-device mesh: this test exercises the TCP transport, not
                # sharding (test_end_to_end_training covers the 8-dev mesh).
                # On virtual CPU devices an 8-way all-reduce rendezvous can
                # starve when the two inference engines (learner + remote
                # machine, same process here) occupy the XLA CPU thread pool.
                "mesh": {"dp": 1},
                "worker": {"num_parallel": 2, "entry_port": entry_port, "data_port": data_port},
            },
            "worker_args": {
                "server_address": "localhost",
                "num_parallel": 2,
                "entry_port": entry_port,
            },
        }
    )

    learner = Learner(args, remote=True)
    learner_thread = threading.Thread(target=learner.run, daemon=True)
    learner_thread.start()

    worker_thread = threading.Thread(target=worker_main, args=(args,), daemon=True)
    worker_thread.start()

    learner_thread.join(timeout=300)
    assert not learner_thread.is_alive(), "remote training did not finish"
    worker_thread.join(timeout=30)

    assert os.path.exists("models/latest.ckpt")
    assert os.path.exists("models/2.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= 2
    assert learner.num_returned_episodes >= 22


@pytest.mark.slow
def test_worker_chaos_kill_and_rejoin(tmp_path, monkeypatch):
    """Actor-plane elasticity under real failure: a remote worker process
    is SIGKILLed mid-epoch and a fresh one joins — training keeps
    consuming episodes, finishes every epoch, and shutdown still drains
    (reference claim: workers join/leave freely, worker.py:199-213; drop
    handling connection.py:198-224)."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time

    import yaml

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    entry_port, data_port = free_port(), free_port()
    cfg = {
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 8,
            "forward_steps": 4,
            "minimum_episodes": 10,
            "update_episodes": 12,
            "maximum_episodes": 200,
            "epochs": 3,
            "num_batchers": 1,
            "eval_rate": 0.2,
            "mesh": {"dp": 1},  # TCP-transport test, not a sharding test
            "worker": {
                "num_parallel": 2,
                "entry_port": entry_port,
                "data_port": data_port,
            },
        },
        "worker_args": {
            "server_address": "localhost",
            "num_parallel": 2,
            "entry_port": entry_port,
        },
    }
    args = normalize_args(cfg)
    with open("config.yaml", "w") as f:
        yaml.safe_dump(cfg, f)

    learner = Learner(args, remote=True)
    learner_thread = threading.Thread(target=learner.run, daemon=True)
    learner_thread.start()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "PYTHONPATH": repo,
        "HANDYRL_PLATFORM": "cpu",  # a killed process must never hold a chip lease
    }

    def spawn_worker():
        return subprocess.Popen(
            [sys.executable, os.path.join(repo, "main.py"), "--worker"],
            cwd=tmp_path,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    victim = spawn_worker()
    try:
        # let it join and deliver a few episodes, then kill it without warning
        deadline = time.time() + 120
        while learner.num_returned_episodes < 4 and time.time() < deadline:
            time.sleep(0.5)
        assert learner.num_returned_episodes >= 4, "first worker never delivered"
        episodes_before_kill = learner.num_returned_episodes
    finally:
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)

    time.sleep(1.0)  # give the hub a beat to notice the dropped connections
    replacement = spawn_worker()
    try:
        learner_thread.join(timeout=420)
        assert not learner_thread.is_alive(), "training did not survive the worker kill"
        # the replacement actually contributed: episode flow resumed past
        # whatever the victim had delivered before dying
        assert learner.num_returned_episodes > episodes_before_kill
        assert os.path.exists("models/latest.ckpt")
        assert os.path.exists("models/3.ckpt")
        records = [json.loads(l) for l in open("metrics.jsonl")]
        assert len(records) >= 3
    finally:
        replacement.terminate()
        try:
            replacement.wait(timeout=30)
        except subprocess.TimeoutExpired:
            replacement.kill()


# -- network battle mode ----------------------------------------------------


@pytest.mark.slow
def test_network_battle_mode(capsys):
    from handyrl_tpu.runtime.battle import eval_client_main, eval_server_main

    port = free_port()
    args = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": {}})

    server = threading.Thread(
        target=eval_server_main, args=(args, ["2"]), kwargs={"port": port}, daemon=True
    )
    server.start()

    clients = [
        threading.Thread(
            target=eval_client_main,
            args=(args, [spec, "localhost"]),
            kwargs={"port": port},
            daemon=True,
        )
        for spec in ("random", "random")
    ]
    for c in clients:
        c.start()

    server.join(timeout=120)
    assert not server.is_alive(), "battle server did not finish"
    for c in clients:
        c.join(timeout=30)

    out = capsys.readouterr().out
    assert "total =" in out
    assert "game 0" in out and "game 1" in out
