"""Network-match bookkeeping pins (runtime/battle.py).

The payoff matrix consumes ``exec_network_match`` results, so its
outcome accounting — draws, multi-player placements, severed-peer
forfeits — is pinned here against the REAL match executor and env rules,
socket-free: scripted agents speak the NetworkAgentClient protocol
(update/action/observe/outcome over a replica env) and sever on cue.
"""

import numpy as np
import pytest

from handyrl_tpu.envs import make_env
from handyrl_tpu.league.matchmaker import PayoffMatrix
from handyrl_tpu.runtime.battle import (
    PeerSevered,
    exec_recorded_match,
    forfeit_outcome,
)

pytestmark = pytest.mark.league


class ScriptedPeer:
    """A NetworkAgent-shaped peer: replica env synced by deltas, moves
    from a script, optionally severing (connection death) at move k or
    during the final outcome-notification round."""

    def __init__(self, env_name, player, moves, sever_at=None,
                 sever_on_outcome=False):
        self.env = make_env({"env": env_name})
        self.player = player
        self.moves = list(moves)
        self.sever_at = sever_at
        self.sever_on_outcome = sever_on_outcome
        self.final_outcome = None
        self._move_i = 0

    def update(self, info, reset):
        self._maybe_sever()
        self.env.update(info, reset)

    def action(self, player):
        self._maybe_sever()
        a = self.moves[self._move_i]
        self._move_i += 1
        return self.env.action2str(a, player)

    def observe(self, player):
        return None

    def outcome(self, outcome):
        if self.sever_on_outcome:
            raise PeerSevered(self.player)
        self.final_outcome = outcome

    def _maybe_sever(self):
        if self.sever_at is not None and self._move_i >= self.sever_at:
            raise PeerSevered(self.player)


# X at 0,1,5,6,8 / O at 2,3,4,7 — no line of three: a drawn game
DRAW_X = [0, 1, 5, 6, 8]
DRAW_O = [2, 3, 4, 7]
# X takes the top row before O finishes anything
WIN_X = [0, 1, 2]
WIN_O = [3, 4]


def _play(moves_x, moves_o, payoff=None, names=None, sever_x_at=None):
    env = make_env({"env": "TicTacToe"})
    agents = {
        0: ScriptedPeer("TicTacToe", 0, moves_x, sever_at=sever_x_at),
        1: ScriptedPeer("TicTacToe", 1, moves_o),
    }
    outcome, severed = exec_recorded_match(env, agents, names, payoff)
    return env, agents, outcome, severed


def test_decisive_game_records_pairwise():
    p = PayoffMatrix()
    _, agents, outcome, severed = _play(
        WIN_X, WIN_O, p, names={0: "alice", 1: "bob"}
    )
    assert severed is None
    assert outcome == {0: 1, 1: -1}
    assert p.win_points("alice", "bob") == 1.0
    assert p.win_points("bob", "alice") == 0.0
    assert p.matches == 1 and p.forfeits == 0
    # both replica envs saw the delta-synced game and the final outcome
    assert agents[0].final_outcome == 1
    assert agents[1].final_outcome == -1
    assert agents[0].env.terminal() and agents[1].env.terminal()


def test_draw_records_half_win_each_way():
    p = PayoffMatrix()
    _, _, outcome, severed = _play(DRAW_X, DRAW_O, p, {0: "alice", 1: "bob"})
    assert severed is None
    assert outcome == {0: 0, 1: 0}
    assert p.win_points("alice", "bob") == pytest.approx(0.5)
    assert p.win_points("bob", "alice") == pytest.approx(0.5)


def test_severed_peer_forfeits_with_books():
    """A peer dying mid-game must neither kill the match thread nor
    vanish from the books: the severed seat takes the loss, the match
    counts, and the returned outcome says who forfeited."""
    p = PayoffMatrix()
    _, _, outcome, severed = _play(
        WIN_X, WIN_O, p, {0: "alice", 1: "bob"}, sever_x_at=2
    )
    assert severed == 0
    assert outcome == {0: -1.0, 1: 1.0}
    assert p.win_points("bob", "alice") == 1.0
    assert p.win_points("alice", "bob") == 0.0
    assert p.matches == 1 and p.forfeits == 1


def test_sever_during_outcome_delivery_keeps_real_result():
    """A client that wins and then drops its connection before the
    server's outcome round played a FINISHED game: the master env holds
    the real result, and booking a forfeit would record a loss for an
    actual winner — the true outcome must land in the books."""
    p = PayoffMatrix()
    env = make_env({"env": "TicTacToe"})
    agents = {
        0: ScriptedPeer("TicTacToe", 0, WIN_X, sever_on_outcome=True),
        1: ScriptedPeer("TicTacToe", 1, WIN_O),
    }
    outcome, severed = exec_recorded_match(
        env, agents, {0: "alice", 1: "bob"}, p
    )
    assert severed is None
    assert outcome == {0: 1, 1: -1}
    assert p.win_points("alice", "bob") == 1.0
    assert p.forfeits == 0 and p.matches == 1


def test_default_names_are_seats():
    p = PayoffMatrix()
    _play(WIN_X, WIN_O, p)   # no names: seat{p} convention
    assert p.win_points("seat0", "seat1") == 1.0


def test_no_ledger_still_plays():
    _, _, outcome, severed = _play(WIN_X, WIN_O, payoff=None)
    assert outcome == {0: 1, 1: -1} and severed is None


def test_forfeit_outcome_multiplayer_shape():
    out = forfeit_outcome([0, 1, 2, 3], 2)
    assert out == {0: 1.0, 1: 1.0, 2: -1.0, 3: 1.0}


def test_multiplayer_match_placements_via_ledger():
    """A 4-player HungryGeese-style placement outcome decomposes into
    pairwise entries when recorded by the same ledger battle matches use
    (no extra convention between battle and league accounting)."""
    p = PayoffMatrix()
    names = {0: "a", 1: "b", 2: "c", 3: "d"}
    p.record_outcome(names, {0: 1.0, 1: 1 / 3, 2: -1 / 3, 3: -1.0})
    got = np.array([
        [np.nan if a == b else p.win_points(a, b) for b in "abcd"]
        for a in "abcd"
    ])
    want = np.array([
        [np.nan, 1.0, 1.0, 1.0],
        [0.0, np.nan, 1.0, 1.0],
        [0.0, 0.0, np.nan, 1.0],
        [0.0, 0.0, 0.0, np.nan],
    ])
    np.testing.assert_array_equal(got, want)
