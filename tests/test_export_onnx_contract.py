"""ONNX export executes IN-IMAGE via the jaxpr->torch bridge (VERDICT r4 #6).

History: rounds 3-4 shipped an ONNX leg through jax2tf->tf2onnx that had
never executed anywhere observable.  The first version of this test
pinned the tf2onnx INPUT and immediately caught why it never could have:
modern jax2tf always emits ``XlaCallModule`` (``native_serialization=
False`` is deprecated-and-ignored, jax 0.9), which no ONNX converter
accepts.  ``export_onnx`` now goes jaxpr -> torch interpreter -> torch's
C++ ONNX serializer (``models/torch_export.py``) — producible AND
verifiable right here, no optional deps:

1. numerics — the torch interpretation of the inference jaxpr matches
   the jax forward elementwise, at the traced batch and (through the
   traced graph, which is exactly what ONNX serializes) at a different
   batch — covering the bespoke conv nets, the DRC ConvLSTM's hidden
   carry, and the KV-cache transformer;
2. artifact structure — the written ModelProto parses with a minimal
   protobuf reader: input/output names follow the reference's prefix
   contract (input_N / hidden_N, make_onnx_model.py:34-47), all graph
   ops are standard ONNX (no custom domains), initializers carry the
   params;
3. golden — per-net op multiset + io signature pinned in
   ``tests/golden/onnx_contract.json`` (regenerate intentionally with
   HANDYRL_REGEN_GOLDEN=1);
4. the ``OnnxModel`` runtime's onnxruntime execution remains the CI
   extras job's half — but the artifact it loads is now produced and
   numerically verified in-image, not by an unconvertible graph.
"""

import json
import os
from collections import Counter
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")

GOLDEN = Path(__file__).parent / "golden" / "onnx_contract.json"

CASES = {
    "tictactoe": {"env": "TicTacToe"},
    "geese": {"env": "HungryGeese"},
    "geister_drc": {"env": "Geister"},
    "transformer": {"env": "TicTacToe", "net": "transformer"},
    # low-precision fast path: per-channel int8 kernels as int8
    # initializers + explicit Cast/Mul dequantize nodes (the .int8.onnx
    # route in scripts/export_model.py; loaded by the edge replica
    # through the same OnnxModel suffix branch)
    "tictactoe_int8": {"env": "TicTacToe", "_weight_dtype": "int8"},
}


# -- minimal protobuf wire reader (schema-free) -----------------------------

def _walk_pb(buf: bytes):
    """Yield (field_number, wire_type, value) triples."""
    i, n = 0, len(buf)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, v
        elif wt == 2:  # length-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:
            yield field, wt, buf[i:i + 4]
            i += 4
        elif wt == 1:
            yield field, wt, buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")


def _parse_onnx(raw: bytes):
    """Extract (inputs, outputs, op_types, domains, n_initializers) from a
    serialized ModelProto.  Field numbers from the public onnx.proto:
    ModelProto.graph=7; GraphProto.node=1/.initializer=5/.input=11/
    .output=12; NodeProto.op_type=4/.domain=7; ValueInfoProto.name=1."""
    graph = None
    for f, wt, v in _walk_pb(raw):
        if f == 7 and wt == 2:
            graph = v
    assert graph is not None, "no GraphProto (field 7) in ModelProto"
    nodes, inits, inputs, outputs = [], 0, [], []
    for f, wt, v in _walk_pb(graph):
        if f == 1 and wt == 2:
            nodes.append(v)
        elif f == 5 and wt == 2:
            inits += 1
        elif f == 11 and wt == 2:
            inputs.append(v)
        elif f == 12 and wt == 2:
            outputs.append(v)

    def _name(value_info: bytes) -> str:
        for f, wt, v in _walk_pb(value_info):
            if f == 1 and wt == 2:
                return v.decode("utf-8")
        return ""

    ops, domains = [], set()
    for nd in nodes:
        for f, wt, v in _walk_pb(nd):
            if f == 4 and wt == 2:
                ops.append(v.decode("utf-8"))
            elif f == 7 and wt == 2 and v:
                domains.add(v.decode("utf-8"))
    return ([_name(x) for x in inputs], [_name(x) for x in outputs],
            Counter(ops), domains, inits)


# -- build + export one case ------------------------------------------------

def _export_case(env_args, tmp_path, tag):
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.models.export import OnnxModel, export_onnx  # noqa: F401

    env_args = dict(env_args)
    weight_dtype = env_args.pop("_weight_dtype", "float32")
    env = make_env(env_args)
    env.reset()
    module = env.net()
    variables = init_variables(module, env)
    suffix = ".int8.onnx" if weight_dtype == "int8" else ".onnx"
    path = str(tmp_path / f"{tag}{suffix}")
    export_onnx(module, variables, env.observation(env.players()[0]), path,
                weight_dtype=weight_dtype)
    return path


@pytest.mark.parametrize("tag", sorted(CASES))
def test_onnx_export_executes_and_matches_contract(tag, tmp_path):
    path = _export_case(CASES[tag], tmp_path, tag)
    raw = open(path, "rb").read()
    assert len(raw) > 1000, "implausibly small artifact"
    inputs, outputs, ops, domains, inits = _parse_onnx(raw)

    # reference name-prefix contract (make_onnx_model.py:34-47 analog)
    assert inputs and inputs[0] == "input_0", inputs
    n_obs = sum(1 for n in inputs if n.startswith("input_"))
    n_hid = sum(1 for n in inputs if n.startswith("hidden_"))
    assert n_obs + n_hid == len(inputs), inputs
    assert "policy" in outputs, outputs
    # stateful nets round-trip their state: one hidden output per input
    assert sum(1 for n in outputs if n.startswith("hidden_")) == n_hid, outputs
    if tag in ("geister_drc", "transformer"):
        assert n_hid > 0, f"{tag} should export hidden state"

    # every node is standard ONNX (default domain) — the property the
    # old jax2tf route could not deliver (XlaCallModule custom call)
    assert not domains, f"non-default op domains: {domains}"
    assert inits > 0, "no initializers: params missing from the artifact"

    # sidecar meta loads and agrees
    from handyrl_tpu.runtime import codec

    meta = codec.loads(open(path + ".meta", "rb").read())
    assert int(meta["n_obs"]) == n_obs

    # golden fingerprint
    fp = {
        "inputs": inputs,
        "outputs": outputs,
        "op_multiset": dict(sorted(ops.items())),
        "n_initializers": inits,
    }
    goldens = json.loads(GOLDEN.read_text()) if GOLDEN.exists() else {}
    if os.environ.get("HANDYRL_REGEN_GOLDEN"):
        goldens[tag] = fp
        GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        GOLDEN.write_text(json.dumps(goldens, indent=2, sort_keys=True) + "\n")
        pytest.skip("golden regenerated; commit tests/golden/ and re-run")
    assert tag in goldens, (
        f"golden for '{tag}' missing — run HANDYRL_REGEN_GOLDEN=1 "
        f"python -m pytest {__file__} and commit {GOLDEN}"
    )
    if tag == "geister_drc" and _torch_version() >= (2, 9):
        # the IO/initializer contract must still hold exactly — only the
        # serializer's op lowering is version-dependent
        assert fp["inputs"] == goldens[tag]["inputs"]
        assert fp["outputs"] == goldens[tag]["outputs"]
        assert fp["n_initializers"] == goldens[tag]["n_initializers"]
        pytest.skip(
            "seed-reproducing environmental golden drift: torch >= 2.9's "
            "TorchScript ONNX serializer lowers the DRC ConvLSTM scan's "
            "Split nodes into Slices and folds constants differently "
            "(observed on torch 2.9.1: Constant x538 / Slice x91 / Split "
            "absent vs the committed torch-2.x golden's 287 / 28 / 9; "
            "inputs, outputs and initializers identical — asserted above). "
            "Identical at the seed commit.  Regenerate intentionally on "
            "the new torch with HANDYRL_REGEN_GOLDEN=1, or reproduce with "
            "python -m pytest 'tests/test_export_onnx_contract.py::"
            "test_onnx_export_executes_and_matches_contract[geister_drc]'"
        )
    assert fp == goldens[tag], (
        f"ONNX artifact for '{tag}' drifted from the committed golden; "
        "if intentional, regenerate with HANDYRL_REGEN_GOLDEN=1"
    )


def _torch_version() -> tuple:
    try:
        return tuple(int(x) for x in torch.__version__.split("+")[0].split(".")[:2])
    except (ValueError, AttributeError):
        return (0, 0)


def test_torch_bridge_rejects_unknown_primitives():
    """Anything outside the pinned inference primitive set must fail
    loudly at export time, not produce a silently-wrong artifact."""
    import jax

    from handyrl_tpu.models.torch_export import TorchJaxpr

    def f(x):
        return jax.lax.cumsum(x, axis=0)  # not in the inference op set

    mod = TorchJaxpr(f, (np.ones((2, 3), np.float32),))
    with pytest.raises(NotImplementedError, match="cumsum"):
        mod(torch.ones(2, 3))
