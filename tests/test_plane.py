"""Split actor/learner device planes (runtime/plane.py, parallel/mesh.py).

The disaggregation contract, pinned on the virtual CPU mesh:

* `dispatch_serialized` keys its locks on the participating DEVICES —
  two programs on disjoint device sets must overlap (the whole split
  design rests on it), while overlapping sets keep the legacy mutual
  exclusion.
* `split_mesh` carves disjoint learner/actor meshes, learner keeping the
  device-list prefix.
* `PlaneParamCache` versions advance monotonically; `RecordTransfer`
  re-lays rollout records onto the learner mesh.
* End to end on 2 learner + 2 actor chips: the actor plane fills the
  learner plane's rings while the learner trains concurrently, loss
  stays finite, and the param versions the actor observes never rewind.
"""

import threading
import time

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.parallel import make_mesh, split_mesh
from handyrl_tpu.parallel.mesh import dispatch_serialized
from handyrl_tpu.runtime.plane import PlaneParamCache, PlaneStats, RecordTransfer

pytestmark = pytest.mark.plane

needs4 = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >= 4 (virtual) devices"
)
needs2 = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 (virtual) devices"
)


# ---------------------------------------------------------------- locks


def _enqueue_on(device):
    """Enqueue a trivial single-device program and return its async out."""
    x = jax.device_put(np.float32(1.0), device)
    return x + 1


@needs2
def test_disjoint_dispatches_overlap():
    """Two disjoint single-device dispatches must be in flight at once.

    Each call() blocks on a shared barrier BEFORE enqueueing: both
    threads can only pass it if dispatch_serialized admitted them
    concurrently.  Under the old global DISPATCH_LOCK the second thread
    would still be waiting to acquire when the first hits the barrier —
    the barrier times out and the test fails."""
    d0, d1 = jax.devices()[:2]
    barrier = threading.Barrier(2, timeout=30.0)
    out, errs = {}, []

    def run(name, dev):
        def call():
            barrier.wait()          # both inside their dispatch, or bust
            return _enqueue_on(dev)

        try:
            out[name] = dispatch_serialized(call, [dev])
        except Exception as exc:  # barrier timeout surfaces here
            errs.append(f"{name}: {exc!r}")

    threads = [
        threading.Thread(target=run, args=("a", d0)),
        threading.Thread(target=run, args=("b", d1)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not errs, errs
    assert float(out["a"]) == 2.0 and float(out["b"]) == 2.0


def test_same_device_dispatches_still_serialize():
    """Overlapping device sets keep the mutual-exclusion guarantee: the
    in-dispatch intervals of two same-device calls never overlap."""
    dev = jax.devices()[0]
    spans = []

    def run():
        def call():
            t0 = time.perf_counter()
            time.sleep(0.05)
            r = _enqueue_on(dev)
            spans.append((t0, time.perf_counter()))
            return r

        dispatch_serialized(call, [dev])

    threads = [threading.Thread(target=run) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert len(spans) == 2
    (a0, a1), (b0, b1) = sorted(spans)
    assert a1 <= b0, f"same-device dispatches overlapped: {spans}"


@needs2
def test_multi_lock_acquisition_no_deadlock():
    """Opposite-order device sets ({d0,d1} vs {d1,d0}) must not deadlock:
    the registry acquires in canonical sorted order."""
    d0, d1 = jax.devices()[:2]
    done = []

    def run(devs):
        dispatch_serialized(lambda: _enqueue_on(devs[0]), devs)
        done.append(devs)

    threads = [
        threading.Thread(target=run, args=([d0, d1],)),
        threading.Thread(target=run, args=([d1, d0],)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert len(done) == 2


# ----------------------------------------------------------- split_mesh


@needs4
def test_split_mesh_partitions_devices():
    devices = jax.devices()[:4]
    learner, actor = split_mesh({"dp": 2}, 2, devices=devices)
    l_ids = [d.id for d in learner.devices.flat]
    a_ids = [d.id for d in actor.devices.flat]
    # disjoint, covering, learner keeps the prefix (device 0 stays the
    # coordinator/checkpoint owner)
    assert set(l_ids) & set(a_ids) == set()
    assert sorted(l_ids + a_ids) == [d.id for d in devices]
    assert l_ids == [d.id for d in devices[:2]]
    assert learner.shape.get("dp") == 2
    assert actor.shape == {"dp": 2}


def test_split_mesh_rejects_bad_actor_chips():
    devices = jax.devices()
    with pytest.raises(ValueError, match="at least one learner device"):
        split_mesh(None, len(devices), devices=devices)
    with pytest.raises(ValueError, match=">= 1"):
        split_mesh(None, 0, devices=devices)


# ------------------------------------------------------- config surface


def test_config_validates_plane():
    ok = normalize_args(
        {
            "env_args": {"env": "HungryGeese"},
            "train_args": {
                "plane": "split",
                "actor_chips": 2,
                "device_rollout_games": 16,
                "turn_based_training": False,
            },
        }
    )
    assert ok["train_args"]["plane"] == "split"

    with pytest.raises(ValueError, match="plane"):
        normalize_args(
            {"env_args": {"env": "HungryGeese"},
             "train_args": {"plane": "sideways"}}
        )
    # the actor plane generates with the on-device streaming rollout
    with pytest.raises(ValueError, match="device_rollout_games"):
        normalize_args(
            {"env_args": {"env": "HungryGeese"},
             "train_args": {"plane": "split"}}
        )
    with pytest.raises(ValueError, match="actor_chips"):
        normalize_args(
            {"env_args": {"env": "HungryGeese"},
             "train_args": {"plane": "split", "actor_chips": 0,
                            "device_rollout_games": 16}}
        )
    with pytest.raises(ValueError, match="param_refresh_updates"):
        normalize_args(
            {"env_args": {"env": "HungryGeese"},
             "train_args": {"plane": "split", "device_rollout_games": 16,
                            "param_refresh_updates": 0}}
        )


# ------------------------------------------------- cross-plane plumbing


def test_param_cache_versions_monotone():
    mesh = make_mesh({"dp": 1}, jax.devices()[-1:])
    cache = PlaneParamCache(mesh)
    params = {"w": np.ones((4, 4), np.float32)}
    with pytest.raises(RuntimeError, match="before first publish"):
        cache.latest()
    cache.publish(params, 0)
    cache.publish(params, 8)
    version, got = cache.latest()
    assert version == 8
    assert [d.id for d in jax.tree.leaves(got)[0].devices()] == [
        jax.devices()[-1].id
    ]
    with pytest.raises(ValueError, match="monotonically"):
        cache.publish(params, 8)
    with pytest.raises(ValueError, match="monotonically"):
        cache.publish(params, 3)
    assert cache.refreshes == 2
    assert cache.bytes_transferred == 2 * 4 * 4 * 4
    assert cache.lag(12) == 4
    assert cache.lag(8) == 0


@needs4
def test_record_transfer_moves_to_learner_mesh():
    devices = jax.devices()[:4]
    learner, actor = split_mesh({"dp": 2}, 2, devices=devices)
    from jax.sharding import NamedSharding, PartitionSpec

    # a (K, B, ...) record batch laid out lane-sharded on the ACTOR mesh
    rec = {
        "obs": jax.device_put(
            np.zeros((4, 8, 3), np.float32),
            NamedSharding(actor, PartitionSpec(None, "dp")),
        )
    }
    xfer = RecordTransfer(learner)
    moved = xfer(rec)
    got_ids = {d.id for d in moved["obs"].sharding.device_set}
    assert got_ids <= {d.id for d in learner.devices.flat}
    assert xfer.transfers == 1
    assert xfer.bytes_transferred == 4 * 8 * 3 * 4


def test_plane_stats_accumulate():
    stats = PlaneStats()
    stats.bump(actor_dispatches=1, param_lag_sum=3.0)
    stats.bump(actor_dispatches=1, actor_busy_s=0.5)
    snap = stats.snapshot()
    assert snap["actor_dispatches"] == 2
    assert snap["param_lag_sum"] == 3.0
    assert snap["actor_busy_s"] == 0.5


# ------------------------------------------------------ end-to-end smoke


@needs4
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_split_plane_smoke():
    """2 learner + 2 actor chips: rollouts on the actor mesh fill the
    learner mesh's rings WHILE the learner trains, loss stays finite, and
    the param versions the actor observes advance monotonically."""
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel import TrainContext
    from handyrl_tpu.runtime.device_replay import DeviceReplay
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn

    devices = jax.devices()[:4]
    learner_mesh, actor_mesh = split_mesh({"dp": 2}, 2, devices=devices)

    env = make_env({"env": "HungryGeese"})
    venv = env.vector_env()
    module = env.net()
    params = init_variables(module, env)["params"]
    cfg = normalize_args(
        {
            "env_args": {"env": "HungryGeese"},
            "train_args": {
                "turn_based_training": False,
                "observation": False,
                "batch_size": 4,
                "forward_steps": 4,
                "burn_in_steps": 0,
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    n_lanes, k_steps = 8, 8
    fn = build_streaming_fn(venv, module, n_lanes, k_steps, mesh=actor_mesh,
                            use_observe_mask=False)
    replay = DeviceReplay(venv, module, args, learner_mesh, n_lanes, slots=64)
    xfer = RecordTransfer(learner_mesh)
    cache = PlaneParamCache(actor_mesh)
    cache.publish(params, 0)

    vstate = venv.init(n_lanes, jax.random.PRNGKey(0))
    hidden = module.initial_state((n_lanes, venv.num_players))
    key = jax.random.PRNGKey(1)
    seen_versions = []

    def rollout():
        nonlocal vstate, hidden, key
        version, p = cache.latest()
        seen_versions.append(version)
        key, sub = jax.random.split(key)
        vstate, hidden, records = dispatch_serialized(
            lambda: fn(p, vstate, hidden, sub), actor_mesh
        )
        return replay.ingest(xfer(records))

    # prefill from the ACTOR plane until the learner rings are sampleable
    deadline = time.monotonic() + 300.0
    while replay.eligible_count() < args["batch_size"]:
        rollout()
        assert time.monotonic() < deadline, "rings never became sampleable"
    assert replay.eligible_count() >= args["batch_size"]

    ctx = TrainContext(module, args, learner_mesh)
    state = ctx.init_state(params)
    train = replay.train_fn(ctx, fused_steps=1)
    state, metrics = train(state, jax.random.PRNGKey(2), 1e-5)  # compile
    jax.block_until_ready(metrics["total"])

    # both planes inside one window: a producer thread keeps rolling out
    # (actor locks only) while this thread trains (learner locks only)
    stop = threading.Event()
    prod = {"dispatches": 0, "error": None}

    def producer():
        try:
            while not stop.is_set():
                rollout()
                prod["dispatches"] += 1
        except Exception as exc:
            prod["error"] = repr(exc)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    steps = 0
    try:
        while prod["dispatches"] < 2 or steps < 3:
            tkey = jax.random.PRNGKey(100 + steps)
            state, metrics = train(state, tkey, 1e-5)
            jax.block_until_ready(metrics["total"])
            steps += 1
            cache.publish(state["params"], steps)
            assert time.monotonic() < deadline, (
                f"planes never both progressed: {steps=} {prod=}"
            )
            time.sleep(0.01)  # hand the unfair locks to the producer
    finally:
        stop.set()
        thread.join(timeout=120.0)
    assert prod["error"] is None, prod["error"]
    assert prod["dispatches"] >= 2          # actor plane ran concurrently
    assert steps >= 3                        # learner plane ran concurrently
    assert np.isfinite(float(jax.device_get(metrics["total"])))
    # the versions the actor observed never rewound, and refreshes landed
    assert seen_versions == sorted(seen_versions)
    assert seen_versions[-1] > seen_versions[0]


@needs4
@pytest.mark.slow
def test_learner_split_plane_end_to_end(tmp_path, monkeypatch):
    """The full Learner under `plane: split`: rollouts on the actor mesh
    feed the learner mesh's rings across two real epochs, and the
    plane-health keys land in metrics.jsonl."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    args = normalize_args(
        {
            "env_args": {"env": "ParallelTicTacToe"},
            "train_args": {
                "plane": "split",
                "actor_chips": 2,
                "param_refresh_updates": 2,
                "mesh": {"dp": 2},
                "turn_based_training": False,
                "observation": False,
                "batch_size": 8,
                "forward_steps": 4,
                "burn_in_steps": 0,
                "device_rollout_games": 8,
                "device_replay": True,
                "device_replay_slots": 64,
                "device_replay_k_steps": 16,
                "minimum_episodes": 20,
                "update_episodes": 30,
                "maximum_episodes": 400,
                "epochs": 2,
                "num_batchers": 1,
                "eval_rate": 0.0,
                "worker": {"num_parallel": 1},
            },
        }
    )
    learner = Learner(args)
    learner.run()

    assert os.path.exists("models/latest.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert records[-1]["steps"] > 0
    # the plane-health keys the soaks watch, from a real split run
    epoch_rows = [r for r in records if "plane_actor_busy_frac" in r]
    assert epoch_rows, f"no plane_* keys in metrics.jsonl: {records}"
    # cumulative counters are diffed per epoch: late epochs can be all
    # idle (episode budget met), but SOME epoch saw the actor plane work
    assert max(r["plane_actor_busy_frac"] for r in epoch_rows) > 0
    assert max(r["plane_xfer_bytes_per_sec"] for r in epoch_rows) > 0
    # the trainer surfaced its realized staleness + refresh count
    assert learner.trainer.stats.get("plane_param_refreshes", 0) > 0
    assert learner.trainer.param_cache.version > 0


# ------------------------------------------------- rung 2: cross-host wire


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gw_dist(port):
    # explicit plane_port: the tests must not depend on health-port
    # derivation (and must not collide with anything else on the host)
    return {"coordinator_address": "127.0.0.1:6000", "plane_port": port}


def test_plane_wire_pack_round_trip():
    from handyrl_tpu.runtime.plane import _pack_tree, _unpack_tree

    tree = {
        "a": {"b": np.arange(6, dtype=np.float32).reshape(2, 3)},
        "c": np.array([1, -2], dtype=np.int8),
    }
    out = _unpack_tree(_pack_tree(tree))
    assert out["a"]["b"].dtype == np.float32
    np.testing.assert_array_equal(out["a"]["b"], tree["a"]["b"])
    np.testing.assert_array_equal(out["c"], tree["c"])
    # non-dict containers cannot round-trip the self-describing flattening
    with pytest.raises(ValueError, match="nested dicts"):
        _pack_tree({"a": [np.zeros(2)]})
    with pytest.raises(ValueError, match="separator"):
        _pack_tree({"a\x1fb": np.zeros(2)})


def test_plane_gateway_round_trip():
    """Records in, versioned params out, monotone versions, byte counts,
    and the clean-stop protocol — one gateway, one client, real sockets."""
    from handyrl_tpu.runtime.plane import PlaneClient, PlaneGateway

    dist = _gw_dist(_free_port())
    received = []
    gw = PlaneGateway(dist, on_records=received.append)
    gw.start()
    client = PlaneClient(dist, timeout=10.0)
    try:
        gw.publish({"w": np.float32([1.0, 2.0])}, 10)
        assert client.connect(retry_for=10.0) == 10
        version, params = client.poll_params(have=-1)
        assert version == 10
        np.testing.assert_array_equal(params["w"], np.float32([1.0, 2.0]))
        # caught up: no payload rides the reply
        version, params = client.poll_params()
        assert version == 10 and params is None
        # records land in on_records BEFORE the reply (the ingest is the
        # ack), and the reply carries the poll hint
        recs = {"obs": np.zeros((4, 2), np.float32), "rew": np.ones((4,), np.float32)}
        assert client.ship_records(recs) == 10
        assert len(received) == 1
        np.testing.assert_array_equal(received[0]["obs"], recs["obs"])
        gw.publish({"w": np.float32([3.0, 4.0])}, 20)
        assert client.ship_records(recs) == 20
        version, fresh = client.poll_params()
        assert version == 20 and fresh is not None
        assert client.param_version == 20
        assert gw.record_batches == 2
        assert gw.bytes_in > 0 and gw.bytes_out > 0
        assert gw.bytes_transferred == gw.bytes_in + gw.bytes_out
        assert gw.lag(23) == 3
        with pytest.raises(ValueError, match="monotonically"):
            gw.publish({"w": np.zeros(2, np.float32)}, 20)
        assert gw.actor_hosts == 1 and gw.actor_hosts_seen == 1
        # run concluding: the next request is answered with a clean stop —
        # the client reports None (exit 0 path), NOT a counted loss
        gw.begin_stop()
        assert client.ship_records(recs) is None
        assert client.stopped
        client.close()
        deadline = time.time() + 5.0
        while gw.actor_hosts > 0 and time.time() < deadline:
            time.sleep(0.01)
        assert gw.actor_host_losses == 0
    finally:
        client.close()
        gw.stop()


def test_plane_gateway_counts_actor_host_loss():
    """Disconnect-after-hello while the run is live = a LOSS the books
    must show (dist_actor_host_losses); the gateway keeps serving."""
    from handyrl_tpu.runtime.plane import PlaneClient, PlaneGateway

    dist = _gw_dist(_free_port())
    gw = PlaneGateway(dist, on_records=lambda r: None)
    gw.start()
    try:
        gw.publish({"w": np.zeros(2, np.float32)}, 1)
        client = PlaneClient(dist, timeout=10.0)
        client.connect(retry_for=10.0)
        client.close()   # vanish mid-run, no goodbye protocol exists
        deadline = time.time() + 5.0
        while gw.actor_host_losses == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert gw.actor_host_losses == 1
        assert gw.actor_hosts == 0
        # the gateway survives its lost producer: a new client connects
        client2 = PlaneClient(dist, timeout=10.0)
        assert client2.connect(retry_for=10.0) == 1
        client2.close()
    finally:
        gw.stop()
