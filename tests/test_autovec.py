"""Twin-less env compiler tests (envs/autovec.py).

Three parity layers pin the lift end to end:

1. rules == scalar env: the pure-numpy rules namespace, executed with
   host numpy, replays random games in lock-step with the 17-method host
   Environment (ConnectFour here; the device-rollout suite replays whole
   device-generated games through the host env on top of this);
2. lift == rules: ``verify()`` steps random games through the numpy
   rules and the lifted jnp env simultaneously (the
   ``autovec_verify_games`` startup self-check);
3. lift == hand twin: the autovectorized TicTacToe is bit-identical to
   the hand-written ``VectorTicTacToe`` on identical action streams —
   the apples-to-apples pair the ``league`` bench stage measures.

Plus the loud-diagnostic contract: every liftability break (in-place
mutation, value-dependent branching, missing jnp API, shape-unstable
apply, np.random) must fail at ``autovectorize`` time as an
``AutovecError`` naming the offending function.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from handyrl_tpu.envs.autovec import AutovecError, autovectorize
from handyrl_tpu.envs.tictactoe import TicTacToeRules
from handyrl_tpu.envs.vector_tictactoe import VectorTicTacToe

pytestmark = pytest.mark.league


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------


def test_connect_four_rules_match_scalar_env():
    """Layer 1: the numpy rules ARE the scalar env's rules — random games
    stepped through both in lock-step (turn view, legality, terminal,
    outcome)."""
    from examples.connect_four import ConnectFourRules as R
    from examples.connect_four import Environment

    rng = np.random.default_rng(3)
    for _ in range(25):
        env = Environment()
        env.reset()
        state = R.init()
        for step in range(R.max_steps):
            assert bool(R.terminal(state, step)) == env.terminal()
            if env.terminal():
                break
            legal = np.flatnonzero(np.asarray(R.legal_mask(state)))
            assert legal.tolist() == env.legal_actions()
            np.testing.assert_allclose(
                np.asarray(R.observation(state, step)),
                env.observation(env.turn()),
                atol=1e-6,
            )
            a = int(rng.choice(legal))
            state = R.apply(state, a, step)
            env.play(a)
        out = np.asarray(R.outcome(state))
        host = env.outcome()
        assert float(out[0]) == host[0] and float(out[1]) == host[1]


def test_verify_passes_for_bundled_rules():
    """Layer 2: the built-in rules namespaces clear their own step-parity
    self-check (what autovec_verify_games runs at Learner startup)."""
    from examples.connect_four import ConnectFourRules

    autovectorize(TicTacToeRules).verify(16, seed=0)
    autovectorize(ConnectFourRules).verify(16, seed=1)


def test_lift_bit_identical_to_hand_twin():
    """Layer 3: autovec TicTacToe vs the hand-written VectorTicTacToe,
    same action stream — every observable bit-equal at every step."""
    V = autovectorize(TicTacToeRules)
    assert (V.num_actions, V.max_steps, V.num_players) == (9, 9, 2)
    rng = np.random.default_rng(0)
    s_a, s_h = V.init(16), VectorTicTacToe.init(16)
    for t in range(V.max_steps):
        assert np.array_equal(
            jax.device_get(V.terminal(s_a, t)),
            jax.device_get(VectorTicTacToe.terminal(s_h, t)),
        )
        la = jax.device_get(V.legal_mask(s_a))
        assert np.array_equal(la, jax.device_get(VectorTicTacToe.legal_mask(s_h)))
        assert np.array_equal(
            jax.device_get(V.observation(s_a, t)),
            jax.device_get(VectorTicTacToe.observation(s_h, t)),
        )
        acts = np.asarray(
            [rng.choice(np.flatnonzero(m)) if m.any() else 0 for m in la],
            np.int32,
        )
        s_a = V.apply(s_a, jnp.asarray(acts), t)
        s_h = VectorTicTacToe.apply(s_h, jnp.asarray(acts), t)
    assert np.array_equal(
        jax.device_get(V.outcome(s_a)), jax.device_get(VectorTicTacToe.outcome(s_h))
    )


def test_lift_is_memoized_and_flagged():
    V = autovectorize(TicTacToeRules)
    assert autovectorize(TicTacToeRules) is V
    assert V.__autovec__ is True
    assert V.rules is TicTacToeRules


def test_example_env_vector_twin_is_the_lift():
    """The zoo's ConnectFour onboards the device path with NO hand
    twin: vector_env() must hand back the autovec lift."""
    from examples.connect_four import ConnectFourRules, Environment

    venv = Environment.vector_env()
    assert venv is autovectorize(ConnectFourRules)


# ---------------------------------------------------------------------------
# loud diagnostics
# ---------------------------------------------------------------------------


def _rules(**overrides):
    """A minimal liftable 2-action namespace, with injectable breakage."""

    class Minimal:
        num_actions = 2
        max_steps = 2
        num_players = 2

        @staticmethod
        def init():
            return {"x": np.zeros(2, np.int8)}

        @staticmethod
        def observation(state, step):
            return state["x"].astype(np.float32)

        @staticmethod
        def legal_mask(state):
            return state["x"] == 0

        @staticmethod
        def terminal(state, step):
            return (state["x"] != 0).all() | (step >= 2)

        @staticmethod
        def apply(state, action, step):
            x = np.where(np.arange(2) == action, np.int8(1), state["x"])
            return {"x": x}

        @staticmethod
        def outcome(state):
            return state["x"].astype(np.float32)

    for name, fn in overrides.items():
        setattr(Minimal, name, staticmethod(fn))
    Minimal.__name__ = "Minimal" + "_".join(overrides) if overrides else "Minimal"
    return Minimal


def test_minimal_rules_lift():
    autovectorize(_rules()).verify(4, seed=0)


def test_inplace_mutation_fails_loudly():
    def apply(state, action, step):
        x = state["x"].copy()
        x[action] = 1                      # in-place: not liftable
        return {"x": x}

    with pytest.raises(AutovecError, match=r"apply.*immutable|apply.*liftab"):
        autovectorize(_rules(apply=apply))


def test_value_dependent_branch_fails_loudly():
    def terminal(state, step):
        if state["x"][0] > 0:              # python branch on array value
            return np.bool_(True)
        return np.bool_(step >= 2)

    with pytest.raises(AutovecError, match="terminal"):
        autovectorize(_rules(terminal=terminal))


def test_missing_jnp_api_fails_loudly():
    def outcome(state):
        return np.busday_count("2026-01", "2026-02") * state["x"].astype(np.float32)

    with pytest.raises(AutovecError, match="busday_count"):
        autovectorize(_rules(outcome=outcome))


def test_np_random_fails_loudly():
    def apply(state, action, step):
        return {"x": (state["x"] + np.random.randint(2)).astype(np.int8)}

    with pytest.raises(AutovecError, match="np.random"):
        autovectorize(_rules(apply=apply))


def test_shape_unstable_apply_fails_loudly():
    def apply(state, action, step):
        return {"x": np.concatenate([state["x"], state["x"]])}

    with pytest.raises(AutovecError, match="shape/dtype-stable|changes state"):
        autovectorize(_rules(apply=apply))


def test_wrong_legal_mask_spec_fails_loudly():
    def legal_mask(state):
        return (state["x"] == 0).astype(np.float32)

    with pytest.raises(AutovecError, match="legal_mask"):
        autovectorize(_rules(legal_mask=legal_mask))


def test_missing_function_fails_loudly():
    bad = _rules()
    del bad.outcome
    with pytest.raises(AutovecError, match="outcome"):
        autovectorize(bad)


def test_totality_wrapper_freezes_finished_lanes():
    """Finished lanes must pass through apply unchanged (the
    vector_common select) even though the traced user apply still ran."""
    V = autovectorize(_rules())
    state = V.init(3)
    # lane 0 finishes at step 0+1 (both cells set? no — one action sets one
    # cell); drive lane 0 two steps so it terminates, then step again
    state = V.apply(state, jnp.asarray([0, 0, 1]), 0)
    state = V.apply(state, jnp.asarray([1, 0, 1]), 1)
    done = jax.device_get(V.terminal(state, 1))       # lane 0 only
    assert done.tolist() == [True, False, False]
    snap = jax.device_get(state["x"])
    state2 = V.apply(state, jnp.asarray([0, 0, 0]), 1)
    snap2 = jax.device_get(state2["x"])
    assert np.array_equal(snap2[done], snap[done])
    assert not np.array_equal(snap2[~done], snap[~done])
