"""HS001 fixture: blocking host syncs in a hot-loop module.

Parsed (never imported) by tests/test_graftlint.py with this file
configured as a hot-loop module.  MUST-trigger sites are tagged in
comments; everything else MUST NOT trigger.
"""

import jax
import numpy as np


def hot_loop_bad(ctx, state, batches):
    for batch in batches:
        state, metrics = ctx.train_step(state, batch, 1e-3)
        jax.block_until_ready(metrics)             # HS001: always-on
        fetched = jax.device_get(metrics)          # HS001: always-on
        loss = metrics["total"].item()             # HS001: always-on
        arr = np.asarray(fetched)                  # HS001: dispatching loop
        val = float(loss)                          # HS001: dispatching loop
    return state, arr, val


def non_dispatching_loop_ok(rows):
    out = []
    for row in rows:
        # float()/np.asarray of host values in a loop that never
        # dispatches: not a per-dispatch sync
        out.append(float(row) + np.asarray(row).sum())
    return out


def epoch_end_ok(metrics):
    # outside any loop: float()/asarray are only loop-scoped primitives
    return float(np.asarray(metrics).sum())


def drain(pending):
    # allowlisted teardown path: the block is the POINT here
    jax.block_until_ready(pending)


class Plane:
    def __init__(self, state):
        # allowlisted construction path
        self.state_host = jax.device_get(state)

    def stop(self):
        jax.block_until_ready(self.state_host)


def pragma_ok(metrics):
    # graftlint: allow[HS001] reason=epoch-end fetch, once per epoch
    return jax.device_get(metrics)
