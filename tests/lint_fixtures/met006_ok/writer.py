"""MET006 ok-fixture writer: every key registered."""

PIPE_STAT_KEYS = ("sample_s", "assemble_s")
SENTINEL_EVENT_KEYS = ("sentinel_rollbacks",)


class W:
    def update(self):
        record = {"epoch": 0}
        record["loss"] = 0.5
        record.update(steps=3)
        self.stats["pipe_sample_s"] = 0.1
        for key in PIPE_STAT_KEYS:
            self.stats["pipe_" + key] = 0.0
        self._write_metrics(record)
