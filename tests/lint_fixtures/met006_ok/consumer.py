"""MET006 ok-fixture consumer: reads only registered keys."""

from handyrl_tpu.utils.metrics import read_metrics


def main(path):
    records = [r for r in read_metrics(path) if r.get("loss")]
    return [(rec["epoch"], rec.get("pipe_sample_s")) for rec in records]
