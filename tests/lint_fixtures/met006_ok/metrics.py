"""MET006 ok-fixture registry."""

METRIC_KEYS = frozenset({"epoch", "loss", "steps", "sentinel_rollbacks"})
METRIC_KEY_PREFIXES = ("pipe_",)
