"""DL002 fixture: compiled-call dispatch sites vs the dispatch locks."""

import jax

from handyrl_tpu.parallel.mesh import dispatch_serialized


def make_fn():
    def f(x):
        return x

    return jax.jit(f)          # marks make_fn as a jit factory


class Roll:
    def __init__(self, mesh):
        self.mesh = mesh
        self._fn = make_fn()                  # factory-bound target
        self._step = jax.jit(lambda x: x)     # directly jit-bound target

    def bad(self, x):
        y = self._step(x)                     # DL002: unwrapped
        z = self._fn(x)                       # DL002: unwrapped (factory)
        w = jax.jit(lambda t: t)(x)           # DL002: immediate invocation
        return y, z, w

    def bad_scope(self, x):
        return dispatch_serialized(lambda: self._step(x))        # DL002: no scope

    def bad_none(self, x):
        return dispatch_serialized(lambda: self._step(x), None)  # DL002: None scope

    def good_lambda(self, x):
        return dispatch_serialized(lambda: self._step(x), self.mesh)

    def good_def(self, x):
        def _run():
            return self._fn(x)

        return dispatch_serialized(_run, self.mesh)

    def good_pragma(self, x):
        # graftlint: allow[DL002] reason=construction-time layout put, runs before any concurrent dispatcher exists
        return jax.jit(lambda t: t)(x)
