"""MET006 pragma-fixture registry."""

METRIC_KEYS = frozenset({"epoch", "loss"})
METRIC_KEY_PREFIXES = ("pipe_",)
