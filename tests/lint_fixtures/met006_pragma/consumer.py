"""MET006 pragma-fixture consumer: the escape hatch works in
contract-rule files too (consumers are not in the scanned path set)."""

from handyrl_tpu.utils.metrics import read_metrics


def main(path):
    records = [r for r in read_metrics(path) if r.get("loss")]
    out = []
    for rec in records:
        # graftlint: allow[MET006] reason=transitional key, writer lands next PR
        out.append(rec.get("transitional_key"))
        # graftlint: allow[MET006]
        out.append(rec.get("reasonless_key"))
    return out
