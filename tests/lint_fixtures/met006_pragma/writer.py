"""MET006 pragma-fixture writer: clean."""


class W:
    def update(self):
        record = {"epoch": 0}
        record["loss"] = 0.5
        self._write_metrics(record)
