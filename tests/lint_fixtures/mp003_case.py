"""MP003 fixture: mp primitives in batcher-child code paths."""

import multiprocessing as mp
import os
import queue as thqueue


def _child_bad(free_q, stop):
    evt = mp.Event()                      # MP003: mp primitive in child
    while not evt.is_set():               # MP003: lock-holding accessor
        if free_q.qsize() > 0:            # MP003: lock-holding accessor
            free_q.get()


def _child_helper(unused):
    return mp.Queue()                     # MP003: reached via _child_chain


def _child_chain():
    _child_helper(None)


def _child_ok(free_q, stop, ready_w):
    while not stop.value:                 # lock-free raw Value: allowed
        try:
            free_q.get(timeout=0.2)       # private per-child queue: allowed
        except thqueue.Empty:
            continue
        os.write(ready_w, b"x")           # raw pipe write: allowed


def parent():
    # parent-side construction is fine — the rule covers CHILD code paths
    q = mp.Queue()
    stop = mp.Value("i", 0, lock=False)
    p1 = mp.Process(target=_child_bad, args=(q, stop))
    p2 = mp.Process(target=_child_ok, args=(q, stop, 1))
    p3 = mp.Process(target=_child_chain)
    return p1, p2, p3
