"""CFG005 bad fixture: one undocumented knob, one stale docs row."""

DEFAULT_TRAIN_ARGS = {
    "gamma": 0.8,
    "undocumented_knob": 1,
    "worker": {"num_parallel": 2},
    "mesh": {"dp": -1},
    # dotted-nested: enabled is documented, min_replicas is not
    "fleet": {"autoscale": {"enabled": False, "min_replicas": 1}},
}

DEFAULT_WORKER_ARGS = {
    "server_address": "",
}
