"""CFG005 ok fixture: defaults and docs in two-way parity."""

DEFAULT_TRAIN_ARGS = {
    "gamma": 0.8,
    "worker": {"num_parallel": 2},
    "mesh": {"dp": -1},
}

DEFAULT_WORKER_ARGS = {
    "server_address": "",
}
