"""CFG005 ok fixture: defaults and docs in two-way parity."""

DEFAULT_TRAIN_ARGS = {
    "gamma": 0.8,
    "worker": {"num_parallel": 2},
    "mesh": {"dp": -1},
    # second-level nesting: "fleet.autoscale" is itself in cfg005_nested,
    # so its children are per-knob rows, not one opaque dict
    "fleet": {"port": 9999, "autoscale": {"enabled": False, "min_replicas": 1}},
}

DEFAULT_WORKER_ARGS = {
    "server_address": "",
}
