"""RNG004 fixture: PRNG keys consumed twice without a split."""

import jax


def double_use_bad(params, fn):
    key = jax.random.PRNGKey(0)
    a = fn(params, key)                    # first consumption
    b = jax.random.normal(key, (3,))       # RNG004: second consumption
    return a, b


def split_ok(params, fn):
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    a = fn(params, sub)
    key, sub = jax.random.split(key)
    b = jax.random.normal(sub, (3,))
    return a, b, key


def branch_ok(params, fn, flag):
    # one consumption per branch: only one branch runs
    key = jax.random.PRNGKey(0)
    if flag:
        return fn(params, key)
    return jax.random.normal(key, (3,))


def loop_rebind_ok(params, fn, n):
    key = jax.random.PRNGKey(0)
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        out.append(fn(params, sub))
    return out
