"""Pragma fixture: suppression with a reason vs a reasonless pragma."""

import jax


def suppressed_trailing(metrics):
    return jax.device_get(metrics)  # graftlint: allow[HS001] reason=unit-test window fetch


def suppressed_above(metrics):
    # graftlint: allow[HS001] reason=unit-test window fetch
    return jax.device_get(metrics)


def reasonless(metrics):
    # graftlint: allow[HS001]
    return jax.device_get(metrics)
