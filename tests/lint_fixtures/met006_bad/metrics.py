"""MET006 bad-fixture registry."""

METRIC_KEYS = frozenset({"epoch", "loss", "steps"})
METRIC_KEY_PREFIXES = ("pipe_",)
