"""MET006 bad-fixture writer: one unregistered key, one bad tuple key."""

PIPE_STAT_KEYS = ("sample_s", "assemble_s")
SENTINEL_EVENT_KEYS = ("unregistered_event",)   # MET006 via tuple


class W:
    def update(self):
        record = {"epoch": 0}
        record["loss"] = 0.5
        record["unregistered_key"] = 2          # MET006
        record.update(steps=3)
        self.stats["pipe_sample_s"] = 0.1       # ok: registered prefix
        for key in PIPE_STAT_KEYS:
            self.stats["pipe_" + key] = 0.0     # ok: literal prefix
        self._write_metrics(record)
