"""MET006 bad-fixture consumer: reads one key no writer registers."""

from handyrl_tpu.utils.metrics import read_metrics


def main(path):
    records = [r for r in read_metrics(path) if r.get("loss")]
    out = []
    for rec in records:
        out.append(rec["epoch"])
        out.append(rec.get("bogus_key"))        # MET006
    return out
