"""graftlint (tools/graftlint) — rule fixtures, pragmas, baselines, and
the repo self-gate.

Every rule has a must-trigger and a must-not-trigger fixture under
tests/lint_fixtures/ (fixtures are PARSED, never imported).  The final
tests run the real configuration over handyrl_tpu/ — the acceptance
gate: the tree lints clean with an empty HS001/DL002/MP003 baseline.
"""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import (
    LintConfig,
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def _fixture_config(**overrides) -> LintConfig:
    cfg = LintConfig(root=FIXTURES)
    for key, value in overrides.items():
        setattr(cfg, key, value)
    return cfg


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


# -- HS001 --------------------------------------------------------------------


def test_hs001_triggers_and_boundaries():
    cfg = _fixture_config(hs001_modules=("hs001_case.py",))
    findings = run_lint(cfg, ["hs001_case.py"], rules=["HS001"])
    hs = _rules(findings, "HS001")
    # exactly the five tagged sites in hot_loop_bad: block_until_ready,
    # device_get, .item(), asarray-in-dispatching-loop, float-in-loop
    assert len(hs) == 5, [f.format() for f in hs]
    src = (FIXTURES / "hs001_case.py").read_text().splitlines()
    assert all("# HS001" in src[f.line - 1] for f in hs), [f.format() for f in hs]
    kinds = " ".join(f.message for f in hs)
    for needle in ("block_until_ready", "device_get", ".item()", "np.asarray", "float()"):
        assert needle in kinds
    # the pragma'd site and allowlisted funcs produced nothing
    assert not any(f.line > 30 for f in hs), [f.format() for f in hs]


def test_hs001_scope_is_module_list():
    # same file NOT configured as a hot module -> no findings
    cfg = _fixture_config(hs001_modules=("some/other/module.py",))
    findings = run_lint(cfg, ["hs001_case.py"], rules=["HS001"])
    assert findings == []


# -- DL002 --------------------------------------------------------------------


def test_dl002_triggers_and_guards():
    cfg = _fixture_config(dl002_modules=("dl002_case.py",))
    findings = run_lint(cfg, ["dl002_case.py"], rules=["DL002"])
    dl = _rules(findings, "DL002")
    # bad(): 3 unwrapped sites; bad_scope(): missing scope; bad_none():
    # explicit None scope — the good_* variants stay silent
    assert len(dl) == 5, [f.format() for f in dl]
    messages = " ".join(f.message for f in dl)
    assert "self._step(...)" in messages
    assert "self._fn(...)" in messages          # factory-bound target
    assert "jax.jit(...)(...)" in messages      # immediate invocation
    assert "explicit device scope" in messages
    src = (FIXTURES / "dl002_case.py").read_text().splitlines()
    for f in dl:
        assert "good" not in src[f.line - 1], f.format()


# -- MP003 --------------------------------------------------------------------


def test_mp003_child_closure():
    cfg = _fixture_config()
    findings = run_lint(cfg, ["mp003_case.py"], rules=["MP003"])
    mp3 = _rules(findings, "MP003")
    # _child_bad: Event + is_set + qsize; _child_helper (via the
    # _child_chain closure): Queue — parent() and _child_ok are silent
    assert len(mp3) == 4, [f.format() for f in mp3]
    messages = " ".join(f.message for f in mp3)
    assert "mp.Event" in messages and "mp.Queue" in messages
    assert ".is_set()" in messages and ".qsize()" in messages
    assert not any("parent" in f.message for f in mp3)


# -- RNG004 -------------------------------------------------------------------


def test_rng004_double_use_only():
    cfg = _fixture_config()
    findings = run_lint(cfg, ["rng004_case.py"], rules=["RNG004"])
    rng = _rules(findings, "RNG004")
    assert len(rng) == 1, [f.format() for f in rng]
    assert "'key'" in rng[0].message
    src = (FIXTURES / "rng004_case.py").read_text().splitlines()
    assert "RNG004" in src[rng[0].line - 1]  # lands on the tagged line


# -- pragmas ------------------------------------------------------------------


def test_pragma_suppresses_and_reasonless_pragma_reports():
    cfg = _fixture_config(hs001_modules=("pragma_case.py",))
    findings = run_lint(cfg, ["pragma_case.py"], rules=["HS001"])
    # both reasoned pragmas (trailing + line-above) suppress their HS001;
    # the reasonless pragma suppresses its target too but surfaces GL000
    assert _rules(findings, "HS001") == [], [f.format() for f in findings]
    gl = _rules(findings, "GL000")
    assert len(gl) == 1, [f.format() for f in findings]
    assert "no reason=" in gl[0].message


# -- CFG005 -------------------------------------------------------------------


def test_cfg005_both_directions():
    cfg = _fixture_config(
        cfg005_config="cfg005_bad/config.py",
        cfg005_docs="cfg005_bad/docs/parameters.md",
    )
    findings = run_lint(cfg, [], rules=["CFG005"])
    msgs = [f.message for f in _rules(findings, "CFG005")]
    assert len(msgs) == 3, msgs
    assert any("undocumented_knob" in m and "no docs" in m for m in msgs)
    assert any("stale_row" in m for m in msgs)
    # dotted-nested section ("fleet.autoscale") flattens to per-knob keys:
    # the undocumented child surfaces, the documented sibling stays silent
    assert any("fleet.autoscale.min_replicas" in m for m in msgs)
    assert not any("fleet.autoscale.enabled" in m for m in msgs)


def test_cfg005_clean_with_alias():
    cfg = _fixture_config(
        cfg005_config="cfg005_ok/config.py",
        cfg005_docs="cfg005_ok/docs/parameters.md",
    )
    assert run_lint(cfg, [], rules=["CFG005"]) == []


# -- MET006 -------------------------------------------------------------------


def _met006_config(tree: str) -> LintConfig:
    return _fixture_config(
        met006_registry=f"{tree}/metrics.py",
        met006_writers=(f"{tree}/writer.py",),
        met006_consumers=(f"{tree}/consumer.py",),
    )


def test_met006_writer_and_consumer_parity():
    findings = run_lint(_met006_config("met006_bad"), [], rules=["MET006"])
    msgs = [f.message for f in _rules(findings, "MET006")]
    assert len(msgs) == 3, msgs
    assert any("unregistered_key" in m for m in msgs)       # direct write
    assert any("unregistered_event" in m for m in msgs)     # via *_KEYS tuple
    assert any("bogus_key" in m and "consumer" in m for m in msgs)


def test_met006_clean():
    assert run_lint(_met006_config("met006_ok"), [], rules=["MET006"]) == []


def test_pragmas_work_in_contract_rule_files():
    """Consumers/writers/docs are NOT in the scanned path set, but the
    pragma escape hatch (and GL000 enforcement) must still cover them —
    otherwise contract-rule findings would only be suppressible via the
    baseline, which is documented as burn-down-only."""
    findings = run_lint(_met006_config("met006_pragma"), [], rules=["MET006"])
    assert _rules(findings, "MET006") == [], [f.format() for f in findings]
    gl = _rules(findings, "GL000")
    assert len(gl) == 1 and "no reason=" in gl[0].message, (
        [f.format() for f in findings]
    )


# -- baseline round trip ------------------------------------------------------


def test_baseline_roundtrip_and_burn_down(tmp_path):
    cfg = _fixture_config(hs001_modules=("hs001_case.py",))
    findings = run_lint(cfg, ["hs001_case.py"], rules=["HS001"])
    assert findings
    baseline_path = tmp_path / "baseline.json"
    write_baseline(baseline_path, findings)

    # same tree + baseline -> everything suppressed, nothing stale
    again = run_lint(cfg, ["hs001_case.py"], rules=["HS001"])
    new, suppressed, stale = apply_baseline(again, load_baseline(baseline_path))
    assert new == [] and len(suppressed) == len(findings) and stale == {}

    # fix one violation -> its fingerprint goes stale (burn-down signal),
    # and content-addressing keeps the others matched despite line drift
    fixed_root = tmp_path / "fixed"
    fixed_root.mkdir()
    src = (FIXTURES / "hs001_case.py").read_text()
    src = src.replace("        jax.block_until_ready(metrics)             # HS001: always-on\n", "\n\n")
    (fixed_root / "hs001_case.py").write_text(src)
    cfg_fixed = LintConfig(root=fixed_root, hs001_modules=("hs001_case.py",))
    after = run_lint(cfg_fixed, ["hs001_case.py"], rules=["HS001"])
    new, suppressed, stale = apply_baseline(after, load_baseline(baseline_path))
    assert new == [], [f.format() for f in new]
    assert len(suppressed) == len(findings) - 1
    assert sum(len(v) for v in stale.values()) == 1


# -- the repo self-gate (acceptance criterion) --------------------------------


def test_repo_lints_clean():
    """THE gate: handyrl_tpu/ has zero unsuppressed findings under the
    real configuration — every invariant either holds or carries a
    reasoned pragma."""
    cfg = LintConfig(root=REPO)
    findings = run_lint(cfg, ["handyrl_tpu/"])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_repo_baseline_is_empty_for_core_rules():
    baseline = load_baseline(REPO / "tools" / "graftlint" / "baseline.json")
    for rule in ("HS001", "DL002", "MP003"):
        assert not baseline.get(rule), (
            f"{rule} baseline must stay empty — fix or pragma-annotate "
            "instead of grandfathering"
        )


def test_cli_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "handyrl_tpu/", "--baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_exits_nonzero_on_findings(tmp_path):
    # a violating file through the real CLI -> exit 1 + a formatted finding
    tree = tmp_path / "repo"
    (tree / "handyrl_tpu" / "runtime").mkdir(parents=True)
    bad = tree / "handyrl_tpu" / "runtime" / "trainer.py"
    bad.write_text(
        "import jax\n\n\ndef loop(fn, state, batches):\n"
        "    for b in batches:\n"
        "        state, m = fn.train_step(state, b, 1e-3)\n"
        "        jax.block_until_ready(m)\n"
        "    return state\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "handyrl_tpu/",
         "--root", str(tree), "--rules", "HS001", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "HS001" in proc.stdout and "trainer.py:7" in proc.stdout
