"""Runtime tests: inference engine, agents, match execution, end-to-end train.

The end-to-end test is the build's analogue of the reference's empirical
validation (README.md:94-103: win rate climbing) compressed into CI scale:
a few epochs on TicTacToe must run through the full learner/actor stack and
produce checkpoints + metrics.
"""

import json
import os
import threading

import numpy as np
import pytest

from handyrl_tpu.agents import Agent, RandomAgent, SoftAgent
from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime import BatchedInferenceEngine, evaluate_mp, exec_match
from handyrl_tpu.runtime.inference_engine import EngineStopped
from handyrl_tpu.runtime.learner import Learner


def _tictactoe_model():
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env)
    return env, InferenceModel(module, variables)


def test_inference_engine_matches_direct():
    env, model = _tictactoe_model()
    engine = BatchedInferenceEngine(model, max_batch=8).start()
    env.reset()
    obs = env.observation(0)

    direct = model.inference(obs)
    results = [None] * 16
    def call(i):
        results[i] = engine.client().inference(obs)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    engine.stop()

    assert engine.requests_served >= 16
    for r in results:
        np.testing.assert_allclose(r["policy"], direct["policy"], rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(r["value"], direct["value"], rtol=2e-4, atol=2e-5)


def test_engine_submit_stop_race_strands_no_future():
    """submit racing stop() must leave NO future pending forever: every
    future a submitter holds resolves — with a result, or EngineStopped.
    The old post-put re-entrant drain lost this race (a second submit
    could land in a queue nobody drained again); the lifecycle lock +
    single-owner drain closes it."""
    env, model = _tictactoe_model()
    for _ in range(5):  # the race needs a few spins to be convincing
        engine = BatchedInferenceEngine(model, max_batch=8, max_wait_ms=0.5).start()
        futures = []
        flock = threading.Lock()
        go = threading.Event()

        def submitter():
            go.wait()
            for _ in range(20):
                fut = engine.submit(env.observation(0))
                with flock:
                    futures.append(fut)

        threads = [threading.Thread(target=submitter) for _ in range(4)]
        for t in threads:
            t.start()
        go.set()
        engine.stop()  # fires while submitters are mid-burst
        for t in threads:
            t.join(30)
        with flock:
            pending = list(futures)
        for fut in pending:
            try:
                out = fut.result(timeout=30)  # hangs here = the old bug
                assert "policy" in out
            except EngineStopped:
                pass


def test_exec_match_agents():
    env, model = _tictactoe_model()
    agents = {0: Agent(model), 1: RandomAgent()}
    outcome = exec_match(env, agents)
    assert outcome is not None
    assert set(outcome) == {0, 1}
    assert abs(outcome[0] + outcome[1]) < 1e-6  # zero-sum


def test_soft_agent_samples_legal():
    env, model = _tictactoe_model()
    agent = SoftAgent(model)
    env.reset()
    agent.reset(env)
    for _ in range(5):
        a = agent.action(env, env.turn())
        assert a in env.legal_actions(env.turn())


def test_parse_eval_spec():
    """CLI parity: ':' separates evaluated model from opponent (reference
    evaluation.py:383-402); '+' joins ensemble members."""
    from handyrl_tpu.runtime.evaluation import parse_eval_spec

    assert parse_eval_spec("models/1.ckpt") == {
        "main": "models/1.ckpt",
        "opponent": "random",
    }
    assert parse_eval_spec("models/1.ckpt:models/2.ckpt") == {
        "main": "models/1.ckpt",
        "opponent": "models/2.ckpt",
    }
    assert parse_eval_spec("a.ckpt+b.ckpt:rulebase") == {
        "main": "a.ckpt+b.ckpt",
        "opponent": "rulebase",
    }
    with pytest.raises(ValueError):
        parse_eval_spec("a:b:c")


def test_model_vs_model_eval():
    """--eval A:B pits two checkpoints against each other offline."""
    env, model = _tictactoe_model()
    a = Agent(model)
    b = Agent(InferenceModel(model.module, model.variables))
    results = evaluate_mp({"env": "TicTacToe"}, {0: a, 1: b}, num_games=6, num_workers=2)
    games = sum(sum(r.values()) for r in results.values())
    assert games == 6


def test_ensemble_agent_pools_members():
    env, model = _tictactoe_model()
    from handyrl_tpu.agents import EnsembleAgent

    single = Agent(model)
    double = EnsembleAgent([model, model])
    env.reset()
    single.reset(env)
    double.reset(env)
    obs = env.observation(env.turn())
    np.testing.assert_allclose(
        single._forward(obs)["policy"], double._forward(obs)["policy"], rtol=1e-5
    )


def test_evaluate_mp_random_vs_random(capsys):
    agents = {0: RandomAgent(), 1: RandomAgent()}
    results = evaluate_mp({"env": "TicTacToe"}, agents, num_games=20, num_workers=4)
    games = sum(sum(r.values()) for r in results.values())
    assert games == 20
    out = capsys.readouterr().out
    assert "total =" in out


@pytest.mark.slow
def test_end_to_end_training(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 8,  # divisible by the 8-device dp mesh
            "forward_steps": 4,
            "minimum_episodes": 10,
            "update_episodes": 15,
            "maximum_episodes": 100,
            "epochs": 2,
            "num_batchers": 1,
            "eval_rate": 0.2,
            "worker": {"num_parallel": 2},
        },
    })
    learner = Learner(args)
    learner.run()

    assert os.path.exists("models/latest.ckpt")
    assert os.path.exists("models/2.ckpt")
    assert os.path.exists("models/state.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= 2
    assert records[-1]["steps"] > 0
    assert learner.num_returned_episodes >= 25


@pytest.mark.slow
def test_training_learns_tictactoe(tmp_path, monkeypatch):
    """The reference's only empirical bar, as a test: win rate vs random
    must CLIMB over training (README.md:94-103).  ~120 epochs / ~1000
    updates of the default TD/TD objective lift TicTacToe self-play from
    the random-vs-random baseline (~0.65 with seat balancing, first-player
    advantage included) to >=0.75; probe runs land the final-20-epoch mean
    around 0.80, so 0.72 leaves ~5 sigma of eval noise (~900 games)."""
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 64,
            "forward_steps": 8,
            "minimum_episodes": 100,
            "update_episodes": 100,
            "maximum_episodes": 3000,
            "epochs": 120,
            "num_batchers": 1,
            "eval_rate": 0.25,
            "worker": {"num_parallel": 6},
        },
    })
    Learner(args).run()

    win = [
        json.loads(l).get("win_rate", {}).get("total")
        for l in open("metrics.jsonl")
    ]
    win = [w for w in win if w is not None]
    assert len(win) >= 100
    early = float(np.mean(win[:20]))
    late = float(np.mean(win[-20:]))
    assert late >= 0.72, f"final win rate {late:.3f} (early {early:.3f})"
    assert late > early, f"no climb: early {early:.3f} -> late {late:.3f}"


@pytest.mark.slow
def test_training_learns_tictactoe_transformer(tmp_path, monkeypatch):
    """The same empirical bar for the transformer family: the KV-cache
    memory net (seq-attention training path, whole-window einsum) must
    climb vs random through the full --train stack.  Probe run
    (2026-08-01, 1-core host, ~13 min): early-20 mean 0.721 -> late-20
    mean 0.912, so the 0.72 floor leaves wide margin."""
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "TicTacToe", "net": "transformer",
                     "net_args": {"d_model": 64, "n_heads": 4,
                                  "n_layers": 2, "memory_len": 16}},
        "train_args": {
            "batch_size": 64,
            "forward_steps": 8,
            "burn_in_steps": 0,
            "observation": True,
            "seq_attention": "einsum",
            "minimum_episodes": 100,
            "update_episodes": 100,
            "maximum_episodes": 3000,
            "epochs": 120,
            "num_batchers": 1,
            "eval_rate": 0.25,
            "worker": {"num_parallel": 6},
        },
    })
    Learner(args).run()

    win = [
        json.loads(l).get("win_rate", {}).get("total")
        for l in open("metrics.jsonl")
    ]
    win = [w for w in win if w is not None]
    assert len(win) >= 100
    early = float(np.mean(win[:20]))
    late = float(np.mean(win[-20:]))
    assert late >= 0.72, f"final win rate {late:.3f} (early {early:.3f})"
    assert late > early, f"no climb: early {early:.3f} -> late {late:.3f}"
