"""Pod-slice-shaped multichip dryruns: n=16 and n=32 virtual devices
(VERDICT r4 #8 — the v4-32 extrapolation should rest on more than an
8-device dryrun).

Each run executes the FULL sharded surface in a CPU-forced subprocess —
dp x mp train step, dp x sp ring-attention transformer step, sp ring
attention golden check, dp streaming rollout, dp device replay (both
modes) — and must report finite losses plus compile/step timing stats,
which docs/performance.md records.  n=32 compiles several minutes of
XLA on the 1-core host, hence slow-marked.
"""

import re
import subprocess
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as graft


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", [16, 32])
def test_pod_slice_dryrun(n_devices):
    cmd, env, cwd = graft.dryrun_subprocess_spec(n_devices)
    proc = subprocess.run(
        cmd, env=env, cwd=cwd, capture_output=True, text=True, timeout=3600
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"dryrun({n_devices}) failed:\n{out[-3000:]}"
    line = next(
        (l for l in proc.stdout.splitlines() if l.startswith("dryrun_multichip")),
        "",
    )
    assert f"dryrun_multichip({n_devices}): ok" in line, out[-2000:]
    # timing stats present for the scaling record
    assert re.search(r"compile=[\d.]+s step=\d+ms", line), line
    # the pod-slice-shaped dp x sp transformer stage ran (n%4==0 here)
    assert f"transformer dp={n_devices // 4} sp=4" in line, line
    # the split actor/learner plane leg ran (half the devices each)
    assert f"split-plane {n_devices // 2}L+{n_devices // 2}A" in line, line
    print(line)
