"""Batch assembly + jitted sharded train step tests (8-device CPU mesh)."""

import random

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.ops import compute_loss_from_outputs
from handyrl_tpu.parallel import TrainContext, make_mesh, forward_prediction
from handyrl_tpu.runtime.batch import make_batch
from handyrl_tpu.runtime.generation import Generator
from handyrl_tpu.runtime.replay import EpisodeStore


def _gen_episodes(env_name, n, train_args, seed=0):
    random.seed(seed)
    env = make_env({"env": env_name})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=seed))
    gen = Generator(env, train_args)
    models = {p: model for p in env.players()}
    args = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    eps = []
    while len(eps) < n:
        ep = gen.generate(models, args)
        if ep is not None:
            eps.append(ep)
    return env, module, model, eps


def _args(env_name="TicTacToe", **over):
    raw = {"env_args": {"env": env_name}, "train_args": over}
    return normalize_args(raw)["train_args"]


def test_generation_episode_format():
    targs = _args()
    env, module, model, eps = _gen_episodes("TicTacToe", 3, targs)
    ep = eps[0]
    assert ep["steps"] >= 5
    assert set(ep["outcome"].keys()) == {0, 1}
    assert len(ep["blocks"]) == (ep["steps"] + 3) // 4  # compress_steps=4


def test_make_batch_shapes_turn_based():
    targs = _args(batch_size=4, forward_steps=8)
    env, module, model, eps = _gen_episodes("TicTacToe", 6, targs)
    store = EpisodeStore(100)
    store.extend(eps)
    windows = [store.sample_window(8, 0, 4) for _ in range(4)]
    batch = make_batch(windows, targs)
    B, T = 4, 8
    assert batch["observation"].shape == (B, T, 1, 3, 3, 3)  # turn player only
    assert batch["selected_prob"].shape == (B, T, 1, 1)
    assert batch["action"].shape == (B, T, 1, 1)
    assert batch["action_mask"].shape == (B, T, 1, 9)
    assert batch["value"].shape == (B, T, 2, 1)  # all players
    assert batch["turn_mask"].shape == (B, T, 2, 1)
    assert batch["outcome"].shape == (B, 1, 2, 1)
    assert batch["episode_mask"].shape == (B, T, 1, 1)
    assert batch["progress"].shape == (B, T, 1)
    # each unpadded step has exactly one acting player
    acting = batch["turn_mask"].sum(axis=2)[..., 0]
    assert set(np.unique(acting)).issubset({0.0, 1.0})
    # padded region: episode_mask 0, selected_prob 1, amask all-illegal
    pad = batch["episode_mask"][..., 0, 0] == 0
    if pad.any():
        assert np.all(batch["selected_prob"][pad] == 1.0)
        assert np.all(batch["action_mask"][pad] >= 1e31)


def test_make_batch_value_padding_is_outcome():
    targs = _args(batch_size=2, forward_steps=16)
    env, module, model, eps = _gen_episodes("TicTacToe", 4, targs, seed=1)
    store = EpisodeStore(100)
    store.extend(eps)
    windows = [store.sample_window(16, 0, 4) for _ in range(2)]
    batch = make_batch(windows, targs)
    pad = batch["episode_mask"][..., 0, 0] == 0  # (B, T)
    for b in range(2):
        for t in np.flatnonzero(pad[b]):
            np.testing.assert_array_equal(batch["value"][b, t], batch["outcome"][b, 0])


def test_forward_prediction_and_loss_finite():
    targs = _args(batch_size=2, forward_steps=8)
    env, module, model, eps = _gen_episodes("TicTacToe", 4, targs, seed=2)
    store = EpisodeStore(100)
    store.extend(eps)
    batch = make_batch([store.sample_window(8, 0, 4) for _ in range(2)], targs)
    variables = model.variables
    outputs = forward_prediction(module, variables["params"], batch, targs)
    assert outputs["policy"].shape == (2, 8, 1, 9)
    assert outputs["value"].shape == (2, 8, 2, 1)  # broadcast to all players
    losses, dcnt = compute_loss_from_outputs(outputs, batch, targs)
    assert float(dcnt) > 0
    for k, v in losses.items():
        assert np.isfinite(float(v)), f"loss {k} not finite"


@pytest.mark.parametrize("env_name,policy_target", [("TicTacToe", "TD"), ("TicTacToe", "VTRACE")])
def test_train_step_runs_on_mesh(env_name, policy_target):
    targs = _args(env_name, batch_size=8, forward_steps=8, policy_target=policy_target)
    env, module, model, eps = _gen_episodes(env_name, 6, targs, seed=3)
    store = EpisodeStore(100)
    store.extend(eps)
    mesh = make_mesh({"dp": -1})
    assert mesh.shape["dp"] == 8  # conftest forces 8 virtual devices
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(model.variables["params"])
    batch = ctx.put_batch(make_batch([store.sample_window(8, 0, 4) for _ in range(8)], targs))
    state, metrics = ctx.train_step(state, batch, 1e-3)
    assert int(jax.device_get(state["steps"])) == 1
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"])
    assert m["dcnt"] > 0


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)
    and jax.default_backend() == "cpu",
    reason="seed-reproducing environmental failure on this container's jax "
    "0.4.x XLA:CPU: the fixed-batch total loss is non-monotone over 10 steps "
    "at lr 1e-3 (observed seed-4 trajectory starts at -3.39 and oscillates "
    "through +46/-9 without decreasing) — identical at the seed commit, so "
    "it measures this jax/backend's optimizer numerics, not a repo "
    "regression.  Reproduce: JAX_PLATFORMS=cpu python -m pytest "
    "tests/test_training.py::test_train_step_learns_direction on jax<0.5",
)
def test_train_step_learns_direction():
    """A few steps of training increase the probability of chosen actions
    that won (policy gradient sanity on a fixed batch)."""
    targs = _args(batch_size=8, forward_steps=8, entropy_regularization=0.0)
    env, module, model, eps = _gen_episodes("TicTacToe", 8, targs, seed=4)
    store = EpisodeStore(100)
    store.extend(eps)
    mesh = make_mesh({"dp": -1})
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(model.variables["params"])
    batch_np = make_batch([store.sample_window(8, 0, 4) for _ in range(8)], targs)
    batch = ctx.put_batch(batch_np)
    first = None
    for _ in range(10):
        state, metrics = ctx.train_step(state, batch, 1e-3)
        total = float(jax.device_get(metrics["total"]))
        if first is None:
            first = total
    assert total < first, f"loss did not decrease: {first} -> {total}"


def test_geister_rnn_train_step():
    """Recurrent path: burn-in scan + hidden-carry masking compiles and runs."""
    targs = _args(
        "Geister",
        batch_size=8,
        forward_steps=4,
        burn_in_steps=2,
        observation=True,
        compress_steps=4,
    )
    env, module, model, eps = _gen_episodes("Geister", 2, targs, seed=5)
    store = EpisodeStore(100)
    store.extend(eps)
    mesh = make_mesh({"dp": -1})
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(model.variables["params"])
    batch = ctx.put_batch(make_batch([store.sample_window(4, 2, 4) for _ in range(8)], targs))
    state, metrics = ctx.train_step(state, batch, 1e-4)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"])
    assert np.isfinite(m["r"])  # return head in play


@pytest.mark.slow  # ~40s of unroll-vs-scan recompiles on 1 CPU core;
# the slow CI leg keeps it green
def test_geister_rnn_unroll_remat_match_scan():
    """The CPU-fallback strategy (fully unrolled scan) and the TPU strategy
    (looped scan + jax.checkpoint remat) must produce the same update as
    the plain loop — same program, different schedule (train_step.py
    backend-aware scan strategy)."""
    targs = _args(
        "Geister",
        batch_size=4,
        forward_steps=4,
        burn_in_steps=2,
        observation=True,
        compress_steps=4,
    )
    env, module, model, eps = _gen_episodes("Geister", 2, targs, seed=7)
    store = EpisodeStore(100)
    store.extend(eps)
    mesh = make_mesh({"dp": 1})  # single device: the gate under test
    windows = [store.sample_window(4, 2, 4) for _ in range(4)]
    host_batch = make_batch(windows, targs)

    results = {}
    for name, over in {
        "scan": {"unroll": False, "remat": False},
        "unroll": {"unroll": True, "remat": False},
        "remat": {"unroll": False, "remat": True},
    }.items():
        ctx = TrainContext(module, dict(targs, **over), mesh)
        state = ctx.init_state(model.variables["params"])
        state, metrics = ctx.train_step(state, ctx.put_batch(host_batch), 1e-4)
        results[name] = (
            jax.device_get(metrics["total"]),
            jax.device_get(jax.tree.leaves(state["params"])[0]),
        )
    for name in ("unroll", "remat"):
        np.testing.assert_allclose(results[name][0], results["scan"][0], rtol=2e-5)
        np.testing.assert_allclose(
            results[name][1], results["scan"][1], rtol=2e-4, atol=1e-6
        )


def test_block_cache_returns_frozen_identical_columns():
    """Decoded blocks are cached (same object back) and frozen read-only so
    an accidental in-place write cannot corrupt later batches."""
    from handyrl_tpu.runtime.replay import compress_block, decompress_block

    cols = {
        "prob": np.random.rand(4, 2).astype(np.float32),
        "turn": np.zeros(4, np.int32),
    }
    blob = compress_block(cols)
    a = decompress_block(blob)
    b = decompress_block(blob)
    assert a is b  # cache hit
    np.testing.assert_array_equal(a["prob"], cols["prob"])
    with pytest.raises(ValueError):
        a["prob"][0, 0] = 5.0
    # identical content under a different bytes object dedups by value
    c = decompress_block(bytes(blob))
    assert c is a


def test_fused_steps_matches_sequential():
    """fused_steps=k (one lax.scan jit call) must reproduce k separate
    train_step calls: same batches, same lr, same final params/metrics."""
    targs = _args(batch_size=8, forward_steps=8)
    env, module, model, eps = _gen_episodes("TicTacToe", 6, targs, seed=5)
    store = EpisodeStore(100)
    store.extend(eps)
    host_batches = [
        make_batch([store.sample_window(8, 0, 4) for _ in range(8)], targs)
        for _ in range(2)
    ]
    mesh = make_mesh({"dp": -1})
    ctx = TrainContext(module, targs, mesh)

    state = ctx.init_state(model.variables["params"])
    metrics_seq = []
    for hb in host_batches:
        state, m = ctx.train_step(state, ctx.put_batch(hb), 1e-3)
        metrics_seq.append(jax.device_get(m))
    seq_params = jax.device_get(state["params"])

    state2 = ctx.init_state(model.variables["params"])
    state2, mf = ctx.train_steps(state2, ctx.put_batches(host_batches), 1e-3)
    fused_params = jax.device_get(state2["params"])
    mf = jax.device_get(mf)

    # scan vs unrolled lets XLA fuse differently -> float reassociation
    # noise at the 1e-7 level; anything beyond that is a semantics bug
    for a, b in zip(jax.tree.leaves(seq_params), jax.tree.leaves(fused_params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    for k in ("total", "dcnt"):
        np.testing.assert_allclose(
            sum(m[k] for m in metrics_seq), mf[k], rtol=1e-5
        )
    assert int(jax.device_get(state2["steps"])) == 2


def test_lr_scale_multiplies_reference_schedule():
    """lr_scale: 1.0 is exact reference parity (3e-8 x data-count EMA,
    train.py:328-332); k multiplies the whole schedule, steps decay and
    EMA dynamics untouched."""
    from handyrl_tpu.runtime.trainer import Trainer

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    params = init_variables(module, env)["params"]
    mesh = make_mesh({"dp": 1})
    scaled = Trainer(_args(lr_scale=8.0), module, params, mesh)
    assert scaled.default_lr == pytest.approx(8.0 * 3e-8)
    lr0 = scaled.lr
    scaled.steps = 1000
    assert scaled.lr == pytest.approx(lr0 / (1 + 1000 * 1e-5))


def test_jaxpr_flops_close_to_hlo():
    """The backend-free analytic counter (flops_per_step fallback 3) must
    track XLA:CPU's HLO 'flops' — it substitutes for it when the platform
    list is pinned to a plugin with no cost model (axon TPU)."""
    import jax.numpy as jnp

    from handyrl_tpu.parallel.train_step import jaxpr_flops

    targs = _args("TicTacToe", batch_size=4, forward_steps=8)
    env, module, model, eps = _gen_episodes("TicTacToe", 6, targs, seed=5)
    store = EpisodeStore(100)
    store.extend(eps)
    mesh = make_mesh({"dp": 1})
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(model.variables["params"])
    batch = ctx.put_batch(
        make_batch([store.sample_window(8, 0, 4) for _ in range(4)], targs)
    )
    # the HLO reference must come from a REAL cost model — flops_per_step
    # falls back to jaxpr_flops itself, which would make this vacuous
    ca = ctx._bind(state).lower(state, batch, jnp.float32(1e-5)).cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    hlo = float(ca.get("flops", 0.0)) if ca else 0.0
    if hlo <= 0:
        pytest.skip("backend reports no HLO flops; nothing to compare against")
    analytic = jaxpr_flops(
        jax.make_jaxpr(ctx._step_fn)(state, batch, jnp.float32(1e-5)).jaxpr
    )
    assert 0.5 < analytic / hlo < 2.0, (analytic, hlo)


def test_peak_flops_lookup():
    from types import SimpleNamespace

    from handyrl_tpu.parallel.train_step import peak_flops_per_chip

    assert peak_flops_per_chip(SimpleNamespace(device_kind="TPU v5 lite")) == 197e12
    assert peak_flops_per_chip(SimpleNamespace(device_kind="TPU v5p")) == 459e12
    assert peak_flops_per_chip(SimpleNamespace(device_kind="cpu")) is None
    assert peak_flops_per_chip(SimpleNamespace()) is None


def test_trainer_reports_mfu_with_known_peak(monkeypatch):
    """End of the first trained epoch resolves FLOPs/update once and, when
    the chip's peak rate is known, emits an 'mfu' stat that rides into
    metrics.jsonl (round-4: MFU is a product stat, not just a bench
    extra).  The CPU host has no peak entry, so the lookup is patched."""
    import handyrl_tpu.parallel.train_step as ts
    from handyrl_tpu.runtime.trainer import Trainer

    fake_peak = 1e12
    monkeypatch.setattr(ts, "peak_flops_per_chip", lambda d: fake_peak)

    targs = _args(batch_size=4, minimum_episodes=2, mesh={"dp": 1})
    targs["env"] = {"env": "TicTacToe"}
    env, module, model, eps = _gen_episodes("TicTacToe", 8, targs)
    trainer = Trainer(targs, module, model.variables["params"], make_mesh({"dp": 1}))
    trainer.store.extend(eps)
    trainer.batcher.start()
    trainer.update_flag = True  # epoch ends after the first completed update
    try:
        trainer.train_epoch()
    finally:
        trainer.stop()

    assert trainer._flops_per_update and trainer._flops_per_update > 1e6, (
        trainer._flops_per_update
    )
    assert "mfu" in trainer.stats and trainer.stats["mfu"] > 0
    # mfu = flops * updates/s / peak (mesh.size == 1)
    expect = (
        trainer._flops_per_update
        * trainer.stats["train_steps_per_sec"]
        / fake_peak
    )
    assert abs(trainer.stats["mfu"] - expect) < max(1e-6, 0.01 * expect)


def test_device_replay_train_fn_exposes_flops():
    """The device-replay fused train program reports analytic FLOPs per
    update (trace-only) for the same MFU stat."""
    from handyrl_tpu.envs.vector_hungry_geese import VectorHungryGeese
    from handyrl_tpu.runtime.device_replay import DeviceReplay

    targs = _args(
        "HungryGeese", batch_size=4, forward_steps=4,
        turn_based_training=False, observation=False, mesh={"dp": 1},
    )
    targs["env"] = {"env": "HungryGeese"}
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    params = init_variables(module, env)["params"]
    mesh = make_mesh({"dp": 1})
    ctx = TrainContext(module, targs, mesh)
    state = ctx.init_state(params)

    replay = DeviceReplay(VectorHungryGeese, module, targs, mesh, 4, slots=64)
    # one ingest materializes the rings (their shapes are what the trace
    # needs; eligibility doesn't matter — nothing executes)
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn

    fn = build_streaming_fn(VectorHungryGeese, module, 4, 16, mesh=None,
                            use_observe_mask=False)
    vstate = VectorHungryGeese.init(4, jax.random.PRNGKey(0))
    _, _, records = fn(params, vstate, None, jax.random.PRNGKey(1))
    replay.ingest(records)

    train = replay.train_fn(ctx, fused_steps=2)
    flops = train.flops_per_update(state)
    assert flops > 1e6, flops
    # per-update: doubling fused_steps must not change the number (~exact:
    # same body, scan length divides back out)
    flops4 = replay.train_fn(ctx, fused_steps=4).flops_per_update(state)
    assert abs(flops - flops4) / flops < 0.05, (flops, flops4)


def test_flops_per_step_accepts_avals():
    """The fused-path FLOPs resolution hands flops_per_step ShapeDtypeStruct
    leaves (a concrete slice would dispatch outside the per-device
    dispatch locks); the
    lowering must accept avals and agree with the concrete-batch count."""
    targs = _args(batch_size=4)
    targs["env"] = {"env": "TicTacToe"}
    env, module, model, eps = _gen_episodes("TicTacToe", 6, targs)
    store = EpisodeStore(100)
    store.extend(eps)
    windows = []
    while len(windows) < 4:
        w = store.sample_window(targs["forward_steps"], targs["burn_in_steps"],
                                targs["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, targs)
    ctx = TrainContext(module, targs, make_mesh({"dp": 1}))
    state = ctx.init_state(model.variables["params"])
    db = ctx.put_batch(batch)
    concrete = ctx.flops_per_step(state, db)
    avals = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), db)
    assert concrete and concrete > 0
    assert ctx.flops_per_step(state, avals) == concrete
