"""Fleet serving tier (docs/serving.md §Fleet tier).

Three layers, pinned smallest-first:

* socket-free SessionCache semantics — open → infer×N → LRU evict to
  the spill ring → bit-identical restore; affinity-miss fallback; close
  releases capacity;
* the serving client's liveness/desync satellites — the stall deadline
  failing pending futures loudly, orphaned reply frames counted;
* wire-level integration — server-resident sessions bit-identical with
  the ship-state path (and ≥5× lighter on the wire), the router's
  bounded replica_lost failover with session re-routing, fleet-wide
  swap, and the edge replica's capability fence.
"""

import socket
import threading
import time

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.fleet import EdgeReplica, FleetRouter, SessionCache
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime.connection import (
    FramedConnection,
    accept_socket_connections,
    open_socket_connection,
)
from handyrl_tpu.serving import ModelRouter, ServingClient, ServingError, ServingServer

pytestmark = pytest.mark.fleet

SERVING_CFG = {
    "port": 0,
    "max_models": 3,
    "slo_ms": 2000.0,
    "shed_policy": "none",
    "max_batch": 8,
    "max_wait_ms": 1.0,
    "warm_buckets": [1, 4, 8],
    "queue_bound": 256,
    "recv_timeout": 0.0,
    "watch_interval": 0.0,
    "stats_interval": 0.0,
    "session_capacity": 64,
    "session_spill": 256,
}

FLEET_CFG = {
    "port": 0,
    "stats_poll_s": 0.2,
    "replica_stall_s": 5.0,
    "rejoin_backoff_s": 0.2,
    "rejoin_backoff_max_s": 1.0,
    "stats_interval": 0.0,
}


def _env_model(name):
    env = make_env({"env": name})
    module = env.net()
    env.reset()
    obs = env.observation(env.players()[0])
    params = init_variables(module, env, seed=1)["params"]
    return module, obs, params


def _start_server(module, obs, params, tmp_path, **cfg_overrides):
    cfg = dict(SERVING_CFG, **cfg_overrides)
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    router.publish(1, params)
    server = ServingServer(router, cfg).run()
    return server


def _fleet(server_ports, **overrides):
    cfg = dict(FLEET_CFG, **overrides)
    cfg["replicas"] = [
        e if isinstance(e, dict) else f"127.0.0.1:{e}" for e in server_ports
    ]
    return FleetRouter(cfg).run(connect_timeout=5.0)


# ---------------------------------------------------------------------------
# SessionCache (socket-free)
# ---------------------------------------------------------------------------


def _hidden(seed):
    rng = np.random.RandomState(seed)
    return (rng.randn(3, 4).astype(np.float32), rng.randn(2).astype(np.float32))


def test_session_cache_roundtrip_and_lru_restore():
    cache = SessionCache(capacity=2, spill_capacity=8)
    sids = [cache.open() for _ in range(3)]
    assert len(set(sids)) == 3
    states = {sid: _hidden(i) for i, sid in enumerate(sids)}
    for sid, h in states.items():
        cache.store(sid, h)
    # capacity 2: the LRU (first-stored) session spilled to host
    stats = cache.stats()
    assert stats["session_resident"] == 2
    assert stats["session_spilled"] == 1
    assert stats["session_evictions"] == 1
    # touching the spilled session re-pins it BIT-IDENTICAL and counts
    # the restore; something else becomes LRU and spills in its place
    h, status = cache.lookup(sids[0])
    assert status == "restored"
    for got, want in zip(h, states[sids[0]]):
        assert np.array_equal(np.asarray(got), np.asarray(want))
    stats = cache.stats()
    assert stats["session_restored"] == 1
    assert stats["session_resident"] == 2
    # resident lookups stay resident and exact
    h2, status2 = cache.lookup(sids[0])
    assert status2 == "resident"
    for got, want in zip(h2, states[sids[0]]):
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_session_cache_close_releases_capacity():
    cache = SessionCache(capacity=1, spill_capacity=4)
    a, b = cache.open(), cache.open()
    cache.store(a, _hidden(1))
    cache.store(b, _hidden(2))  # evicts a to spill
    assert cache.close(a) is True
    assert cache.close(a) is False  # already gone, from the spill tier
    assert cache.close(b) is True
    stats = cache.stats()
    assert stats["session_resident"] == 0
    assert stats["session_spilled"] == 0
    assert stats["session_closed"] == 2
    # a closed sid looks up as a miss (fresh-state fallback), counted
    h, status = cache.lookup(a)
    assert h is None and status == "miss"
    assert cache.stats()["session_affinity_miss"] == 1


def test_session_cache_spill_overflow_drops_oldest():
    cache = SessionCache(capacity=1, spill_capacity=1)
    sids = [cache.open() for _ in range(3)]
    for i, sid in enumerate(sids):
        cache.store(sid, _hidden(i))
    # resident: sids[2]; spill(cap 1): sids[1]; sids[0] dropped
    stats = cache.stats()
    assert stats["session_resident"] == 1
    assert stats["session_spilled"] == 1
    assert stats["session_spill_drops"] == 1
    h, status = cache.lookup(sids[0])
    assert h is None and status == "miss"
    # the miss is recoverable: the next store re-adopts the sid
    cache.store(sids[0], _hidden(9))
    h, status = cache.lookup(sids[0])
    assert status in ("resident", "restored")
    assert np.array_equal(np.asarray(h[0]), _hidden(9)[0])


def test_session_cache_overflow_miss_reopens_fresh_not_restore():
    """Satellite accounting pin: a spill-overflowed sid re-surfaces as
    exactly ONE counted affinity miss, the re-adopted sid is a fresh open
    (not a restore, not a second miss), and its eventual close counts as
    a real close — the overflow→miss→reopen ledger stays honest."""
    cache = SessionCache(capacity=1, spill_capacity=1)
    sids = [cache.open() for _ in range(3)]
    for i, sid in enumerate(sids):
        cache.store(sid, _hidden(i))
    # sids[0] dropped off the ring: first lookup is THE counted miss
    h, status = cache.lookup(sids[0])
    assert h is None and status == "miss"
    assert cache.stats()["session_affinity_miss"] == 1
    # a pipelined second lookup before the re-adopting store is a FRESH
    # start, not another miss — one loss event, one count
    h, status = cache.lookup(sids[0])
    assert h is None and status == "fresh"
    stats = cache.stats()
    assert stats["session_affinity_miss"] == 1
    assert stats["session_restored"] == 0, "reopen must not count a restore"
    # the re-adopted sid is live again: store lands it, close releases it
    cache.store(sids[0], _hidden(9))
    closed_before = cache.stats()["session_closed"]
    assert cache.close(sids[0])
    assert cache.stats()["session_closed"] == closed_before + 1


def test_session_cache_store_drops_stale_spill_copy():
    """A stateless-override store (wire hidden wins) must pop the sid's
    stale spill-ring copy: the spilled gauge stays honest and the ring
    slot is freed instead of evicting some other session for it."""
    cache = SessionCache(capacity=1, spill_capacity=4)
    a, b = cache.open(), cache.open()
    cache.store(a, _hidden(1))
    cache.store(b, _hidden(2))       # a evicted to the spill ring
    assert cache.stats()["session_spilled"] == 1
    cache.store(a, _hidden(3))       # fresh store: stale spill copy popped
    stats = cache.stats()
    # b is now the spilled one (evicted by a's store); a's old copy gone
    assert stats["session_spilled"] == 1
    h, status = cache.lookup(a)
    assert status == "resident"
    assert np.array_equal(np.asarray(h[0]), _hidden(3)[0])


def test_session_cache_export_adopt_is_zero_loss_and_bit_identical():
    """Migration seam, socket-free: export_all realizes BOTH tiers and
    the fresh set, clears the source (fork guard: stragglers are loud
    misses), and adopt lands everything on the successor — stateful
    sessions restore bit-identical through the counted spill path and
    fresh sids stay fresh, zero counted losses."""
    src = SessionCache(capacity=1, spill_capacity=8)
    dst = SessionCache(capacity=4, spill_capacity=8)
    sids = [src.open() for _ in range(3)]
    states = {sid: _hidden(i) for i, sid in enumerate(sids)}
    for sid, h in states.items():
        src.store(sid, h)            # capacity 1: two of them spilled
    fresh_sid = src.open()           # opened, never stored
    shipped = src.export_all()
    assert set(shipped["sessions"]) == set(sids)
    assert shipped["fresh"] == [fresh_sid]
    assert src.stats()["session_migrated_out"] == 3
    # the source is CLEARED — a straggler infer is a loud miss, not a fork
    assert src.stats()["session_resident"] == 0
    assert src.stats()["session_spilled"] == 0
    _, status = src.lookup(sids[0])
    assert status == "miss"
    # the successor adopts; every stateful session restores bit-identical
    assert dst.adopt(shipped["sessions"], fresh=shipped["fresh"]) == 3
    assert dst.stats()["session_migrated_in"] == 3
    for sid in sids:
        h, status = dst.lookup(sid)
        assert status == "restored", f"{sid}: {status}"
        for got, want in zip(h, states[sid]):
            assert np.array_equal(np.asarray(got), np.asarray(want))
    assert dst.stats()["session_affinity_miss"] == 0
    # the migrated fresh sid starts fresh on the successor — no phantom miss
    h, status = dst.lookup(fresh_sid)
    assert h is None and status == "fresh"
    assert dst.stats()["session_affinity_miss"] == 0


def test_session_cache_adopt_overflow_is_counted_not_wedged():
    """A too-small successor ring overflows EXACTLY like local spills:
    oldest dropped and counted in session_spill_drops, the rest live."""
    src = SessionCache(capacity=8, spill_capacity=8)
    sids = [src.open() for _ in range(4)]
    for i, sid in enumerate(sids):
        src.store(sid, _hidden(i))
    shipped = src.export_all()
    dst = SessionCache(capacity=8, spill_capacity=2)
    dst.adopt(shipped["sessions"], fresh=shipped["fresh"])
    stats = dst.stats()
    assert stats["session_spilled"] == 2
    assert stats["session_spill_drops"] == 2
    assert stats["session_migrated_in"] == 4


# ---------------------------------------------------------------------------
# client satellites: stall deadline + orphaned replies
# ---------------------------------------------------------------------------


def test_client_stall_deadline_fails_pending_loudly():
    """A peer that holds the socket open but stops sending must fail the
    pending futures with a NAMED error within the stall deadline — never
    hang them until per-call timeouts."""
    sock = open_socket_connection(0)
    sock.listen(8)  # backlog up BEFORE the client connects (the accept
    # generator also listens, but its thread may not have started yet)
    port = sock.getsockname()[1]
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.extend(
            c for c in accept_socket_connections(timeout=5.0, sock=sock, maxsize=1) if c
        ),
        daemon=True,
    )
    t.start()
    client = ServingClient("127.0.0.1", port, stall_timeout=0.5)
    try:
        t0 = time.monotonic()
        fut = client.submit(np.zeros(3, np.float32))
        with pytest.raises(ServingError) as err:
            fut.result(timeout=10)
        assert err.value.kind == "stalled"
        assert time.monotonic() - t0 < 5.0  # bounded, not the 10s timeout
    finally:
        client.close()
        sock.close()


def test_client_idle_connection_survives_stall_deadline():
    """The stall deadline only reaps a peer with requests PENDING: an
    idle bursty client keeps its connection."""
    sock = open_socket_connection(0)
    sock.listen(8)  # backlog up BEFORE the client connects (the accept
    # generator also listens, but its thread may not have started yet)
    port = sock.getsockname()[1]
    conns = []
    t = threading.Thread(
        target=lambda: conns.extend(
            c for c in accept_socket_connections(timeout=5.0, sock=sock, maxsize=1) if c
        ),
        daemon=True,
    )
    t.start()
    client = ServingClient("127.0.0.1", port, stall_timeout=0.2)
    try:
        time.sleep(0.8)  # several idle stall windows pass
        t.join(timeout=5)
        assert conns, "server never saw the connection"
        # the connection still works: a reply sent now resolves a request
        server_conn = conns[0]
        server_conn.send(("result", {"rid": 1, "model": 0, "out": {"x": 1}}))
        fut = client.submit(np.zeros(3, np.float32))  # becomes rid 1
        assert fut.result(timeout=10)["out"] == {"x": 1}
    finally:
        client.close()
        sock.close()


def test_client_counts_orphaned_replies():
    """Reply frames with a missing/unknown rid (a desynced server) are
    counted, not silently discarded."""
    sock = open_socket_connection(0)
    sock.listen(8)  # backlog up BEFORE the client connects (the accept
    # generator also listens, but its thread may not have started yet)
    port = sock.getsockname()[1]
    conns = []
    t = threading.Thread(
        target=lambda: conns.extend(
            c for c in accept_socket_connections(timeout=5.0, sock=sock, maxsize=1) if c
        ),
        daemon=True,
    )
    t.start()
    client = ServingClient("127.0.0.1", port)
    try:
        t.join(timeout=5)
        assert conns
        conns[0].send(("result", {"rid": 999, "out": {}}))   # unknown rid
        conns[0].send(("result", {"out": {}}))               # missing rid
        deadline = time.monotonic() + 5.0
        while client.replies_orphaned < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.replies_orphaned == 2
    finally:
        client.close()
        sock.close()


# ---------------------------------------------------------------------------
# server-resident sessions over the wire (recurrent model)
# ---------------------------------------------------------------------------


def test_sessions_bit_identical_with_ship_state_and_lighter(tmp_path):
    """THE session acceptance pin: a server-resident session replays the
    exact trajectory of the ship-state-both-ways loop — bit-identical
    outputs — while the wire carries no hidden state in either
    direction."""
    module, obs, params = _env_model("Geister")
    server = _start_server(module, obs, params, tmp_path)
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        steps = 4
        # leg 1: stateless ship-state loop (serial, batch-1: deterministic)
        hidden = InferenceModel(module, {"params": params}).init_hidden()
        shipped = []
        for _ in range(steps):
            out = client.infer(obs, hidden=hidden)["out"]
            hidden = out.pop("hidden")
            shipped.append(out)
        ship_sent, ship_recv = client.wire_bytes()

        # leg 2: the same trajectory through a server-resident session
        sid = client.open_session()
        b0_sent, b0_recv = client.wire_bytes()
        sessioned = []
        for _ in range(steps):
            reply = client.infer(obs, sid=sid)
            assert reply["sid"] == sid
            assert "hidden" not in reply["out"], "session reply shed its state"
            sessioned.append(reply["out"])
        s_sent = client.wire_bytes()[0] - b0_sent
        s_recv = client.wire_bytes()[1] - b0_recv

        for a, b in zip(shipped, sessioned):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))
        # Geister's DRC hidden (~27 KB/step each way) dwarfs the obs: the
        # session leg must be >= 5x lighter per request in BOTH directions
        assert ship_sent / max(s_sent, 1) >= 5.0
        assert ship_recv / max(s_recv, 1) >= 5.0

        stats = client.stats()
        assert stats["session_opened"] == 1
        assert stats["session_resident"] == 1
        assert client.close_session(sid)["existed"] is True
        assert client.stats()["session_resident"] == 0
    finally:
        client.close()
        server.shutdown()


def test_session_disabled_is_a_loud_bad_request(tmp_path):
    module, obs, params = _env_model("TicTacToe")
    server = _start_server(module, obs, params, tmp_path, session_capacity=0)
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        with pytest.raises(ServingError) as err:
            client.open_session()
        assert err.value.kind == "bad_request"
        with pytest.raises(ServingError) as err:
            client.infer(obs, sid="s-nope")
        assert err.value.kind == "bad_request"
        # the stateless path is untouched
        assert client.infer(obs)["model"] == 1
    finally:
        client.close()
        server.shutdown()


# ---------------------------------------------------------------------------
# fleet router
# ---------------------------------------------------------------------------


def test_router_proxies_and_balances(tmp_path):
    module, obs, params = _env_model("TicTacToe")
    s1 = _start_server(module, obs, params, tmp_path / "a")
    s2 = _start_server(module, obs, params, tmp_path / "b")
    fleet = _fleet([s1.bound_port, s2.bound_port])
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        direct = InferenceModel(module, {"params": params}).inference(obs)
        futs = [client.submit(obs) for _ in range(32)]
        for fut in futs:
            out = fut.result(timeout=30)
            assert out["model"] == 1
            np.testing.assert_allclose(
                out["out"]["policy"], direct["policy"], rtol=2e-4, atol=2e-5
            )
        stats = client.stats()
        assert stats["fleet_replies"] == 32
        assert stats["fleet_replicas_live"] == 2
        assert len(stats["replicas"]) == 2
        # both replicas actually served (round-robin at equal load)
        assert all(
            r["serve_replies"] >= 1 for r in stats["replicas"].values()
        )
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()
        s2.shutdown()


def test_router_failover_is_bounded_and_survivors_serve(tmp_path):
    """THE failover acceptance pin, updated for the elastic fleet's
    bounded-retry contract: an in-flight STATEFUL request on a killed
    replica fails loudly (replica_lost, bounded, never a hang) because a
    session infer is not idempotent from the router's seat — while
    stateless traffic keeps succeeding on the survivor, and the dead
    replica's sessions re-route with a counted affinity miss."""
    module, obs, params = _env_model("Geister")
    s1 = _start_server(module, obs, params, tmp_path / "a")
    s2 = _start_server(module, obs, params, tmp_path / "b")
    # stats_poll 5s: the background poll can't race this test's kill —
    # the first post-kill request is what discovers the dead replica
    fleet = _fleet([s1.bound_port, s2.bound_port], replica_stall_s=2.0,
                   stats_poll_s=5.0)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    servers = {s1.bound_port: s1, s2.bound_port: s2}
    try:
        # two sessions: with round-robin-at-equal-load picks they land on
        # different replicas, so one of them lives on the victim
        sids = [client.open_session() for _ in range(2)]
        for sid in sids:
            assert client.infer(obs, sid=sid)["sid"] == sid
        owners = {rep.spec.port: sid for sid, rep in
                  ((s, fleet._affinity[s]) for s in sids)}
        assert len(owners) == 2, "sessions should spread over both replicas"

        victim_port = s1.bound_port
        servers[victim_port].shutdown()

        # stateful request pinned to the (still-assumed-live) victim:
        # loud bounded replica_lost — never retried, never a hang
        lost_sid = owners[victim_port]
        t0 = time.monotonic()
        with pytest.raises(ServingError) as err:
            client.infer(obs, sid=lost_sid, timeout=15)
        assert err.value.kind == "replica_lost"
        assert time.monotonic() - t0 < 10.0, "failover must be bounded"

        # the survivor keeps serving stateless traffic, no errors
        for _ in range(4):
            assert client.infer(obs, timeout=15) is not None

        # the victim's session re-routes to the survivor: served fresh-
        # state (affinity miss counted there), same sid, no hang
        reply = client.infer(obs, sid=lost_sid, timeout=30)
        assert reply["sid"] == lost_sid
        stats = client.stats()
        assert stats["fleet_replicas_live"] == 1
        assert stats["fleet_replica_lost"] >= 1
        survivor = stats["replicas"][f"127.0.0.1:{s2.bound_port}"]
        assert survivor["session_affinity_miss"] >= 1
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()
        s2.shutdown()


@pytest.mark.slow  # ~5s of loss-detection waits; CI fleet step runs it
def test_router_retries_stateless_requests_once_on_replica_loss(tmp_path):
    """Satellite pin, the other half of the failover contract: a no-sid
    in-flight request caught on a dying replica is retried ONCE on a
    survivor (counted in fleet_failover_retries) and succeeds — the
    caller never sees the loss."""
    module, obs, params = _env_model("TicTacToe")
    s1 = _start_server(module, obs, params, tmp_path / "a")
    s2 = _start_server(module, obs, params, tmp_path / "b")
    fleet = _fleet([s1.bound_port, s2.bound_port], replica_stall_s=2.0,
                   stats_poll_s=5.0)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        assert client.infer(obs) is not None  # fleet warm end-to-end
        # force the next pick onto the victim: the survivor looks loaded
        victim = next(r for r in fleet._reps()
                      if r.spec.port == s1.bound_port)
        for rep in fleet._reps():
            rep.load = 0.0 if rep is victim else 999.0
            rep.picked = 0
        s1.shutdown()
        # routed to the "live" victim, transport fails, retried on the
        # survivor — the caller just sees a reply
        reply = client.infer(obs, timeout=15)
        assert reply is not None
        stats = client.stats()
        assert stats["fleet_failover_retries"] == 1
        assert stats["fleet_replicas_live"] == 1
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()
        s2.shutdown()


def test_fleet_wide_swap_flips_every_replica(tmp_path):
    module, obs, params = _env_model("TicTacToe")
    env = make_env({"env": "TicTacToe"})
    params2 = init_variables(module, env, seed=2)["params"]
    s1 = _start_server(module, obs, params, tmp_path / "a")
    s2 = _start_server(module, obs, params, tmp_path / "b")
    fleet = _fleet([s1.bound_port, s2.bound_port])
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        reply = client.swap(2, params=params2)
        assert reply["replicas"] == 2
        assert reply["warm_ms"] >= 0
        # every subsequent request, whichever replica it lands on, serves
        # the new latest
        for _ in range(8):
            assert client.infer(obs)["model"] == 2
        assert client.stats()["fleet_hot_swaps"] == 1
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()
        s2.shutdown()


# ---------------------------------------------------------------------------
# edge replica
# ---------------------------------------------------------------------------


def test_edge_replica_serves_wire_protocol():
    module, obs, params = _env_model("TicTacToe")
    model = InferenceModel(module, {"params": params})
    edge = EdgeReplica(model, port=0, workers=2).run()
    client = ServingClient("127.0.0.1", edge.bound_port)
    try:
        direct = model.inference(obs)
        reply = client.infer(obs)
        assert reply["model"] == 0  # one frozen artifact, no generations
        np.testing.assert_allclose(
            reply["out"]["policy"], direct["policy"], rtol=2e-4, atol=2e-5
        )
        stats = client.stats()
        assert stats["serve_replies"] == 1
        # stateful requests are refused loudly, swap likewise
        with pytest.raises(ServingError) as err:
            client.infer(obs, sid="s-x")
        assert err.value.kind == "bad_request"
        with pytest.raises(ServingError) as err:
            client.swap(2, params=params)
        assert err.value.kind == "bad_request"
    finally:
        client.close()
        edge.shutdown()


def test_router_keeps_stateful_routes_off_edge(tmp_path):
    """The capability fence: with an edge replica registered, sessions and
    wire-hidden requests land only on full replicas; stateless requests
    may use edge capacity."""
    module, obs, params = _env_model("Geister")
    full = _start_server(module, obs, params, tmp_path)
    model = InferenceModel(module, {"params": params})
    edge = EdgeReplica(model, port=0, workers=2).run()
    fleet = _fleet([
        full.bound_port,
        {"host": "127.0.0.1", "port": edge.bound_port, "tags": ["edge"]},
    ])
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        sid = client.open_session()
        owner = fleet._affinity[sid]
        assert not owner.is_edge
        for _ in range(3):
            assert client.infer(obs, sid=sid)["sid"] == sid
        # ship-state is stateful too: never routed to edge (which would
        # refuse it) — every request succeeds
        hidden = model.init_hidden()
        out = client.infer(obs, hidden=hidden)["out"]
        assert "hidden" in out
    finally:
        client.close()
        fleet.shutdown()
        edge.shutdown()
        full.shutdown()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def _cfg(**over):
    train = {"fleet": over.pop("fleet", {}), "serving": over.pop("serving", {})}
    return {"env_args": {"env": "TicTacToe"}, "train_args": train}


def test_fleet_config_validation():
    ok = normalize_args(_cfg())["train_args"]
    assert ok["fleet"]["port"] == 9996
    assert ok["serving"]["session_capacity"] == 1024
    with pytest.raises(ValueError, match="host:port"):
        normalize_args(_cfg(fleet={"replicas": ["nocolon"]}))
    with pytest.raises(ValueError, match="host.*port"):
        normalize_args(_cfg(fleet={"replicas": [{"port": 1}]}))
    with pytest.raises(ValueError, match="stats_poll_s"):
        normalize_args(_cfg(fleet={"stats_poll_s": 0}))
    with pytest.raises(ValueError, match="replica_stall_s"):
        normalize_args(_cfg(fleet={"replica_stall_s": -1}))
    with pytest.raises(ValueError, match="rejoin_backoff_max_s"):
        normalize_args(_cfg(fleet={"rejoin_backoff_s": 5.0,
                                   "rejoin_backoff_max_s": 1.0}))
    with pytest.raises(ValueError, match="edge_workers"):
        normalize_args(_cfg(fleet={"edge_workers": 0}))
    with pytest.raises(ValueError, match="session_capacity"):
        normalize_args(_cfg(serving={"session_capacity": -1}))
    with pytest.raises(ValueError, match="fleet.port"):
        normalize_args(_cfg(fleet={"port": 70000}))
