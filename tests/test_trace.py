"""Observability plane: the span tracer, its exporter, and the
zero-overhead pin (docs/observability.md).

The contract under test, in priority order:

1. **Provably free when off** — `trace_span` disabled returns ONE shared
   no-op object (no allocation), and a real `batch_pipeline: device`
   window with tracing off records ZERO blocking host syncs and ZERO XLA
   recompiles under the PR 9 sanitizers: the instrumentation cannot have
   added a hot-path cost it claims not to have.
2. **Never blocking when on** — a full span ring DROPS and counts
   (`trace_dropped`), the flusher drains in the background, and a
   trace-enabled window still shows zero recompiles (spans are host-side
   bookkeeping, not device work).
3. **Crash-tolerant** — `read_trace` tolerates exactly one truncated
   FINAL line; mid-file corruption raises.
4. **Exportable** — the Chrome/Perfetto exporter's mapping is pinned by
   a committed golden (regenerate intentionally with
   HANDYRL_REGEN_GOLDEN=1).
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.obs

from handyrl_tpu.utils import trace as trace_mod
from handyrl_tpu.utils.trace import (
    META_NAME,
    read_trace,
    trace_event,
    trace_span,
    trace_stats,
)

GOLDEN_DIR = Path(__file__).parent / "golden"
SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")


@pytest.fixture(autouse=True)
def _tracer_reset():
    """Every test leaves the process tracer disarmed (the module singleton
    is process-global state shared with any Learner the suite builds)."""
    trace_mod.shutdown()
    yield
    trace_mod.shutdown()


def _configure(tmp_path, rank=0, **over):
    cfg = {"enabled": True, "path": str(tmp_path / "trace.jsonl"),
           "ring_size": 4096, "flush_interval": 0.05}
    cfg.update(over)
    assert trace_mod.configure(cfg, rank=rank)
    return trace_mod.current_path()


# -- disabled path ------------------------------------------------------------


def test_disabled_span_is_one_shared_noop_object():
    """The disabled fast path allocates nothing: every call returns the
    SAME context-manager instance, and nothing is recorded."""
    a = trace_span("x", plane="learner")
    b = trace_span("y")
    assert a is b
    with a:
        pass
    trace_event("z", 0.5)
    assert trace_stats() == {"trace_spans": 0, "trace_dropped": 0}


def test_unwritable_sink_fails_at_configure_naming_the_knob(tmp_path):
    """A run ASKED to trace must fail at startup, not record nothing."""
    with pytest.raises(ValueError, match="trace.path"):
        trace_mod.configure({
            "enabled": True,
            "path": str(tmp_path / "no" / "such" / "dir" / "t.jsonl"),
        })
    assert not trace_mod.enabled()


# -- enabled recording --------------------------------------------------------


def test_span_nesting_and_attribution(tmp_path):
    path = _configure(tmp_path)
    with trace_span("outer", plane="learner"):
        with trace_span("inner", step=3):
            time.sleep(0.01)

    done = threading.Event()

    def worker():
        with trace_span("threaded"):
            pass
        done.set()

    threading.Thread(target=worker, name="obs-worker", daemon=True).start()
    assert done.wait(5.0)
    trace_mod.shutdown()

    recs = {r["name"]: r for r in read_trace(path) if r["name"] != META_NAME}
    assert set(recs) == {"outer", "inner", "threaded"}
    outer, inner = recs["outer"], recs["inner"]
    # temporal containment: the nested span lies inside its parent
    assert outer["t_mono"] <= inner["t_mono"]
    assert inner["t_mono"] + inner["dur_s"] <= outer["t_mono"] + outer["dur_s"] + 1e-6
    assert inner["dur_s"] >= 0.01
    assert inner["attrs"] == {"step": 3}
    assert outer["attrs"] == {"plane": "learner"}
    # attribution: thread name + rank ride every record
    assert recs["threaded"]["thread"] == "obs-worker"
    assert all(r["rank"] == 0 for r in recs.values())
    # the wall<->monotonic anchor is the file's first line
    first = read_trace(path)[0]
    assert first["name"] == META_NAME and first["version"] >= 1


def test_ring_overflow_drops_counted_never_blocking(tmp_path):
    _configure(tmp_path, ring_size=8, flush_interval=999.0)  # flusher idle
    t0 = time.perf_counter()
    for _ in range(100):
        trace_event("spam", 0.001)
    elapsed = time.perf_counter() - t0
    stats = trace_stats()
    assert stats["trace_spans"] == 8
    assert stats["trace_dropped"] == 92
    # 100 drops in well under a flush interval: the full ring never blocks
    assert elapsed < 1.0


def test_rank_suffix_path_derivation(tmp_path):
    path = _configure(tmp_path, rank=2)
    assert path.endswith("trace.rank2.jsonl")
    with trace_span("s"):
        pass
    trace_mod.shutdown()
    recs = read_trace(path)
    assert all(r["rank"] == 2 for r in recs)


# -- crash tolerance ----------------------------------------------------------


def test_truncated_tail_tolerated_mid_file_raises(tmp_path):
    path = _configure(tmp_path)
    for i in range(3):
        trace_event(f"s{i}", 0.001)
    trace_mod.shutdown()
    # a kill mid-append leaves a half-written FINAL line: tolerated
    with open(path, "a") as f:
        f.write('{"name": "torn", "ts": 1.0, "dur_')
    recs = read_trace(path)
    assert [r["name"] for r in recs if r["name"] != META_NAME] == ["s0", "s1", "s2"]
    # but corruption anywhere EARLIER is a real integrity failure
    lines = open(path).read().splitlines()
    lines[1] = lines[1][: len(lines[1]) // 2]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError):
        read_trace(path)


# -- Perfetto export ----------------------------------------------------------


def _export_chrome():
    sys.path.insert(0, SCRIPTS)
    try:
        from trace_export import export_chrome
    finally:
        sys.path.remove(SCRIPTS)
    return export_chrome


def test_perfetto_export_matches_golden():
    """The exporter's mapping (event shape, cross-rank wall alignment,
    deterministic tid assignment) is pinned by a committed golden built
    from the fixture files; regenerate with HANDYRL_REGEN_GOLDEN=1."""
    export_chrome = _export_chrome()
    record_lists = [
        read_trace(str(GOLDEN_DIR / "trace_fixture.jsonl")),
        read_trace(str(GOLDEN_DIR / "trace_fixture_rank1.jsonl")),
    ]
    out = export_chrome(record_lists)
    golden_path = GOLDEN_DIR / "trace_perfetto.json"
    if os.environ.get("HANDYRL_REGEN_GOLDEN"):
        golden_path.write_text(json.dumps(out, indent=1) + "\n")
        pytest.skip("golden regenerated; commit tests/golden/ and re-run")
    assert out == json.loads(golden_path.read_text()), (
        "Perfetto export drifted from the committed golden; if intentional, "
        "regenerate with HANDYRL_REGEN_GOLDEN=1"
    )


def test_real_trace_round_trips_through_the_exporter(tmp_path):
    """write -> read_trace -> export: every recorded span becomes exactly
    one complete ('X') event with in-range timestamps."""
    path = _configure(tmp_path)
    with trace_span("a", plane="learner"):
        with trace_span("b"):
            pass
    trace_event("c", 0.01, plane="pipeline")
    trace_mod.shutdown()
    export_chrome = _export_chrome()
    recs = read_trace(path)
    out = export_chrome([recs])
    xs = [e for e in out["traceEvents"] if e["ph"] == "X"]
    assert sorted(e["name"] for e in xs) == ["a", "b", "c"]
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    assert {e["cat"] for e in xs} == {"learner", "pipeline", "trace"}


def test_export_cli_writes_chrome_trace(tmp_path):
    import subprocess

    path = _configure(tmp_path)
    with trace_span("cli_span"):
        pass
    trace_mod.shutdown()
    out_path = tmp_path / "export.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "trace_export.py"), path,
         "-o", str(out_path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    data = json.loads(out_path.read_text())
    assert any(e["name"] == "cli_span" for e in data["traceEvents"])


# -- the zero-overhead pin (acceptance) ---------------------------------------


def _pipeline_window():
    """One warm batch_pipeline: device window (the test_sanitizers
    surface): pipeline batch() sampling dispatches + real train steps."""
    # tests/ is on sys.path under pytest's rootdir insertion (no
    # tests/__init__.py), same mechanism the scripts use for _logparse
    from test_sanitizers import _device_pipeline

    return _device_pipeline(dp=2)


@pytest.mark.slow
def test_trace_disabled_window_is_sync_and_recompile_free():
    """Acceptance pin: with `trace: false` (the default) the instrumented
    hot path — dispatch_serialized spans, pipeline wait events, train-step
    spans all compiled IN but disarmed — adds ZERO blocking host syncs and
    ZERO XLA recompiles to a warm streaming window.  This is the harness
    that keeps 'off by default and provably free' true."""
    from handyrl_tpu.utils.sanitizers import HostSyncSanitizer, RecompileSentinel

    assert not trace_mod.enabled()
    pipe, ctx, state, stop = _pipeline_window()
    try:
        batch = pipe.batch()  # warm: ring init + sampler jit
        assert batch is not None
        state, _ = ctx.train_step(state, batch, 1e-5)
        with HostSyncSanitizer() as sync, RecompileSentinel() as sentinel:
            for _ in range(4):
                batch = pipe.batch()
                assert batch is not None
                state, _ = ctx.train_step(state, batch, 1e-5)
        sync.assert_clean("trace: false device-pipeline window")
        sentinel.assert_no_recompiles("trace: false device-pipeline window")
    finally:
        stop.set()
        pipe.stop()


@pytest.mark.slow
def test_trace_enabled_window_records_spans_without_recompiles(tmp_path):
    """Arming the tracer must not change the compiled program either: the
    same warm window records the dispatch/train/pipe spans and still
    shows zero XLA recompiles (spans are host bookkeeping, not device
    work)."""
    from handyrl_tpu.utils.sanitizers import RecompileSentinel

    pipe, ctx, state, stop = _pipeline_window()
    try:
        batch = pipe.batch()
        assert batch is not None
        state, _ = ctx.train_step(state, batch, 1e-5)
        path = _configure(tmp_path)
        with RecompileSentinel() as sentinel:
            for _ in range(4):
                batch = pipe.batch()
                assert batch is not None
                state, _ = ctx.train_step(state, batch, 1e-5)
        trace_mod.shutdown()
        sentinel.assert_no_recompiles("trace: true device-pipeline window")
        names = {r["name"] for r in read_trace(path)}
        # the window's seams all reported: the per-dispatch spans and the
        # pipeline's measured waits
        assert "dispatch.run" in names, names
        assert "dispatch.wait" in names, names
    finally:
        stop.set()
        pipe.stop()
