"""Learning soaks on the flagship envs (run explicitly: pytest -m soak).

The reference's empirical bar is "win rate climbs over training"
(README.md:94-103); round 2 proved it end-to-end for TicTacToe only
(tests/test_runtime.py::test_training_learns_tictactoe).  These soaks
extend the same bar to the two flagship paths the framework exists for:

* HungryGeese (the north-star competition env, README.md:116) trained
  ENTIRELY by streaming on-device self-play, evaluated against the greedy
  rule-based opponent (envs/hungry_geese.py rule_based_action — the
  reference's kaggle/hungry_geese.py:60-66 food-greedy baseline);
* Geister (imperfect-information, README.md:117 family) through the DRC
  ConvLSTM recurrent path with burn-in + UPGO, evaluated against random.

Each asserts (a) the win curve CLIMBS and (b) a floor calibrated from
probe runs on the 1-core CI host, with the full curve left in
metrics.jsonl for inspection.
"""

import json

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime.learner import Learner


def _win_curve(path="metrics.jsonl", key="total"):
    win = []
    for line in open(path):
        w = json.loads(line).get("win_rate", {}).get(key)
        if w is not None:
            win.append(w)
    return win


@pytest.mark.soak
@pytest.mark.slow  # belt and braces: CI's `-m "not slow"` overrides addopts
def test_geese_device_selfplay_beats_rulebase(tmp_path, monkeypatch):
    """GeeseNet trained ONLY by on-device streaming self-play must climb
    against the greedy rule-based agent (3 opponent seats).  Win points
    count a top-half finish as a win (outcome > 0), so random-ish play
    scores well under 0.5 while food-greedy survival play scores above.
    """
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 32,
            "forward_steps": 16,
            "minimum_episodes": 60,
            "update_episodes": 60,
            "maximum_episodes": 2000,
            "epochs": 30,
            "num_batchers": 1,
            "eval_rate": 0.9,          # host workers exist to measure, not generate
            "device_rollout_games": 64,
            "worker": {"num_parallel": 4},
            "eval": {"opponent": ["rulebase"]},
        },
    })
    Learner(args).run()

    win = _win_curve()
    assert len(win) >= 20, f"only {len(win)} eval epochs recorded"
    early = float(np.mean(win[:5]))
    late = float(np.mean(win[-10:]))
    assert late > early, f"no climb vs rulebase: {early:.3f} -> {late:.3f}"
    assert late >= 0.35, f"final win points vs rulebase {late:.3f} (early {early:.3f})"


@pytest.mark.soak
@pytest.mark.slow
def test_geister_drc_beats_random(tmp_path, monkeypatch):
    """GeisterNet (DRC ConvLSTM) through the recurrent burn-in + UPGO path
    must climb against random play — 'compiles and loss goes down' is not
    the bar for the imperfect-information flagship."""
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "Geister"},
        "train_args": {
            "observation": True,
            "batch_size": 16,
            "forward_steps": 8,
            "burn_in_steps": 4,
            "policy_target": "UPGO",
            "value_target": "UPGO",
            "minimum_episodes": 40,
            "update_episodes": 40,
            "maximum_episodes": 1500,
            "epochs": 25,
            "num_batchers": 1,
            "eval_rate": 0.3,
            "worker": {"num_parallel": 6},
            "eval": {"opponent": ["random"]},
        },
    })
    Learner(args).run()

    win = _win_curve()
    assert len(win) >= 15, f"only {len(win)} eval epochs recorded"
    early = float(np.mean(win[:5]))
    late = float(np.mean(win[-8:]))
    assert late > early, f"no climb vs random: {early:.3f} -> {late:.3f}"
    assert late >= 0.55, f"final win rate vs random {late:.3f} (early {early:.3f})"
