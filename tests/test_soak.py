"""Learning soaks on the flagship envs (run explicitly: pytest -m soak).

The reference's empirical bar is "win rate climbs over training"
(README.md:94-103); round 2 proved it end-to-end for TicTacToe only
(tests/test_runtime.py::test_training_learns_tictactoe).  These soaks
extend the same bar to the two flagship paths the framework exists for:

* HungryGeese (the north-star competition env, README.md:116) trained
  ENTIRELY by streaming on-device self-play, evaluated against the greedy
  rule-based opponent (envs/hungry_geese.py rule_based_action — the
  reference's kaggle/hungry_geese.py:60-66 food-greedy baseline);
* Geister (imperfect-information, README.md:117 family) through the DRC
  ConvLSTM recurrent path with burn-in + UPGO, evaluated against random.

Geister asserts the per-epoch win curve climbs plus a floor; HungryGeese
(whose per-epoch evals starve on the 1-core CI host) asserts a decisive
offline evaluation — trained vs untrained net, matched 240-game evals
against rule-based seats — plus a floor.  Full curves are left in
metrics.jsonl for inspection.
"""

import json

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime.learner import Learner


def _win_curve(path="metrics.jsonl", key="total"):
    win = []
    for line in open(path):
        w = json.loads(line).get("win_rate", {}).get(key)
        if w is not None:
            win.append(w)
    return win


def _eval_vs_rulebase(env_args, agent0, num_games: int, num_workers: int = 4):
    """(win points, mean outcome) vs 3 greedy rule-based seats — the shared
    margin-calibrated aggregation (runtime/evaluation.py:eval_vs_baseline)."""
    from handyrl_tpu.runtime.evaluation import eval_vs_baseline

    return eval_vs_baseline(env_args, agent0, "rulebase", num_games, num_workers)


@pytest.mark.soak
@pytest.mark.slow  # belt and braces: CI's `-m "not slow"` overrides addopts
def test_geese_device_selfplay_beats_rulebase(tmp_path, monkeypatch):
    """GeeseNet trained ONLY by on-device streaming self-play must beat the
    SAME net untrained against the greedy rule-based agent (3 opponent
    seats), by a decisive offline evaluation after training — per-epoch
    host evals starve on a 1-core CI host (1-2 games/epoch of pure noise,
    round-3 probe run), so the learning claim rests on a big matched
    eval instead; the noisy per-epoch rulebase curve is still recorded in
    metrics.jsonl for inspection.

    The asserted signal is MEAN OUTCOME (rank ladder {-1,-1/3,+1/3,+1}) —
    a first 25-epoch/~150-update probe run measured win points flat at
    0.525 -> 0.512, i.e. the top-half boundary is too coarse and ~150
    updates propagate the terminal outcome only ~10 steps back at
    lambda 0.7 (target influence decays lambda^k from the end while the
    value net is cold).  That probe also exposed a near-deterministic
    policy at init (entropy 0.004 of ln4; fixed by zero-init output heads
    in models/nets.py).  A second probe (fixed init, lambda 0.95, ~250
    updates) measured mean outcome -0.136 -> -0.224: at the parity lr
    (3e-8 x data-count EMA ~= 4e-5 here) 250 updates barely tilt the
    logits, and greedy argmax of a near-zero policy is a degenerate
    first-legal-action straight-liner.  The schedule assumes GPU-scale
    update counts, so this soak runs it at lr_scale 8 with a 2.5x longer
    epoch budget.  Margin calibration: per-game outcome std <= ~0.75, so
    each 240-game mean has se <= 0.048, the matched difference se <=
    0.068, and the +0.12 margin holds the no-learning false-pass rate
    under ~4%.  The wp floor asserts the headline: the trained net
    finishes top-half more often than not."""
    from handyrl_tpu.runtime.evaluation import load_model_agent

    monkeypatch.chdir(tmp_path)
    cfg = {
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 32,
            "forward_steps": 16,
            "lambda": 0.95,
            "lr_scale": 8.0,
            "minimum_episodes": 100,
            "update_episodes": 150,
            "maximum_episodes": 8000,
            "epochs": 250,
            "num_batchers": 1,
            # Host workers are eval-only under device_replay; the single
            # worker plays rule-based eval games continuously, but its
            # per-epoch curve is sparse/lagged on this host — the learning
            # claim rests on the big matched offline eval below.
            "eval_rate": 0.0,
            # 16 lanes, not more: the epoch cadence is episode-counted, so
            # the update budget per epoch is set by how LONG an epoch's
            # episodes take to produce — 64 lanes filled the 150-episode
            # budget in one rollout call and the run measured ~1 update per
            # epoch (92 updates by epoch 87); 16 lanes spread it over ~6
            # calls the trainer interleaves with, and fused_steps doubles
            # the updates per dispatch
            "device_rollout_games": 16,
            # the learning proof doubles as the device-resident-replay
            # proof: data never leaves the device between self-play and
            # SGD (runtime/device_replay.py); host workers are eval-only
            # in this mode by design
            "device_replay": True,
            "fused_steps": 2,
            # single-device mesh: the conftest's 8 VIRTUAL cpu devices share
            # one physical core, so sharded programs only add collective
            # overhead here — and the fused scan on a multi-device CPU mesh
            # is pathologically slow (see Trainer's fused clamp).  The
            # sharded device-replay path is covered by the parity suite and
            # the multichip dry-run; the soak's job is learning evidence.
            "mesh": {"dp": 1},
            "worker": {"num_parallel": 1},
            "eval": {"opponent": ["rulebase"]},
        },
    }
    args = normalize_args(cfg)
    Learner(args).run()

    env_args = args["env_args"]
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.agents import Agent

    env = make_env(env_args)
    module = env.net()
    untrained = Agent(InferenceModel(module, init_variables(module, env)))
    trained = load_model_agent("models/latest.ckpt", env, module)

    wp_u, out_u = _eval_vs_rulebase(env_args, untrained, 240)
    wp_t, out_t = _eval_vs_rulebase(env_args, trained, 240)
    print(
        f"vs rulebase: win points {wp_u:.3f} -> {wp_t:.3f}, "
        f"mean outcome {out_u:.3f} -> {out_t:.3f}"
    )
    assert out_t > out_u + 0.12, (
        f"no learning signal vs rulebase: mean outcome {out_u:.3f} -> {out_t:.3f} "
        f"(win points {wp_u:.3f} -> {wp_t:.3f})"
    )
    assert wp_t >= 0.5, (
        f"trained net does not finish top-half more often than not: wp {wp_t:.3f}"
    )


@pytest.mark.soak
@pytest.mark.slow
def test_geister_drc_beats_random(tmp_path, monkeypatch):
    """GeisterNet (DRC ConvLSTM) through the recurrent burn-in + UPGO path
    must climb against random play — 'compiles and loss goes down' is not
    the bar for the imperfect-information flagship.

    Sizing (1-core CI host, round-3 probe run): a DRC update at batch 16 x
    window 12 takes ~60 s wall under worker contention, i.e. a 25-epoch /
    update_episodes-40 run ends after ~25 updates — no budget to learn.
    This config halves the batch (~30 s/update), runs the lr schedule at
    lr_scale 16 (see docs/parameters.md), and sizes epochs so the run
    lasts ~2.5 h (~300 updates): epochs x update_episodes / ~1.3
    episodes/s of worker throughput.  Win rates are averaged over epoch
    windows because per-epoch eval games are few (~10-40)."""
    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "Geister"},
        "train_args": {
            "observation": True,
            "batch_size": 8,
            "forward_steps": 8,
            "burn_in_steps": 4,
            "policy_target": "UPGO",
            "value_target": "UPGO",
            "lr_scale": 16.0,
            # the default entropy bonus (1e-1) pins a small-update-budget
            # run at the uniform policy: a probe run measured entropy
            # RISING 2.45 -> 2.59 (= ln 13, uniform over legal moves) over
            # 900 updates while value loss fell 0.23 -> 0.05 — self-play
            # advantages at this scale are too small to outweigh the
            # bonus, so the policy can never commit to exploiting its
            # value knowledge.  1e-2 lets it leave uniform.
            "entropy_regularization": 1.0e-2,
            "minimum_episodes": 40,
            "update_episodes": 80,
            "maximum_episodes": 3000,
            "epochs": 140,
            "num_batchers": 1,
            "eval_rate": 0.3,
            # single-device mesh: on the 1-core CI host the 8 virtual
            # devices only add collective overhead, and single-device CPU
            # unlocks the unrolled RNN train scan (~12x faster DRC updates
            # — parallel/train_step.py unroll note); sharding coverage
            # lives in the parity suite + dry-run, not here
            "mesh": {"dp": 1},
            "worker": {"num_parallel": 4},
            "eval": {"opponent": ["random"]},
        },
    })
    Learner(args).run()

    win = _win_curve()
    assert len(win) >= 40, f"only {len(win)} eval epochs recorded"
    early = float(np.mean(win[:20]))
    late = float(np.mean(win[-20:]))
    # margins sized from the recorded passes (round 3: 0.569 -> 0.649,
    # peak 0.902; on-chip run: +0.35; round 4: a fast-start run reached
    # 0.8+ inside the early window).  A floor of 0.55 with any positive
    # climb let a substantially regressed DRC path still pass, so the bar
    # asks for a 0.60 late-window mean AND either a clear climb or a
    # decisively high late window — a fast learner must not fail merely
    # for having nothing left to climb.
    assert late >= 0.60, f"final win rate vs random {late:.3f} (early {early:.3f})"
    assert (late >= 0.75 and late >= early - 0.05) or late > early + 0.05, (
        f"not climbing (or collapsed from a high start) vs random: "
        f"{early:.3f} -> {late:.3f}"
    )
