"""Runtime sanitizer plane (handyrl_tpu/utils/sanitizers.py).

Units pin the instrumentation itself (counting, named-site attribution,
the dispatch-lock allowlist, clean restore).  The two window tests arm
the sanitizers around REAL training surfaces:

* the ``batch_pipeline: device`` path records ZERO blocking host syncs
  across a pipeline window (batch() + train dispatches) — the PR 6
  invariant, now enforced instead of remembered — with a deliberate
  violation asserting the loud named-site report;
* a warm epoch of the real ``Learner`` streaming hot loop
  (device_replay) records ZERO XLA recompiles — one stray shape change
  silently turns a 3 ms update into a 30 s stall.

CI runs the full ``sanitizer`` marker on the 4-virtual-device CPU mesh;
the Learner window also carries ``slow`` to stay off the tier-1 budget.
"""

import threading
import time

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import init_variables
from handyrl_tpu.parallel import TrainContext, make_mesh
from handyrl_tpu.parallel.mesh import dispatch_serialized
from handyrl_tpu.utils.sanitizers import HostSyncSanitizer, RecompileSentinel

pytestmark = pytest.mark.sanitizer


# -- RecompileSentinel units --------------------------------------------------


def test_recompile_sentinel_quiet_on_warm_path():
    f = jax.jit(lambda x: x * 2 + 1)
    f(np.ones(7, np.float32))  # warm
    with RecompileSentinel() as sentinel:
        for _ in range(3):
            f(np.ones(7, np.float32))
    sentinel.assert_no_recompiles("warm jit loop")
    assert sentinel.count == 0


def test_recompile_sentinel_counts_and_names_the_site():
    f = jax.jit(lambda x: x * 3)
    f(np.ones(4, np.float32))
    with RecompileSentinel() as sentinel:
        f(np.ones(11, np.float32))  # new shape -> real backend compile
    assert sentinel.count >= 1
    report = sentinel.report()
    assert "test_sanitizers.py" in report, report
    with pytest.raises(AssertionError, match="compilation"):
        sentinel.assert_no_recompiles("shape drift")
    # disarmed outside the window
    f(np.ones(13, np.float32))
    assert sentinel.count == len(sentinel.events)


# -- HostSyncSanitizer units --------------------------------------------------


def test_host_sync_sanitizer_clean_on_async_dispatch():
    f = jax.jit(lambda x: x + 1)
    x = f(np.ones(3, np.float32))
    jax.block_until_ready(x)
    with HostSyncSanitizer() as sync:
        y = f(x)
        y = f(y)
    sync.assert_clean("pure async dispatch")
    jax.block_until_ready(y)  # outside the window: not recorded
    assert sync.count == 0


def test_host_sync_sanitizer_names_every_entry_point():
    x = jax.jit(lambda v: v * 2)(np.ones(3, np.float32))
    with HostSyncSanitizer() as sync:
        jax.device_get(x)
        jax.block_until_ready(x)
        float(x[0])          # ArrayImpl to-host conversion
    kinds = {e.kind for e in sync.events}
    assert "device_get" in kinds and "block_until_ready" in kinds, sync.report()
    assert "to_host" in kinds, sync.report()
    report = sync.report()
    assert "test_sanitizers.py" in report, report
    with pytest.raises(AssertionError, match="blocking host sync"):
        sync.assert_clean()
    # every patch restored
    assert jax.device_get.__module__.startswith("jax"), jax.device_get


def test_host_sync_sanitizer_allows_dispatch_lock_block():
    """The CPU backend's block INSIDE dispatch_serialized is the
    documented lock behavior (parallel/mesh.py), not a hot-loop leak —
    allowlisted by default, but still visible in the report."""
    f = jax.jit(lambda v: v + 5)
    x = f(np.ones(3, np.float32))
    with HostSyncSanitizer() as sync:
        dispatch_serialized(lambda: f(x), jax.devices()[:1])
    sync.assert_clean("locked dispatch")
    if jax.default_backend() == "cpu":
        assert sync.allowed_events, sync.report()
        assert "allowed" in sync.report()


# -- the batch_pipeline: device window ---------------------------------------


def _device_pipeline(dp=2):
    """A live DeviceBatchPipeline + TrainContext over host-born HungryGeese
    episodes (mirrors tests/test_device_stage.py's end-to-end surface)."""
    import random

    from handyrl_tpu.models.inference import InferenceModel
    from handyrl_tpu.runtime.device_batch import DeviceBatchPipeline
    from handyrl_tpu.runtime.generation import Generator
    from handyrl_tpu.runtime.replay import EpisodeStore

    random.seed(11)
    cfg = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 4,
            "forward_steps": 8,
            "batch_pipeline": "device",
            "device_stage_lanes": dp,
            "device_stage_chunk": 4,
            "device_stage_slots": 256,
            "mesh": {"dp": dp},
        },
    })
    targs = dict(cfg["train_args"])
    targs["env"] = cfg["env_args"]
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=11))
    gen = Generator(env, targs)
    gen_args = {"player": env.players(),
                "model_id": {p: 1 for p in env.players()}}
    eps = []
    while len(eps) < 8:
        ep = gen.generate({p: model for p in env.players()}, gen_args)
        if ep is not None:
            eps.append(ep)
    mesh = make_mesh({"dp": dp})
    ctx = TrainContext(module, targs, mesh)
    store = EpisodeStore(100)
    stop = threading.Event()
    pipe = DeviceBatchPipeline(targs, store, ctx, stop)
    store.extend(eps)
    pipe.start()
    state = ctx.init_state(init_variables(module, env, seed=11)["params"])
    return pipe, ctx, state, stop


def test_device_pipeline_window_is_host_sync_free():
    """PR 6's invariant, armed: across a pipeline window on the
    batch_pipeline: device path — batch() sampling dispatches plus real
    train-step dispatches — the ONLY blocking transfers are the
    allowlisted dispatch-lock blocks (CPU backend).  A deliberate
    violation inside the same window produces the loud named-site
    report."""
    pipe, ctx, state, stop = _device_pipeline(dp=2)
    try:
        # warm everything outside the window: first batch (ring init +
        # sampler jit) and first train dispatch (train-step jit)
        batch = pipe.batch()
        assert batch is not None
        state, _ = ctx.train_step(state, batch, 1e-5)

        with HostSyncSanitizer() as sync, RecompileSentinel() as sentinel:
            for _ in range(4):
                batch = pipe.batch()
                assert batch is not None
                state, metrics = ctx.train_step(state, batch, 1e-5)
        sync.assert_clean("batch_pipeline: device window")
        sentinel.assert_no_recompiles("batch_pipeline: device window")

        # negative: a stray host conversion in the same window is caught
        # and NAMED (file:line of this test, not a vague count)
        with HostSyncSanitizer() as sync:
            batch = pipe.batch()
            np.asarray(jax.device_get(batch["action"]))  # deliberate leak
        assert sync.events, sync.report()
        report = sync.report()
        assert "test_sanitizers.py" in report, report
        with pytest.raises(AssertionError, match="test_sanitizers.py"):
            sync.assert_clean("deliberate violation")
    finally:
        stop.set()
        pipe.stop()


# -- the Learner streaming hot loop ------------------------------------------


@pytest.mark.slow
def test_learner_streaming_epoch_has_zero_recompiles(tmp_path, monkeypatch):
    """Acceptance gate: a POST-WARM-UP epoch of the real Learner
    streaming hot loop (device_replay on the multi-device CPU mesh)
    triggers zero XLA compilations — rollout dispatches, ring ingest,
    fused sample+train, param publish and the epoch boundary all hit
    warm executables.  The sentinel window is aligned to model-epoch
    boundaries (epoch 2 -> 3), after two full epochs warmed every path
    including the eval workers' inference buckets."""
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 8,
            "forward_steps": 8,
            "minimum_episodes": 10,
            "update_episodes": 30,
            "maximum_episodes": 1000,
            "epochs": 4,
            "eval_rate": 0.0,
            "device_rollout_games": 8,
            "device_replay": True,
            "device_replay_slots": 256,
            "device_replay_k_steps": 16,
            "mesh": {"dp": 4},
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    thread = threading.Thread(target=learner.run, daemon=True)
    thread.start()

    def wait_for_epoch(n, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if learner.model_epoch >= n:
                return True
            if not thread.is_alive():
                return learner.model_epoch >= n
            time.sleep(0.2)
        return False

    assert wait_for_epoch(2, 600), (
        f"warm-up never reached epoch 2 (at {learner.model_epoch})"
    )
    with RecompileSentinel() as sentinel:
        assert wait_for_epoch(3, 600), (
            f"window never reached epoch 3 (at {learner.model_epoch})"
        )
    thread.join(timeout=600)
    sentinel.assert_no_recompiles("streaming hot loop epoch 2->3")
    assert learner.trainer.steps > 0
